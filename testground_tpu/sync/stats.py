"""Sync-plane stats: the coordination plane's observability tier.

The sim side has six telemetry tiers (docs/OBSERVABILITY.md); until this
module the sync plane — a standalone network deployment unit since
``tg sync-service`` — exposed three occupancy integers. This is the
shared accounting core behind the wire-versioned ``sync_stats`` **v2**
op (docs/INSTANCE_PROTOCOL.md §4.2):

- **per-op counters** for every protocol op (``SYNC_OPS``), counted at
  dispatch so a ``sync_stats`` reply includes itself deterministically;
- **service-time log2 histograms** per op (µs bins; for ``barrier`` /
  ``signal_and_wait`` the recorded time is the full fan-in wait — that
  IS the latency a client observes);
- **barrier lifecycle timing**: per-waiter parked/released/timed-out/
  canceled counters plus per-episode armed→release wall time keyed by
  the fan-in target's pow2 bucket (bounded label space);
- **pubsub depth**: published entries, live topic/entry gauges, topic
  depth + subscriber high-water marks;
- **connection churn**: accepts/closes/idle-evictions + concurrent
  high-water mark;
- **idempotency-dedup hits** (signal/publish token replays).

Everything is a python int under one lock — the instrumentation is
always-on and cheap (the fan-in bench's instrumented-vs-uninstrumented
A/B is the receipt, PERF.md "Sync fan-in"); the native C++ server
(``testground_tpu/native/syncsvc.cc``) mirrors the **counter-level**
fields of this schema field-for-field (pinned by
``tests/test_sync_stats.py``), while the histogram/episode richness is
python-server-only.

Also hosted here because every consumer is sync-plane-shaped and must
stay import-light (the standalone service should not drag jax in):

- :func:`fetch_sync_stats` — one-shot raw-socket ``sync_stats`` query
  (the CLI verb, the heartbeat, and the metrics exporter all use it, so
  it works identically against either backend, local or remote);
- :func:`heartbeat_line` / :func:`run_stats_heartbeat` — the
  ``tg sync-service --stats-interval`` one-line log heartbeat;
- :class:`SyncMetricsExporter` — the ``--metrics-port`` Prometheus
  endpoint (rendering via ``testground_tpu/metrics/prometheus.py``).
"""

from __future__ import annotations

import json
import socket
import threading
import time

__all__ = [
    "SYNC_OPS",
    "TIME_BINS",
    "PARITY_FIELDS",
    "SyncStats",
    "time_bin",
    "bin_edge_us",
    "hist_quantile_us",
    "target_bucket",
    "fetch_sync_stats",
    "heartbeat_line",
    "run_stats_heartbeat",
    "SyncMetricsExporter",
]

# every wire op, in protocol-doc order (docs/INSTANCE_PROTOCOL.md §4.2)
SYNC_OPS = (
    "signal_entry",
    "counter",
    "barrier",
    "signal_and_wait",
    "publish",
    "subscribe",
    "ping",
    "hello",
    "bye",
    "sync_stats",
)

# log2 service-time bins: bin i covers [2^i, 2^(i+1)) µs, bin 0 also
# catches sub-µs, the last bin is open — 20 bins span 1µs … ≥0.5s
TIME_BINS = 20

# barrier fan-in targets bucket to their pow2 ceiling, capped so the
# label space stays bounded however big a cohort gets
MAX_TARGET_BUCKET = 1 << 20

# the counter-level v2 fields BOTH backends must expose with identical
# semantics — the wire-parity contract tests/test_sync_stats.py pins
# (histograms and barrier episodes are python-server-only richness)
PARITY_FIELDS = {
    "ops": list(SYNC_OPS),
    "conn": ["accepts", "closes", "evictions"],
    "barriers": ["parked", "released", "timed_out", "canceled"],
    "pubsub": ["published", "topics", "entries", "depth_hwm"],
    "dedup": ["signal_hits", "publish_hits"],
}


def time_bin(us: float) -> int:
    """Histogram bin for a service time in µs (log2 bins, clamped)."""
    n = int(us)
    if n < 1:
        return 0
    return min(TIME_BINS - 1, n.bit_length() - 1)


def bin_edge_us(i: int) -> float:
    """Upper edge (exclusive) of bin ``i`` in µs; inf for the open bin."""
    if i >= TIME_BINS - 1:
        return float("inf")
    return float(1 << (i + 1))


def hist_quantile_us(bins: list, q: float) -> float:
    """Interpolated quantile (µs) from log2 bins; 0.0 when empty. The
    last (open) bin answers with its lower edge — a clamped floor, the
    same open-bin rule the delivery-latency histograms use."""
    total = sum(bins)
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    for i, c in enumerate(bins):
        if c <= 0:
            continue
        lo = float(1 << i) if i else 0.0
        hi = bin_edge_us(i)
        if cum + c >= rank:
            if hi == float("inf"):
                return lo
            frac = (rank - cum) / c
            return lo + frac * (hi - lo)
        cum += c
    return float(1 << (TIME_BINS - 1))


def target_bucket(target: int) -> int:
    """Pow2 ceiling of a barrier fan-in target (bounded label space)."""
    t = max(1, int(target))
    b = 1 << (t - 1).bit_length()
    return min(b, MAX_TARGET_BUCKET)


# maximum concurrently-armed (state, target) episodes remembered; a
# barrier that never releases must not leak its arm record forever
_MAX_ARMED = 4096

# distinct task ids remembered by the hello-attribution counters; a
# long-lived service must not grow the map with every run that ever
# connected (overflow aggregates under the "" key so Σ still conserves)
_MAX_TASKS = 64


class SyncStats:
    """Thread-safe sync-plane accounting (one lock, python-int adds).

    The server wires the hooks; :meth:`snapshot` renders the v2 blocks.
    ``clock`` is injectable for deterministic timing tests.
    """

    def __init__(self, clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._start = clock()
        self.ops: dict[str, int] = {op: 0 for op in SYNC_OPS}
        self._op_bins: dict[str, list[int]] = {}
        self._op_total_us: dict[str, int] = {}
        self._op_max_us: dict[str, int] = {}
        # connection churn
        self.accepts = 0
        self.closes = 0
        self.evictions = 0
        self.conns_hwm = 0
        # occupancy high-water (waiters/subs gauges live server-side)
        self.waiters_hwm = 0
        self.subs_hwm = 0
        # barrier lifecycle (per-waiter counters + per-episode timing)
        self.bar_parked = 0
        self.bar_released = 0
        self.bar_timed_out = 0
        self.bar_canceled = 0
        self.episodes_armed = 0
        self.episodes_released = 0
        self._armed: dict[tuple[str, int], float] = {}
        # {pow2 target bucket: [count, total_ms, max_ms]}
        self._by_target: dict[int, list] = {}
        # pubsub
        self.published = 0
        self.depth_hwm = 0
        # idempotency dedup
        self.dedup_signal = 0
        self.dedup_publish = 0
        # hello attribution: ops per task (run) id, bounded — overflow
        # aggregates under "" so totals still conserve
        self._task_ops: dict[str, int] = {}

    # ------------------------------------------------------------- ops

    def count_op(self, op: str) -> None:
        if op not in self.ops:
            return
        with self._lock:
            self.ops[op] += 1

    def _record_time_locked(self, op: str, us: float) -> None:
        """THE one histogram write (lock held): clamp, bin, total, max."""
        n = max(0, int(us))
        bins = self._op_bins.get(op)
        if bins is None:
            bins = self._op_bins[op] = [0] * TIME_BINS
            self._op_total_us[op] = 0
            self._op_max_us[op] = 0
        bins[time_bin(n)] += 1
        self._op_total_us[op] += n
        if n > self._op_max_us[op]:
            self._op_max_us[op] = n

    def op_done(self, op: str, us: float) -> None:
        """Count + service-time in ONE lock acquisition — the hot path
        for inline-answered ops (the server calls this just before the
        reply hits the socket, so a reply a client has seen is always
        already counted)."""
        if op not in self.ops:
            return
        with self._lock:
            self.ops[op] += 1
            self._record_time_locked(op, us)

    def time_op(self, op: str, us: float) -> None:
        if op not in self.ops:
            return
        with self._lock:
            self._record_time_locked(op, us)

    # ------------------------------------------------------ batched hooks
    # The event-loop servers drain MANY ready ops per wake; these flush
    # a whole drain's accounting under ONE lock acquisition instead of
    # one per op (the hot-path half of the <5% instrumentation budget).

    def op_done_batch(self, items: list) -> None:
        """Count + time a batch of completed inline ops in one lock
        acquisition; ``items`` is ``[(op, us), ...]``."""
        if not items:
            return
        with self._lock:
            for op, us in items:
                if op not in self.ops:
                    continue
                self.ops[op] += 1
                self._record_time_locked(op, us)

    def time_op_batch(self, items: list) -> None:
        """Service-time-only batch (ops already counted at dispatch —
        the parked barrier/signal_and_wait path); ``[(op, us), ...]``."""
        if not items:
            return
        with self._lock:
            for op, us in items:
                if op in self.ops:
                    self._record_time_locked(op, us)

    def task_ops_batch(self, items: dict) -> None:
        """Fold one drain's per-task op counts (``{task: n}`` — hello
        attribution, docs/CROSSHOST.md) under one lock acquisition. The
        map is bounded: once ``_MAX_TASKS`` distinct ids are tracked,
        new ids aggregate under ``""`` so Σ over tasks still equals the
        attributed-op total."""
        if not items:
            return
        with self._lock:
            for task, n in items.items():
                key = task
                if key not in self._task_ops and len(
                    self._task_ops
                ) >= _MAX_TASKS:
                    key = ""
                self._task_ops[key] = self._task_ops.get(key, 0) + int(n)

    # ----------------------------------------------------- connections

    def conn_open(self) -> None:
        with self._lock:
            self.accepts += 1
            live = self.accepts - self.closes
            if live > self.conns_hwm:
                self.conns_hwm = live

    def conn_close(self) -> None:
        with self._lock:
            self.closes += 1

    def conn_evicted(self) -> None:
        with self._lock:
            self.evictions += 1

    def note_occupancy(self, waiters: int, subs: int) -> None:
        with self._lock:
            if waiters > self.waiters_hwm:
                self.waiters_hwm = waiters
            if subs > self.subs_hwm:
                self.subs_hwm = subs

    # --------------------------------------------------------- barriers

    def barrier_parked(self, state: str, target: int) -> None:
        with self._lock:
            self.bar_parked += 1
            key = (state, int(target))
            if key not in self._armed and len(self._armed) < _MAX_ARMED:
                self._armed[key] = self._clock()
                self.episodes_armed += 1

    def _close_episode_locked(
        self, state: str, target: int, released: bool
    ) -> None:
        """ANY terminal outcome closes the episode's arm record (lock
        held) — a timed-out/canceled episode must not pin (state,
        target) armed forever (it would block re-arming AND leak toward
        _MAX_ARMED); only a release records armed→release timing."""
        t0 = self._armed.pop((state, int(target)), None)
        if not released or t0 is None:
            return  # non-release outcome, or a later waiter of an
            # already-closed episode
        wall_ms = max(0.0, (self._clock() - t0) * 1e3)
        self.episodes_released += 1
        rec = self._by_target.setdefault(target_bucket(target), [0, 0.0, 0.0])
        rec[0] += 1
        rec[1] += wall_ms
        if wall_ms > rec[2]:
            rec[2] = wall_ms

    def _barrier_done(self, counter: str, state: str, target: int) -> None:
        with self._lock:
            setattr(self, counter, getattr(self, counter) + 1)
            self._close_episode_locked(
                state, target, counter == "bar_released"
            )

    def barrier_released(self, state: str, target: int) -> None:
        self._barrier_done("bar_released", state, target)

    def barrier_released_batch(self, state: str, target: int, n: int) -> None:
        """Coalesced barrier release: ``n`` waiters of one (state,
        target) episode released in one fan-out pass — one lock, one
        episode close (the wall recorded once, as the first releaser
        would have)."""
        if n <= 0:
            return
        with self._lock:
            self.bar_released += n
            self._close_episode_locked(state, target, True)

    def barrier_timed_out(self, state: str, target: int) -> None:
        self._barrier_done("bar_timed_out", state, target)

    def barrier_canceled(self, state: str, target: int) -> None:
        self._barrier_done("bar_canceled", state, target)

    # ----------------------------------------------------------- pubsub

    def pubsub_published(self, depth: int) -> None:
        with self._lock:
            self.published += 1
            if depth > self.depth_hwm:
                self.depth_hwm = depth

    def dedup_hit(self, kind: str) -> None:
        with self._lock:
            if kind == "signal":
                self.dedup_signal += 1
            else:
                self.dedup_publish += 1

    # --------------------------------------------------------- snapshot

    def snapshot(self, topics: int = 0, entries: int = 0) -> dict:
        """The v2 extension blocks (the server adds the v1 occupancy
        fields + ``boot`` around this). ``topics``/``entries`` are live
        pubsub gauges the caller reads from the service."""
        with self._lock:
            op_time = {
                op: {
                    "count": sum(bins),
                    "total_us": self._op_total_us[op],
                    "max_us": self._op_max_us[op],
                    "bins": list(bins),
                }
                for op, bins in self._op_bins.items()
            }
            return {
                "v": 2,
                "uptime_secs": round(self._clock() - self._start, 3),
                "ops": dict(self.ops),
                "conn": {
                    "accepts": self.accepts,
                    "closes": self.closes,
                    "evictions": self.evictions,
                    "hwm": self.conns_hwm,
                },
                "barriers": {
                    "parked": self.bar_parked,
                    "released": self.bar_released,
                    "timed_out": self.bar_timed_out,
                    "canceled": self.bar_canceled,
                    "episodes": {
                        "armed": self.episodes_armed,
                        "released": self.episodes_released,
                        "by_target": {
                            str(b): {
                                "count": rec[0],
                                "total_ms": round(rec[1], 3),
                                "max_ms": round(rec[2], 3),
                            }
                            for b, rec in sorted(self._by_target.items())
                        },
                    },
                },
                "pubsub": {
                    "published": self.published,
                    "topics": int(topics),
                    "entries": int(entries),
                    "depth_hwm": self.depth_hwm,
                    "subs_hwm": self.subs_hwm,
                },
                "dedup": {
                    "signal_hits": self.dedup_signal,
                    "publish_hits": self.dedup_publish,
                },
                "hwm": {
                    "waiters": self.waiters_hwm,
                    "subs": self.subs_hwm,
                },
                "op_time_us": op_time,
                # additive block (NOT in PARITY_FIELDS): per-task op
                # attribution from hello's `task` field — old clients
                # never send it, the native server never renders it, and
                # readers treat an absent block as "no attribution"
                "tasks": {
                    t: n for t, n in sorted(self._task_ops.items())
                },
            }


# ------------------------------------------------------------- one-shot IO


def fetch_sync_stats(
    host: str, port: int, timeout: float = 5.0
) -> dict:
    """One-shot ``sync_stats`` query over a fresh connection — works
    against either backend, v1 or v2 (the version negotiation rule:
    a reply carrying ``"v": 2`` has the stats blocks; one without is an
    old server and only the occupancy integers exist). Raises OSError-
    family errors when the service is unreachable."""
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(b'{"id": 1, "op": "sync_stats"}\n')
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError(
                    f"sync service {host}:{port} closed during sync_stats"
                )
            buf += chunk
    msg = json.loads(buf.split(b"\n", 1)[0])
    return {k: v for k, v in msg.items() if k != "id"}


def heartbeat_line(prev: dict | None, cur: dict, dt: float) -> str:
    """One log line a detached ``tg sync-service`` is debuggable from:
    occupancy + ops/s over the interval (+ cumulative eviction count)."""
    ops_now = sum((cur.get("ops") or {}).values())
    ops_prev = sum(((prev or {}).get("ops") or {}).values())
    rate = (ops_now - ops_prev) / dt if dt > 0 else 0.0
    bar = cur.get("barriers") or {}
    conn = cur.get("conn") or {}
    return (
        f"sync-stats: conns={cur.get('conns', '?')} "
        f"waiters={cur.get('waiters', '?')} subs={cur.get('subs', '?')} "
        f"ops/s={rate:.1f} ops_total={ops_now} "
        f"barriers={bar.get('released', '?')}/{bar.get('parked', '?')} "
        f"evictions={conn.get('evictions', '?')}"
    )


def run_stats_heartbeat(
    address: tuple[str, int],
    interval: float,
    stop: threading.Event,
    out=None,
) -> None:
    """Loop body of the ``--stats-interval`` heartbeat thread: every
    ``interval`` seconds query the service and print one
    :func:`heartbeat_line` (to stderr by default). Unreachability is a
    line too, not an exception — the service may be shutting down."""
    import sys

    out = out if out is not None else sys.stderr
    prev: dict | None = None
    last = time.monotonic()
    while not stop.wait(interval):
        now = time.monotonic()
        try:
            cur = fetch_sync_stats(address[0], address[1], timeout=5.0)
        except (OSError, ValueError) as e:
            print(f"sync-stats: unreachable ({e})", file=out, flush=True)
            continue
        print(heartbeat_line(prev, cur, now - last), file=out, flush=True)
        prev, last = cur, now


# ----------------------------------------------------- Prometheus exporter


class SyncMetricsExporter:
    """``tg sync-service --metrics-port``: a tiny HTTP endpoint serving
    the ``tg_sync_*`` Prometheus family at ``GET /metrics``.

    Backend-agnostic by construction: every scrape issues a one-shot
    ``sync_stats`` against the service address (python or native, local
    or remote), so the exporter never reaches into server internals and
    a scrape can never block the event loop."""

    def __init__(
        self,
        service_address: tuple[str, int],
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        svc_addr = service_address

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler contract
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                from testground_tpu.metrics.prometheus import (
                    CONTENT_TYPE,
                    render_sync_prometheus,
                )

                try:
                    stats = fetch_sync_stats(*svc_addr, timeout=5.0)
                except (OSError, ValueError) as e:
                    self.send_error(503, explain=f"sync service: {e}")
                    return
                body = render_sync_prometheus(stats).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet: scrapes are periodic
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "SyncMetricsExporter":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            daemon=True,
            name="tg-sync-metrics",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)
