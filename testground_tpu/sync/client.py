"""Blocking sync-service client used by the SDK inside instances.

The analog of sdk-go's ``sync.Client`` (``SignalEntry``, ``SignalAndWait``,
``Barrier``, ``Publish``, ``Subscribe``, ``PublishSubscribe`` — usage:
``plans/network/pingpong.go:54,180,225``). Speaks the JSON-lines protocol of
:mod:`testground_tpu.sync.server`.
"""

from __future__ import annotations

import json
import queue
import socket
import threading
from typing import Any, Iterator

__all__ = ["SyncClient"]


class SyncClient:
    def __init__(self, host: str, port: int, namespace: str = ""):
        """``namespace`` scopes all states/topics, normally
        ``run:<run_id>:`` (the reference scopes keys by run)."""
        self._ns = namespace
        self._sock = socket.create_connection((host, port), timeout=30)
        self._sock.settimeout(None)
        self._wfile = self._sock.makefile("w", encoding="utf-8")
        self._rfile = self._sock.makefile("r", encoding="utf-8")
        self._wlock = threading.Lock()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._queues: dict[int, queue.Queue] = {}
        self._closed = threading.Event()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="tg-sync-client"
        )
        self._reader.start()

    # ------------------------------------------------------------- plumbing

    def _read_loop(self) -> None:
        try:
            for line in self._rfile:
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                q = self._queues.get(msg.get("id"))
                if q is not None:
                    q.put(msg)
        except (OSError, ValueError):
            pass
        finally:
            self._closed.set()
            for q in list(self._queues.values()):
                q.put({"error": "connection closed"})

    def _call(self, op: str, stream: bool = False, **args: Any) -> queue.Queue:
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        q: queue.Queue = queue.Queue()
        self._queues[rid] = q
        req = {"id": rid, "op": op, **args}
        with self._wlock:
            self._wfile.write(json.dumps(req) + "\n")
            self._wfile.flush()
        return q

    def _call_one(self, op: str, timeout: float | None = None, **args: Any) -> dict:
        q = self._call(op, **args)
        try:
            msg = q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"sync op {op} timed out") from None
        if "error" in msg:
            raise RuntimeError(f"sync op {op} failed: {msg['error']}")
        return msg

    def _key(self, name: str) -> str:
        return self._ns + name

    # ------------------------------------------------------------------ API

    def signal_entry(self, state: str) -> int:
        return self._call_one("signal_entry", state=self._key(state))["seq"]

    def counter(self, state: str) -> int:
        return self._call_one("counter", state=self._key(state))["count"]

    def barrier(self, state: str, target: int, timeout: float | None = None) -> None:
        self._call_one(
            "barrier", state=self._key(state), target=target, timeout=timeout
        )

    def signal_and_wait(
        self, state: str, target: int, timeout: float | None = None
    ) -> int:
        return self._call_one(
            "signal_and_wait", state=self._key(state), target=target, timeout=timeout
        )["seq"]

    def publish(self, topic: str, payload: Any) -> int:
        return self._call_one("publish", topic=self._key(topic), payload=payload)[
            "seq"
        ]

    def subscribe(self, topic: str, timeout: float | None = None) -> Iterator[Any]:
        """Yield every entry of the topic in order (all entries from the
        beginning, like the reference's Subscribe)."""
        q = self._call("subscribe", topic=self._key(topic))
        while True:
            try:
                msg = q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(f"subscribe {topic} timed out") from None
            if "error" in msg:
                return
            yield msg["entry"]

    def publish_subscribe(
        self, topic: str, payload: Any, timeout: float | None = None
    ) -> tuple[int, Iterator[Any]]:
        seq = self.publish(topic, payload)
        return seq, self.subscribe(topic, timeout=timeout)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
