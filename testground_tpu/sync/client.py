"""Blocking sync-service client used by the SDK inside instances.

The analog of sdk-go's ``sync.Client`` (``SignalEntry``, ``SignalAndWait``,
``Barrier``, ``Publish``, ``Subscribe``, ``PublishSubscribe`` — usage:
``plans/network/pingpong.go:54,180,225``). Speaks the JSON-lines protocol of
:mod:`testground_tpu.sync.server`.

Failure hardening (docs/CROSSHOST.md):

- **Bounded reconnect.** Initial connects AND mid-run drops retry with
  exponential backoff + jitter under a configurable attempt/deadline
  budget (:class:`SyncRetry`). When the budget is exhausted every
  blocked caller gets a typed :class:`SyncLostError` (address, attempt
  count) instead of hanging forever.
- **Resume semantics.** After a reconnect the client re-subscribes every
  live topic and discards the replayed prefix up to the last seq it
  delivered, re-arms in-flight barriers, and re-sends unacked mutations
  with their original idempotency token — the service deduplicates, so
  at-least-once wire delivery stays exactly-once in effect.
- **Restart detection.** Every connection handshake reads the server's
  boot id; a changed boot id means the service restarted and lost its
  coordination state, which surfaces as :class:`SyncLostError` rather
  than silently resuming against an empty world.
- **Heartbeats.** A background pinger keeps the connection visibly live
  (feeding the server's idle sweep) and detects half-open connections —
  a partitioned server that still has an ESTABLISHED socket — by pong
  timeout, forcing the drop/reconnect path.
"""

from __future__ import annotations

import json
import queue
import random
import socket
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Iterator

from .errors import SyncLostError

__all__ = ["SyncClient", "SyncRetry"]


@dataclass
class SyncRetry:
    """Connect/reconnect budget (threaded from runner config through
    ``RunParams`` — see ``sdk/runparams.py``)."""

    # per-attempt TCP connect + ping-handshake timeout (was a hardcoded
    # 30 s create_connection timeout)
    connect_timeout: float = 30.0
    # per-outage budget: give up after this many connection attempts...
    attempts: int = 8
    # ...or this much wall clock, whichever comes first
    deadline_secs: float = 60.0
    backoff_base: float = 0.1
    backoff_cap: float = 2.0
    # liveness pings (0 disables); also what keeps the server's idle
    # sweep from evicting a healthy-but-quiet instance
    heartbeat_secs: float = 5.0
    # missing-pong window before the connection is declared half-open;
    # 0 → max(2 * heartbeat_secs, 1.0)
    pong_timeout: float = 0.0

    def effective_pong_timeout(self) -> float:
        return self.pong_timeout or max(2.0 * self.heartbeat_secs, 1.0)


@dataclass
class _Pending:
    op: str
    args: dict
    q: queue.Queue


@dataclass
class _Sub:
    topic: str  # already namespaced
    q: queue.Queue
    delivered: int = 0  # last topic seq handed to the consumer


class SyncClient:
    def __init__(
        self,
        host: str,
        port: int,
        namespace: str = "",
        retry: SyncRetry | None = None,
        identity: dict | None = None,
        connect_timeout: float | None = None,
    ):
        """``namespace`` scopes all states/topics, normally
        ``run:<run_id>:`` (the reference scopes keys by run).

        ``identity`` (optional) is sent as a ``hello`` so the service can
        publish an eviction event if this client dies abnormally:
        ``{"events_topic": ..., "group": ..., "instance": ...}``.

        ``connect_timeout`` is a convenience override of
        ``retry.connect_timeout`` for callers that only care about the
        legacy knob.
        """
        self._ns = namespace
        self._addr = (host, port)
        self._retry = retry or SyncRetry()
        if connect_timeout is not None:
            self._retry.connect_timeout = float(connect_timeout)
        self._identity = dict(identity) if identity else None

        self._lock = threading.Lock()  # client state (never held during I/O)
        self._wlock = threading.Lock()  # serializes socket writes
        self._pending: dict[int, _Pending] = {}
        self._subs: dict[int, _Sub] = {}
        self._next_id = 0
        self._epoch = 0
        self._connected = False
        self._closed = False
        self._lost: SyncLostError | None = None
        self._boot: str | None = None
        self._sock: socket.socket | None = None
        self._wfile = None
        self._hb_wake = threading.Event()

        parts = self._connect_with_budget(initial=True)
        with self._lock:
            epoch, resend = self._install_locked(parts)
        self._replay(resend, epoch)

        self._heartbeat: threading.Thread | None = None
        if self._retry.heartbeat_secs > 0:
            self._heartbeat = threading.Thread(
                target=self._heartbeat_loop, daemon=True, name="tg-sync-hb"
            )
            self._heartbeat.start()

    # --------------------------------------------------------- connection

    def _connect_once(self):
        """One TCP connect + ping handshake (+ hello); raises OSError-ish
        on any failure, including a server that accepted but won't answer
        (half-open / stopped)."""
        host, port = self._addr
        sock = socket.create_connection(
            (host, port), timeout=self._retry.connect_timeout
        )
        try:
            sock.settimeout(self._retry.connect_timeout)
            wfile = sock.makefile("w", encoding="utf-8")
            rfile = sock.makefile("r", encoding="utf-8")
            wfile.write(json.dumps({"id": 0, "op": "ping"}) + "\n")
            wfile.flush()
            line = rfile.readline()
            if not line:
                raise ConnectionError("closed during handshake")
            msg = json.loads(line)
            if not msg.get("pong"):
                raise ConnectionError(f"bad handshake reply: {line.strip()!r}")
            boot = msg.get("boot", "")
            if self._identity is not None:
                wfile.write(
                    json.dumps({"id": 0, "op": "hello", **self._identity})
                    + "\n"
                )
                wfile.flush()
                if not rfile.readline():
                    raise ConnectionError("closed during hello")
            sock.settimeout(None)
            return sock, rfile, wfile, boot
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise

    def _connect_with_budget(self, initial: bool):
        """Attempt/deadline-bounded connect loop with exponential backoff
        + jitter; raises :class:`SyncLostError` naming the address."""
        r = self._retry
        max_attempts = max(1, r.attempts) if initial else r.attempts
        start = time.monotonic()
        deadline = start + r.deadline_secs
        attempt = 0
        last_err: BaseException | None = None
        while True:
            if self._closed:
                raise SyncLostError(
                    f"sync client closed while connecting to "
                    f"{self._addr[0]}:{self._addr[1]}",
                    address=self._addr,
                    attempts=attempt,
                    elapsed_secs=time.monotonic() - start,
                )
            if attempt < max_attempts and time.monotonic() < deadline:
                attempt += 1
                try:
                    return self._connect_once()
                except (OSError, ValueError, ConnectionError) as e:
                    last_err = e
            else:
                elapsed = time.monotonic() - start
                raise SyncLostError(
                    f"sync service at {self._addr[0]}:{self._addr[1]} "
                    f"unreachable after {attempt} attempt(s) over "
                    f"{elapsed:.1f}s: {last_err}",
                    address=self._addr,
                    attempts=attempt,
                    elapsed_secs=elapsed,
                )
            backoff = min(r.backoff_cap, r.backoff_base * (2 ** (attempt - 1)))
            sleep = backoff * (0.5 + random.random() / 2)  # jitter
            if time.monotonic() + sleep >= deadline and attempt >= 1:
                # sleeping past the deadline can't help; fail fast on the
                # next loop iteration
                sleep = max(0.0, deadline - time.monotonic())
            time.sleep(sleep)

    def _install_locked(self, parts) -> tuple[int, list[dict]]:
        """Adopt a fresh connection (lock held): boot-id check, re-key
        live subscriptions and unacked calls, start the new reader
        thread. Returns the replay requests for the caller to send
        AFTER releasing the lock — the master lock is never held across
        socket I/O (a stalled peer blocking a replay write must not
        wedge the heartbeat that exists to detect exactly that)."""
        sock, rfile, wfile, boot = parts
        if self._boot is not None and boot and boot != self._boot:
            try:
                sock.close()
            except OSError:
                pass
            raise SyncLostError(
                f"sync service at {self._addr[0]}:{self._addr[1]} restarted "
                "(boot id changed); coordination state was lost",
                address=self._addr,
            )
        if boot:
            self._boot = boot
        self._sock = sock
        self._wfile = wfile
        self._epoch += 1
        self._connected = True
        epoch = self._epoch

        # re-key live subscriptions and pending calls onto fresh request
        # ids; the caller replays them once the lock is released
        resend: list[dict] = []
        subs, self._subs = self._subs, {}
        for sub in subs.values():
            rid = self._next_rid_locked()
            self._subs[rid] = sub
            resend.append({"id": rid, "op": "subscribe", "topic": sub.topic})
        pending, self._pending = self._pending, {}
        for p in pending.values():
            if p.op == "bye":
                continue
            rid = self._next_rid_locked()
            self._pending[rid] = p
            resend.append({"id": rid, "op": p.op, **p.args})

        threading.Thread(
            target=self._read_loop,
            args=(epoch, rfile),
            daemon=True,
            name="tg-sync-client",
        ).start()
        return epoch, resend

    def _replay(self, resend: list[dict], epoch: int) -> None:
        # pinned to the epoch the requests were re-keyed for: if yet
        # another reconnect supersedes it mid-replay, ITS replay owns
        # the re-send (double-sending would leak server-side waiters)
        for req in resend:
            self._send(req, epoch=epoch)

    # ------------------------------------------------------------- plumbing

    def _next_rid_locked(self) -> int:
        self._next_id += 1
        return self._next_id

    def _send(
        self,
        req: dict,
        wait_secs: float | None = None,
        epoch: int | None = None,
    ) -> bool:
        """Best-effort send OUTSIDE the state lock; returns whether the
        bytes were written. A failed or skipped write leaves the request
        parked in ``_pending``/``_subs`` for the reconnect replay (the
        reader/heartbeat notices the dead socket and drives
        reconnection).

        Socket writes can block indefinitely when the peer stalls with a
        full send buffer (a SIGSTOPped server), so the write lock is
        acquired with a bound: if another writer is wedged on it, this
        request simply stays pending — and the WEDGED writer is released
        when the heartbeat force-closes the socket. The client's master
        lock is never held across socket I/O."""
        with self._lock:
            if epoch is not None and epoch != self._epoch:
                # the connection this request was registered against is
                # gone; the reconnect replay owns (or owned) the re-send
                return False
            wfile = self._wfile if self._connected else None
        if wfile is None:
            return False
        timeout = (
            wait_secs if wait_secs is not None else self._retry.connect_timeout
        )
        if not self._wlock.acquire(timeout=timeout):
            return False
        try:
            wfile.write(json.dumps(req) + "\n")
            wfile.flush()
            return True
        except (OSError, ValueError):
            return False
        finally:
            self._wlock.release()

    def _read_loop(self, epoch: int, rfile) -> None:
        try:
            for line in rfile:
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                rid = msg.get("id")
                with self._lock:
                    if epoch != self._epoch:
                        return  # superseded connection
                    p = self._pending.get(rid)
                    sub = self._subs.get(rid)
                if p is not None and "entry" not in msg:
                    with self._lock:
                        self._pending.pop(rid, None)
                    p.q.put(msg)
                elif sub is not None:
                    if "entry" in msg:
                        seq = int(msg.get("seq", 0))
                        deliver = False
                        with self._lock:
                            if seq > sub.delivered:
                                sub.delivered = seq
                                deliver = True
                        if deliver:  # replayed prefix after reconnect: skip
                            sub.q.put(msg)
                    else:
                        sub.q.put(msg)
        except (OSError, ValueError):
            pass
        self._conn_lost(epoch)

    def _conn_lost(self, epoch: int) -> None:
        """Reader exit path: poison on user close, otherwise reconnect
        within budget (in this thread — it has nothing else to do)."""
        with self._lock:
            if self._closed or self._lost is not None:
                self._poison_locked({"error": "connection closed"})
                return
            if epoch != self._epoch:
                return
            self._connected = False
            self._close_sock_locked()
        try:
            parts = self._connect_with_budget(initial=False)
            with self._lock:
                if self._closed:
                    try:
                        parts[0].close()
                    except OSError:
                        pass
                    self._poison_locked({"error": "connection closed"})
                    return
                epoch, resend = self._install_locked(parts)
            self._replay(resend, epoch)
        except SyncLostError as e:
            with self._lock:
                self._lost = e
                self._poison_locked({"sync_lost": str(e)})

    def _poison_locked(self, msg: dict) -> None:
        for p in self._pending.values():
            p.q.put(dict(msg))
        for sub in self._subs.values():
            sub.q.put(dict(msg))
        self._pending.clear()

    def _close_sock_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._wfile = None

    def _heartbeat_loop(self) -> None:
        interval = self._retry.heartbeat_secs
        pong = self._retry.effective_pong_timeout()
        # consecutive rounds whose ping could not even be WRITTEN (write
        # lock held by a possibly-wedged writer): one busy round is
        # normal under write load and must not kill a healthy
        # connection, but a persistently unavailable write path means a
        # writer is wedged on a stalled socket — force the drop then.
        unsent_rounds = 0
        while not self._hb_wake.wait(interval):
            with self._lock:
                if self._closed or self._lost is not None:
                    return
                if not self._connected:
                    unsent_rounds = 0
                    continue  # reconnect in progress
                sock = self._sock
                rid = self._next_rid_locked()
                hb_epoch = self._epoch
                q: queue.Queue = queue.Queue()
                self._pending[rid] = _Pending(op="ping", args={}, q=q)
            # short write-lock bound: a wedged writer must not delay the
            # detector that exists to un-wedge it
            sent = self._send(
                {"id": rid, "op": "ping"}, wait_secs=0.2, epoch=hb_epoch
            )
            if not sent:
                with self._lock:
                    self._pending.pop(rid, None)
                unsent_rounds += 1
                if unsent_rounds < 3:
                    continue  # transient write-lock contention
            else:
                unsent_rounds = 0
                try:
                    q.get(timeout=pong)
                    continue  # healthy
                except queue.Empty:
                    with self._lock:
                        self._pending.pop(rid, None)
            # no pong (half-open / stopped server) or persistently
            # unwritable socket: force the drop so the reader runs the
            # reconnect path (and any wedged writer gets an OSError)
            unsent_rounds = 0
            with self._lock:
                if self._connected and self._sock is sock and sock:
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        sock.close()
                    except OSError:
                        pass

    def _call(
        self, op: str, _send_wait: float | None = None, **args: Any
    ) -> queue.Queue:
        q: queue.Queue = queue.Queue()
        with self._lock:
            if self._lost is not None:
                raise SyncLostError(
                    str(self._lost),
                    address=self._lost.address,
                    attempts=self._lost.attempts,
                    elapsed_secs=self._lost.elapsed_secs,
                )
            if self._closed:
                raise RuntimeError("sync client is closed")
            rid = self._next_rid_locked()
            epoch = self._epoch
            if op == "subscribe":
                self._subs[rid] = _Sub(topic=args["topic"], q=q)
            else:
                self._pending[rid] = _Pending(op=op, args=dict(args), q=q)
        self._send(
            {"id": rid, "op": op, **args}, wait_secs=_send_wait, epoch=epoch
        )
        return q

    def _call_one(
        self,
        op: str,
        timeout: float | None = None,
        _send_wait: float | None = None,
        **args: Any,
    ) -> dict:
        q = self._call(op, _send_wait=_send_wait, **args)
        try:
            msg = q.get(timeout=timeout)
        except queue.Empty:
            with self._lock:  # forget the call: don't replay it later
                for rid, p in list(self._pending.items()):
                    if p.q is q:
                        del self._pending[rid]
            raise TimeoutError(
                f"sync op {op} timed out "
                f"(service {self._addr[0]}:{self._addr[1]})"
            ) from None
        if "sync_lost" in msg:
            raise SyncLostError(
                msg["sync_lost"], address=self._addr
            )
        if "error" in msg:
            raise RuntimeError(f"sync op {op} failed: {msg['error']}")
        return msg

    def _key(self, name: str) -> str:
        return self._ns + name

    # ------------------------------------------------------------------ API

    @property
    def address(self) -> tuple[str, int]:
        return self._addr

    def ping(self, timeout: float | None = None) -> str:
        """Liveness probe; returns the server's boot id."""
        return self._call_one("ping", timeout=timeout).get("boot", "")

    def sync_stats(self, timeout: float | None = None) -> dict:
        """The server's stats plane (docs/INSTANCE_PROTOCOL.md §4.2).

        Version negotiation is by reply shape, so this client tolerates
        old servers: a reply carrying ``"v": 2`` has the full stats
        blocks — per-op counters (``ops``), connection churn (``conn``),
        barrier lifecycle (``barriers``, incl. armed→release episode
        timing by fan-in target on the python server), pubsub depth
        (``pubsub``), idempotency-dedup hits (``dedup``) and per-op
        service-time histograms (``op_time_us``, python server only) —
        while a reply without ``v`` is a pre-stats v1 server and only
        the live-occupancy fields ``{"conns", "waiters", "subs",
        "boot"}`` (present in both versions) exist."""
        msg = self._call_one("sync_stats", timeout=timeout)
        return {k: v for k, v in msg.items() if k != "id"}

    def signal_entry(self, state: str) -> int:
        return self._call_one(
            "signal_entry", state=self._key(state), token=uuid.uuid4().hex
        )["seq"]

    def counter(self, state: str) -> int:
        return self._call_one("counter", state=self._key(state))["count"]

    def barrier(self, state: str, target: int, timeout: float | None = None) -> None:
        self._call_one(
            "barrier", state=self._key(state), target=target, timeout=timeout
        )

    def signal_and_wait(
        self, state: str, target: int, timeout: float | None = None
    ) -> int:
        return self._call_one(
            "signal_and_wait",
            state=self._key(state),
            target=target,
            timeout=timeout,
            token=uuid.uuid4().hex,
        )["seq"]

    def publish(self, topic: str, payload: Any) -> int:
        return self._call_one(
            "publish",
            topic=self._key(topic),
            payload=payload,
            token=uuid.uuid4().hex,
        )["seq"]

    def subscribe(self, topic: str, timeout: float | None = None) -> Iterator[Any]:
        """Yield every entry of the topic in order (all entries from the
        beginning, like the reference's Subscribe). Raises
        :class:`SyncLostError` if the service is lost mid-stream; a
        deliberate ``close()`` ends the iterator normally.

        The subscription is unregistered when the iterator exits for ANY
        reason (timeout, error, the consumer abandoning it) — an
        abandoned subscription must not keep accumulating entries and
        being replayed on every reconnect."""
        q = self._call("subscribe", topic=self._key(topic))
        try:
            while True:
                try:
                    msg = q.get(timeout=timeout)
                except queue.Empty:
                    raise TimeoutError(
                        f"subscribe {topic} timed out"
                    ) from None
                if "sync_lost" in msg:
                    raise SyncLostError(msg["sync_lost"], address=self._addr)
                if "error" in msg:
                    return
                yield msg["entry"]
        finally:
            with self._lock:
                # by queue identity: reconnects re-key the rid
                for rid, sub in list(self._subs.items()):
                    if sub.q is q:
                        del self._subs[rid]

    def publish_subscribe(
        self, topic: str, payload: Any, timeout: float | None = None
    ) -> tuple[int, Iterator[Any]]:
        seq = self.publish(topic, payload)
        return seq, self.subscribe(topic, timeout=timeout)

    def close(self) -> None:
        """Clean shutdown: tells the server (``bye``) so no eviction
        event is published, then drops the connection."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._hb_wake.set()
            was_connected = self._connected
        if was_connected:
            self._send({"id": 0, "op": "bye"}, wait_secs=0.5)
        with self._lock:
            self._close_sock_locked()
            self._poison_locked({"error": "connection closed"})
