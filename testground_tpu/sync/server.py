"""TCP sync service.

The ``local:exec`` runner's infra piece: the analog of the reference's
Redis-backed sync-service container (``pkg/runner/local_common.go:77-104``),
implemented as a newline-delimited-JSON TCP server over
:class:`InMemSyncService`.

Wire protocol (one JSON object per line):

    request:  {"id": N, "op": <op>, ...args}
    reply:    {"id": N, ...result}            exactly one, except:
    subscribe streams {"id": N, "entry": payload, "seq": i} frames until the
    connection closes.

Ops: ``signal_entry(state[, token])``, ``barrier(state, target)``,
``signal_and_wait(state, target[, token])``, ``publish(topic, payload[,
token])``, ``subscribe(topic)``, ``counter(state)``, plus the liveness/
identity plane (docs/CROSSHOST.md):

- ``ping`` → ``{"pong": true, "boot": <id>}`` — heartbeat + boot-id probe
  (a changed boot id tells a reconnecting client the service restarted
  and lost its state);
- ``hello(events_topic, group, instance)`` — registers the connection's
  instance identity; an ABNORMAL disconnect (anything but ``bye``)
  publishes ``{"type": "evicted", ...}`` to ``events_topic`` so runners
  and surviving instances observe the death;
- ``bye`` — clean-shutdown marker (no eviction event);
- ``sync_stats`` → the wire-versioned stats plane (v2, ``"v": 2``):
  the v1 live-occupancy fields ``{"conns", "waiters", "subs"}`` (the
  observable that pins "a dead client's barrier occupancy is
  released") plus per-op counters, service-time log2 histograms,
  barrier lifecycle timing, pubsub depth/high-water marks, connection
  churn and idempotency-dedup hit counts (``sync/stats.py``,
  docs/INSTANCE_PROTOCOL.md §4.2).

``token`` is an idempotency key: reconnecting clients re-send unacked
mutations with the original token and the service replies with the
original seq instead of mutating twice.

Architecture (the 10k fan-in rewrite — docs/CROSSHOST.md "Server
architecture"): a ``selectors``-based EVENT LOOP, not thread-per-
connection. The r1 fan-in bench measured the old
``socketserver.ThreadingTCPServer`` + per-op-thread design collapsing at
10k clients (10k accept threads + one thread per parked barrier: accepts
everything, then stops servicing). Now:

- every connection is a non-blocking socket with its own read buffer and
  a BOUNDED outbound queue (``outq_limit``, default 16 MiB — parity with
  the native server's ``--max-wbuf``): a slow or stalled reader is shed
  (dropped + counted as an eviction) the moment its backlog trips the
  bound, and can never wedge any other peer;
- parked barriers and subscriptions are RECORDS, not threads; each drain
  of ready sockets dispatches every complete line, applies mutations,
  then runs ONE coalesced release pass (one
  ``InMemSyncService.counters_snapshot`` for all touched states, every
  satisfiable waiter fanned out in one sweep) and ONE fanout pass per
  touched topic (entries fetched once, payload JSON encoded once,
  streamed to every subscriber cursor);
- replies are buffered per connection and flushed once per drain via
  ``socket.sendmsg`` (writev) — many frames, one syscall;
- barrier deadlines, evict-grace windows and the idle sweep ride a
  hashed TIMER WHEEL owned by the loop (the old per-disconnect
  ``threading.Timer`` spray is gone);
- connections can optionally be SHARDED across N loops (``shards``;
  cross-shard releases ride per-loop inboxes + a wakeup pipe). The
  default is one loop — under the GIL extra Python loops buy little,
  the knob exists for symmetry with the native server and for
  experiments off-GIL.

The server binds ``host`` (default loopback; ``0.0.0.0`` opens it to
other hosts — the ``cluster_k8s.go:302`` network-citizen analog) and,
when ``idle_timeout`` is set, sweeps connections that have sent nothing
(not even a heartbeat) for that long: a SIGSTOPped or half-open peer is
evicted, its parked barrier/subscribe waiters released, and its eviction
published, rather than leaking occupancy forever.

This Python server is the behavioral spec; a wire-compatible native C++
implementation (sharded epoll loops) lives at
``testground_tpu/native/syncsvc.cc`` and is what the local:exec runner
boots by default when a toolchain is available (runner config
``sync_service``, default "auto").

Runnable standalone (the cross-host deployment unit, also wrapped by
``tg sync-service``)::

    python -m testground_tpu.sync.server --host 0.0.0.0 --port 9042

prints ``LISTENING <host> <port>`` once bound and serves until
SIGTERM/SIGINT.
"""

from __future__ import annotations

import itertools
import json
import selectors
import socket
import threading
import time
import uuid
from collections import deque

from testground_tpu.logging_ import S

from .inmem import InMemSyncService
from .stats import SyncStats

__all__ = ["SyncServiceServer"]

# bounded per-peer outbound queue: a reader this far behind has stopped
# reading (or is partitioned with an open window) — shedding it beats
# wedging memory/fairness for everyone else; parity with the native
# server's kMaxWbuf default
DEFAULT_OUTQ_LIMIT = 16 << 20

_RECV_SIZE = 262144
_WRITEV_SEGS = 64  # segments per sendmsg flush


class _TimerWheel:
    """Hashed timer wheel: O(1) arm/cancel, fired in batches by the
    owning event loop — replaces the per-waiter ``wait_for`` timeouts
    and per-disconnect ``threading.Timer`` spray of the threaded server.
    Granularity is coarse (50 ms) on purpose: barrier deadlines, grace
    windows and idle sweeps are second-scale contracts."""

    __slots__ = ("_g", "_buckets")

    def __init__(self, granularity: float = 0.05):
        self._g = granularity
        self._buckets: dict[int, list] = {}

    def arm(self, now: float, delay: float, fn) -> list:
        """Schedule ``fn`` after ``delay``; returns a cancel handle."""
        slot = int((now + max(0.0, delay)) / self._g) + 1
        handle = [fn]
        self._buckets.setdefault(slot, []).append(handle)
        return handle

    @staticmethod
    def cancel(handle: list) -> None:
        handle[0] = None

    def next_due(self, now: float) -> float | None:
        """Seconds until the nearest armed slot, or None when empty."""
        if not self._buckets:
            return None
        return max(0.0, min(self._buckets) * self._g - now)

    def fire(self, now: float) -> None:
        if not self._buckets:
            return
        cur = int(now / self._g)
        due = [s for s in self._buckets if s <= cur]
        for s in sorted(due):
            for handle in self._buckets.pop(s):
                fn = handle[0]
                if fn is not None:
                    fn()


class _Conn:
    __slots__ = (
        "sock",
        "fd",
        "loop",
        "rbuf",
        "out",
        "out_bytes",
        "want_write",
        "last_activity",
        "hello",
        "clean",
        "dead",
        "waiters",
        "subs",
    )

    def __init__(self, sock: socket.socket, loop: "_EventLoop"):
        self.sock = sock
        self.fd = sock.fileno()
        self.loop = loop
        self.rbuf = bytearray()
        self.out: deque[bytes] = deque()
        self.out_bytes = 0
        self.want_write = False
        self.last_activity = time.monotonic()
        self.hello: dict | None = None
        self.clean = False
        self.dead = False
        self.waiters: list[_Waiter] = []
        self.subs: list[_SubRec] = []


class _Waiter:
    """A parked barrier / signal_and_wait record (no thread)."""

    __slots__ = ("conn", "rid", "state", "target", "seq", "t0", "timer",
                 "alive")

    def __init__(self, conn, rid, state, target, seq, t0):
        self.conn = conn
        self.rid = rid
        self.state = state
        self.target = target
        self.seq = seq  # None for plain barrier; echoed for signal_and_wait
        self.t0 = t0  # dispatch stamp: release records the FULL fan-in wait
        self.timer = None
        self.alive = True


class _SubRec:
    __slots__ = ("conn", "rid", "topic", "cursor", "alive")

    def __init__(self, conn, rid, topic):
        self.conn = conn
        self.rid = rid
        self.topic = topic
        self.cursor = 0
        self.alive = True


class _Occupancy:
    """Live waiter/subscriber accounting exposed via ``sync_stats``."""

    def __init__(self, stats: SyncStats | None = None):
        self._lock = threading.Lock()
        self.stats = stats
        self.waiters = 0
        self.subs = 0

    def inc(self, kind: str) -> None:
        with self._lock:
            setattr(self, kind, getattr(self, kind) + 1)
            w, s = self.waiters, self.subs
        if self.stats is not None:  # high-water marks
            self.stats.note_occupancy(w, s)

    def dec(self, kind: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, kind, getattr(self, kind) - n)


class _EventLoop(threading.Thread):
    """One selector loop owning a shard of the connections.

    Drain cycle: select → read every ready socket and dispatch all
    complete lines (mutations applied, touched states/topics recorded)
    → fire due timers → ONE coalesced release pass + fanout pass →
    flush every dirty connection with sendmsg (writev)."""

    def __init__(self, server: "SyncServiceServer", index: int):
        super().__init__(daemon=True, name=f"tg-sync-loop-{index}")
        self.server = server
        self.index = index
        self.sel = selectors.DefaultSelector()
        self.conns: dict[int, _Conn] = {}
        self.waiters_by_state: dict[str, list[_Waiter]] = {}
        self.subs_by_topic: dict[str, list[_SubRec]] = {}
        self.wheel = _TimerWheel()
        self._inbox: deque = deque()
        self._inbox_lock = threading.Lock()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        # per-drain scratch (reset each cycle); foreign = forwarded by
        # another loop's pass — processed here but NEVER re-broadcast
        # (re-forwarding would ping-pong touches between loops forever)
        self._touched_states: set[str] = set()
        self._touched_topics: set[str] = set()
        self._foreign_states: set[str] = set()
        self._foreign_topics: set[str] = set()
        self._dirty: set[_Conn] = set()
        self._op_done: list = []  # (op, us) — inline ops, batch-flushed
        self._op_timed: list = []  # (op, us) — released parked ops
        self._task_ops: dict = {}  # task → ops this drain (hello attr.)
        self._compact_states: set[str] = set()
        self._compact_topics: set[str] = set()

    # ----------------------------------------------------- cross-thread

    def post(self, item) -> None:
        with self._inbox_lock:
            self._inbox.append(item)
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # wake byte already pending (or loop gone)

    # -------------------------------------------------------------- run

    def run(self) -> None:
        srv = self.server
        if self.index == 0:
            self.sel.register(srv._listener, selectors.EVENT_READ, "accept")
        if srv.idle_timeout > 0:
            self._arm_idle_sweep()
        while not srv._stop.is_set():
            # a late mutation (e.g. an eviction published from a flush-
            # time drop) can leave touched keys behind after the passes
            # ran — spin one zero-timeout cycle rather than sleeping on
            # undelivered releases
            if (
                self._touched_states
                or self._touched_topics
                or self._foreign_states
                or self._foreign_topics
            ):
                timeout = 0.0
            else:
                timeout = self.wheel.next_due(time.monotonic())
            try:
                events = self.sel.select(timeout)
            except OSError:
                continue
            now = time.monotonic()
            for key, mask in events:
                tag = key.data
                if tag == "wake":
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                elif tag == "accept":
                    self._accept_ready()
                else:
                    conn: _Conn = tag
                    if conn.dead:
                        continue
                    if mask & selectors.EVENT_WRITE:
                        self._flush(conn)
                    if mask & selectors.EVENT_READ and not conn.dead:
                        self._on_readable(conn, now)
            self._drain_inbox()
            # release BEFORE the wheel fires: a barrier satisfied by a
            # signal in this same drain must release, not time out (the
            # native server and the old wait_for both check the
            # predicate first); timers that publish (evict-grace) leave
            # touched keys behind and the zero-timeout spin above
            # delivers them next cycle
            self._release_pass()
            self._fanout_pass()
            self.wheel.fire(now)
            self._compact()
            if self._op_done and srv.stats is not None:
                srv.stats.op_done_batch(self._op_done)
            if self._op_timed and srv.stats is not None:
                srv.stats.time_op_batch(self._op_timed)
            if self._task_ops and srv.stats is not None:
                srv.stats.task_ops_batch(self._task_ops)
            self._op_done = []
            self._op_timed = []
            self._task_ops = {}
            dirty, self._dirty = self._dirty, set()
            for conn in dirty:
                if not conn.dead:
                    self._flush(conn)
        # shutdown: close this shard's connections
        for conn in list(self.conns.values()):
            try:
                conn.sock.close()
            except OSError:
                pass
        self.sel.close()
        try:
            self._wake_r.close()
            self._wake_w.close()
        except OSError:
            pass

    # ----------------------------------------------------------- accept

    def _accept_ready(self) -> None:
        srv = self.server
        while True:
            try:
                sock, _ = srv._listener.accept()
            except (BlockingIOError, OSError):
                return
            try:
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                sock.close()
                continue
            loop = srv._loops[srv._next_shard]
            srv._next_shard = (srv._next_shard + 1) % len(srv._loops)
            if loop is self:
                self._adopt(sock)
            else:
                loop.post(("conn", sock))

    def _adopt(self, sock: socket.socket) -> None:
        conn = _Conn(sock, self)
        self.conns[conn.fd] = conn
        try:
            self.sel.register(sock, selectors.EVENT_READ, conn)
        except (ValueError, OSError):
            conn.dead = True
            self.conns.pop(conn.fd, None)
            sock.close()
            return
        st = self.server.stats
        if st is not None:
            st.conn_open()

    def _drain_inbox(self) -> None:
        if not self._inbox:
            return
        with self._inbox_lock:
            items, self._inbox = self._inbox, deque()
        for item in items:
            kind = item[0]
            if kind == "conn":
                self._adopt(item[1])
            elif kind == "touch":
                self._foreign_states.update(item[1])
                self._foreign_topics.update(item[2])

    # ------------------------------------------------------------- read

    def _on_readable(self, conn: _Conn, now: float) -> None:
        try:
            data = conn.sock.recv(_RECV_SIZE)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn)
            return
        if not data:
            self._drop(conn)
            return
        conn.last_activity = now
        buf = conn.rbuf
        buf += data
        start = 0
        while True:
            nl = buf.find(b"\n", start)
            if nl < 0:
                break
            line = bytes(buf[start:nl])
            start = nl + 1
            if line:
                self._dispatch(conn, line)
                if conn.dead:
                    return
        if start:
            del buf[:start]

    # --------------------------------------------------------- dispatch

    def _dispatch(self, conn: _Conn, line: bytes) -> None:
        srv = self.server
        svc = srv.service
        stats = srv.stats
        perf = time.perf_counter
        t_op = perf()
        try:
            req = json.loads(line)
        except (json.JSONDecodeError, UnicodeDecodeError):
            req = None
        if not isinstance(req, dict):  # `5` / `null` are lines too
            self._send_json(conn, {"id": -1, "error": "malformed request"})
            return
        rid = req.get("id", -1)
        op = req.get("op")
        # hello attribution (docs/CROSSHOST.md): every op from a
        # connection that introduced itself with a task id counts toward
        # that task — accumulated per drain, batch-flushed like _op_done
        # so the hot path takes no stats lock
        if stats is not None and conn.hello:
            _task = conn.hello.get("task", "")
            if _task:
                self._task_ops[_task] = self._task_ops.get(_task, 0) + 1
        out: dict | None = None
        try:
            if op == "signal_entry":
                out = {
                    "id": rid,
                    "seq": svc.signal_entry(
                        req["state"], token=req.get("token")
                    ),
                }
                self._touched_states.add(req["state"])
            elif op == "counter":
                out = {"id": rid, "count": svc.counter(req["state"])}
            elif op == "publish":
                out = {
                    "id": rid,
                    "seq": svc.publish(
                        req["topic"], req["payload"], token=req.get("token")
                    ),
                }
                self._touched_topics.add(req["topic"])
            elif op == "ping":
                out = {"id": rid, "pong": True, "boot": srv.boot_id}
            elif op == "hello":
                hello = {
                    "events_topic": req.get("events_topic", ""),
                    "group": req.get("group", ""),
                    "instance": req.get("instance", -1),
                    # run-id attribution for per-task op counters; ""
                    # from old clients that don't send it (wire-compat:
                    # the field is additive in both directions)
                    "task": req.get("task", ""),
                }
                _ident_retag(srv, conn.hello, hello)
                conn.hello = hello
                out = {"id": rid, "ok": True, "boot": srv.boot_id}
            elif op == "bye":
                conn.clean = True
                out = {"id": rid, "ok": True}
            elif op == "sync_stats":
                payload = {
                    "id": rid,
                    "conns": sum(len(lp.conns) for lp in srv._loops),
                    "waiters": srv.occupancy.waiters,
                    "subs": srv.occupancy.subs,
                    "boot": srv.boot_id,
                }
                if stats is not None:  # v2: v1 fields preserved
                    # flush this drain's accounting, then count this
                    # very query BEFORE snapshotting — the conservation
                    # contract: a sync_stats reply includes itself
                    if self._op_done:
                        stats.op_done_batch(self._op_done)
                        self._op_done = []
                    if self._op_timed:
                        stats.time_op_batch(self._op_timed)
                        self._op_timed = []
                    if self._task_ops:
                        stats.task_ops_batch(self._task_ops)
                        self._task_ops = {}
                    stats.op_done(op, (perf() - t_op) * 1e6)
                    topics, entries = svc.pubsub_gauges()
                    payload.update(
                        stats.snapshot(topics=topics, entries=entries)
                    )
                self._send_json(conn, payload)
                return
            elif op == "barrier" or op == "signal_and_wait":
                if stats is not None:  # parked ops count at dispatch
                    stats.count_op(op)
                # validate EVERY field before any mutation or parking: a
                # malformed request must produce exactly one error reply
                # — never a parked waiter that later answers a second
                # time, nor a half-applied signal
                state = req["state"]
                target = int(req["target"])
                timeout = req.get("timeout")
                delay = None if timeout is None else max(0.0, float(timeout))
                seq = None
                if op == "signal_and_wait":
                    seq = svc.signal_entry(state, token=req.get("token"))
                if stats is not None:
                    stats.barrier_parked(state, target)
                w = _Waiter(conn, rid, state, target, seq, t_op)
                conn.waiters.append(w)
                self.waiters_by_state.setdefault(state, []).append(w)
                srv.occupancy.inc("waiters")
                if delay is not None:
                    # an EXPLICIT 0 is an immediate non-blocking check:
                    # unmet after this drain's release pass → timed out
                    w.timer = self.wheel.arm(
                        time.monotonic(),
                        delay,
                        lambda w=w: self._expire_waiter(w),
                    )
                    if delay == 0.0:
                        self._touched_states.add(state)
                        self._release_pass()
                        if w.alive:
                            self._expire_waiter(w)
                        return
                self._touched_states.add(state)
            elif op == "subscribe":
                topic = req["topic"]
                rec = _SubRec(conn, rid, topic)
                conn.subs.append(rec)
                self.subs_by_topic.setdefault(topic, []).append(rec)
                srv.occupancy.inc("subs")
                if stats is not None:
                    self._op_done.append((op, (perf() - t_op) * 1e6))
                self._touched_topics.add(topic)
            else:
                self._send_json(
                    conn, {"id": rid, "error": f"unknown op {op!r}"}
                )
            if out is not None:
                if stats is not None:
                    self._op_done.append((op, (perf() - t_op) * 1e6))
                self._send_json(conn, out)
        except KeyError as e:
            # the op still counts: the native server counts at dispatch
            # before field extraction, so a malformed request must not
            # diverge the backends' op counters
            if stats is not None and out is None and op not in (
                "barrier", "signal_and_wait",
            ):
                stats.count_op(op)
            self._send_json(conn, {"id": rid, "error": f"missing field {e}"})
        except (TypeError, ValueError) as e:
            self._send_json(conn, {"id": rid, "error": str(e)})

    # --------------------------------------------- release/fanout passes

    def _release_pass(self) -> None:
        local = self._touched_states
        states = local | self._foreign_states
        if not states:
            return
        self._touched_states = set()
        self._foreign_states = set()
        srv = self.server
        if local and len(srv._loops) > 1:
            # forward only LOCALLY-originated touches so other loops'
            # waiters see them; forwarded ones are terminal here
            for lp in srv._loops:
                if lp is not self:
                    lp.post(("touch", tuple(local), ()))
        counts = srv.service.counters_snapshot(states)
        stats = srv.stats
        for state in states:
            lst = self.waiters_by_state.get(state)
            if not lst:
                continue
            count = counts.get(state, 0)
            keep: list[_Waiter] = []
            released: dict[int, int] = {}  # target -> n (episode batch)
            n_released = 0
            for w in lst:
                if not w.alive:
                    continue
                if w.target <= count:
                    self._reply_waiter(w)
                    n_released += 1
                    released[w.target] = released.get(w.target, 0) + 1
                    if stats is not None:
                        op = (
                            "signal_and_wait" if w.seq is not None
                            else "barrier"
                        )
                        self._op_timed.append(
                            (op, (time.perf_counter() - w.t0) * 1e6)
                        )
                else:
                    keep.append(w)
            if stats is not None:
                for target, n in released.items():
                    stats.barrier_released_batch(state, target, n)
            if n_released:
                srv.occupancy.dec("waiters", n_released)
            if keep:
                self.waiters_by_state[state] = keep
            else:
                self.waiters_by_state.pop(state, None)

    def _reply_waiter(self, w: _Waiter) -> None:
        w.alive = False
        if w.timer is not None:
            _TimerWheel.cancel(w.timer)
        rid = w.rid
        if isinstance(rid, int):
            if w.seq is not None:
                frame = b'{"id": %d, "seq": %d, "ok": true}\n' % (rid, w.seq)
            else:
                frame = b'{"id": %d, "ok": true}\n' % rid
            self._enqueue(w.conn, frame)
        else:
            obj = {"id": rid, "ok": True}
            if w.seq is not None:
                obj["seq"] = w.seq
            self._send_json(w.conn, obj)
        try:
            w.conn.waiters.remove(w)
        except ValueError:
            pass

    def _expire_waiter(self, w: _Waiter) -> None:
        if not w.alive:
            return
        w.alive = False
        stats = self.server.stats
        if stats is not None:
            stats.barrier_timed_out(w.state, w.target)
            self._op_timed.append(
                (
                    "signal_and_wait" if w.seq is not None else "barrier",
                    (time.perf_counter() - w.t0) * 1e6,
                )
            )
        self.server.occupancy.dec("waiters")
        self._send_json(
            w.conn,
            {
                "id": w.rid,
                "error": f"barrier {w.state} (target {w.target}) timed out",
            },
        )
        try:
            w.conn.waiters.remove(w)
        except ValueError:
            pass
        self._compact_states.add(w.state)

    def _fanout_pass(self) -> None:
        local = self._touched_topics
        topics = local | self._foreign_topics
        if not topics:
            return
        self._touched_topics = set()
        self._foreign_topics = set()
        srv = self.server
        if local and len(srv._loops) > 1:
            for lp in srv._loops:
                if lp is not self:
                    lp.post(("touch", (), tuple(local)))
        svc = srv.service
        for topic in topics:
            subs = self.subs_by_topic.get(topic)
            if not subs:
                continue
            live = [s for s in subs if s.alive]
            if not live:
                continue
            mn = min(s.cursor for s in live)
            total, entries = svc.entries_since(topic, mn)
            if total == 0:
                continue
            encoded: list[bytes | None] = [None] * len(entries)
            for s in live:
                while s.cursor < total:
                    idx = s.cursor - mn
                    enc = encoded[idx]
                    if enc is None:
                        enc = encoded[idx] = json.dumps(
                            entries[idx]
                        ).encode("utf-8")
                    s.cursor += 1
                    if isinstance(s.rid, int):
                        frame = (
                            b'{"id": %d, "entry": ' % s.rid
                            + enc
                            + b', "seq": %d}\n' % s.cursor
                        )
                    else:
                        frame = (
                            json.dumps(
                                {
                                    "id": s.rid,
                                    "entry": entries[idx],
                                    "seq": s.cursor,
                                }
                            ).encode("utf-8")
                            + b"\n"
                        )
                    self._enqueue(s.conn, frame)
                    if s.conn.dead:
                        break

    def _compact(self) -> None:
        """Purge dead waiter/sub records from the per-key indexes (the
        per-drain batch form of the threaded server's thread exits)."""
        if self._compact_states:
            for state in self._compact_states:
                lst = self.waiters_by_state.get(state)
                if lst is None:
                    continue
                lst = [w for w in lst if w.alive]
                if lst:
                    self.waiters_by_state[state] = lst
                else:
                    self.waiters_by_state.pop(state, None)
            self._compact_states = set()
        if self._compact_topics:
            for topic in self._compact_topics:
                lst = self.subs_by_topic.get(topic)
                if lst is None:
                    continue
                lst = [s for s in lst if s.alive]
                if lst:
                    self.subs_by_topic[topic] = lst
                else:
                    self.subs_by_topic.pop(topic, None)
            self._compact_topics = set()

    # ------------------------------------------------------------ write

    def _send_json(self, conn: _Conn, obj: dict) -> None:
        self._enqueue(conn, json.dumps(obj).encode("utf-8") + b"\n")

    def _enqueue(self, conn: _Conn, data: bytes) -> None:
        if conn.dead:
            return
        conn.out.append(data)
        conn.out_bytes += len(data)
        if conn.out_bytes > self.server.outq_limit:
            # backpressure: the peer stopped reading — shed it rather
            # than let its backlog starve every other connection
            st = self.server.stats
            if st is not None:
                st.conn_evicted()
            S().debug(
                "sync service: shedding slow reader (%d bytes queued)",
                conn.out_bytes,
            )
            self._drop(conn)
            return
        self._dirty.add(conn)

    def _flush(self, conn: _Conn) -> None:
        out = conn.out
        sock = conn.sock
        while out:
            try:
                n = sock.sendmsg(list(itertools.islice(out, _WRITEV_SEGS)))
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._drop(conn)
                return
            conn.out_bytes -= n
            while out and n >= len(out[0]):
                n -= len(out[0])
                out.popleft()
            if n and out:
                out[0] = out[0][n:]
        need_write = bool(out)
        if need_write != conn.want_write:
            conn.want_write = need_write
            events = selectors.EVENT_READ | (
                selectors.EVENT_WRITE if need_write else 0
            )
            try:
                self.sel.modify(sock, events, conn)
            except (KeyError, ValueError, OSError):
                pass

    # ------------------------------------------------------- disconnect

    def _drop(self, conn: _Conn) -> None:
        """The ONE disconnect path (EOF, reset, idle eviction, slow-
        reader shed, write error): release occupancy promptly, then run
        the identity/eviction-event bookkeeping."""
        if conn.dead:
            return
        conn.dead = True
        srv = self.server
        self.conns.pop(conn.fd, None)
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        st = srv.stats
        if st is not None:
            st.conn_close()
        n_waiters = 0
        for w in conn.waiters:
            if w.alive:
                w.alive = False
                n_waiters += 1
                if w.timer is not None:
                    _TimerWheel.cancel(w.timer)
                if st is not None:
                    st.barrier_canceled(w.state, w.target)
                self._compact_states.add(w.state)
        conn.waiters = []
        if n_waiters:
            srv.occupancy.dec("waiters", n_waiters)
        n_subs = 0
        for s in conn.subs:
            if s.alive:
                s.alive = False
                n_subs += 1
                self._compact_topics.add(s.topic)
        conn.subs = []
        if n_subs:
            srv.occupancy.dec("subs", n_subs)
        conn.out.clear()
        conn.out_bytes = 0
        self._dirty.discard(conn)
        if conn.hello and not srv._stop.is_set():
            _note_disconnect(srv, self, conn.hello, conn.clean)

    # ------------------------------------------------------- idle sweep

    def _arm_idle_sweep(self) -> None:
        interval = max(0.1, self.server.idle_timeout / 4.0)
        self.wheel.arm(time.monotonic(), interval, self._idle_sweep)

    def _idle_sweep(self) -> None:
        srv = self.server
        if srv._stop.is_set():
            return
        now = time.monotonic()
        stale = [
            c
            for c in self.conns.values()
            if now - c.last_activity > srv.idle_timeout
        ]
        for conn in stale:
            S().debug(
                "sync service: evicting idle connection (%.1fs silent)",
                now - conn.last_activity,
            )
            if srv.stats is not None:
                srv.stats.conn_evicted()
            self._drop(conn)
        self._arm_idle_sweep()


def _ident_key(hello: dict) -> tuple:
    return (
        hello.get("events_topic", ""),
        hello.get("group", ""),
        hello.get("instance", -1),
    )


def _ident_retag(server, old: dict | None, new: dict) -> None:
    """Track live connection count per instance identity (hello)."""
    with server.ident_lock:
        if old is not None:
            k = _ident_key(old)
            n = server.identities.get(k, 0) - 1
            if n <= 0:
                server.identities.pop(k, None)
            else:
                server.identities[k] = n
        k = _ident_key(new)
        server.identities[k] = server.identities.get(k, 0) + 1


def _note_disconnect(server, loop: _EventLoop, hello: dict, clean: bool) -> None:
    """Identity bookkeeping + GRACE-windowed eviction: an abnormal
    disconnect only becomes an ``evicted`` event if no connection with
    the same identity is back within ``evict_grace`` seconds — a client
    dropping its socket to RECONNECT (heartbeat force-close, partition
    heal) must not be announced dead to the run. The grace window rides
    the owning loop's timer wheel."""
    key = _ident_key(hello)
    with server.ident_lock:
        n = server.identities.get(key, 0) - 1
        if n <= 0:
            server.identities.pop(key, None)
        else:
            server.identities[key] = n
    if clean or n > 0 or not hello.get("events_topic"):
        return

    def fire() -> None:
        if server._stop.is_set():
            return
        with server.ident_lock:
            if server.identities.get(key, 0) > 0:
                return  # the instance came back inside the grace window
        try:
            server.service.publish(
                hello["events_topic"],
                {
                    "type": "evicted",
                    "group": hello.get("group", ""),
                    "instance": hello.get("instance", -1),
                    "error": "connection lost (killed, partitioned, or "
                    "idle-evicted)",
                },
            )
        except Exception:  # noqa: BLE001 — eviction is best-effort
            return
        loop._touched_topics.add(hello["events_topic"])

    grace = float(getattr(server, "evict_grace", 0.0))
    if grace <= 0:
        fire()
        return
    loop.wheel.arm(time.monotonic(), grace, fire)


class SyncServiceServer:
    """Lifecycle wrapper; bind to an ephemeral port with ``port=0``.

    ``host`` is the bind address (default loopback — pass ``"0.0.0.0"``
    to serve other hosts); ``idle_timeout`` (seconds, 0 = disabled)
    evicts connections that have been silent for that long (heartbeating
    clients — the SDK's default — are never idle while alive, so only
    dead/partitioned peers trip the sweep); ``shards`` is the event-loop
    count (default 1; see the module docstring); ``outq_limit`` bounds
    each peer's outbound queue in bytes — a reader that far behind is
    shed instead of wedging the loop's memory and fairness.
    """

    def __init__(
        self,
        service: InMemSyncService | None = None,
        port: int = 0,
        host: str = "127.0.0.1",
        idle_timeout: float = 0.0,
        evict_grace: float = 2.0,
        stats: bool = True,
        shards: int = 1,
        outq_limit: int = DEFAULT_OUTQ_LIMIT,
    ):
        self.service = service or InMemSyncService()
        self.idle_timeout = float(idle_timeout)
        self.evict_grace = float(evict_grace)
        self.outq_limit = int(outq_limit)
        # the sync-plane stats recorder (always on by default — batched
        # python-int adds; stats=False exists for the fan-in bench's
        # instrumented-vs-uninstrumented A/B and doubles as the old-
        # server emulation for client version-tolerance tests: with it
        # off, sync_stats answers the v1 shape, no "v" field)
        self.stats: SyncStats | None = SyncStats() if stats else None
        self.service.stats = self.stats
        self.occupancy = _Occupancy(self.stats)
        self.boot_id = uuid.uuid4().hex
        # hello'd-identity → live connection count; disconnects below a
        # count of zero arm the evict_grace timer (see _note_disconnect)
        self.identities: dict = {}
        self.ident_lock = threading.Lock()
        self._stop = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        # the old socketserver default backlog of 5 overflowed instantly
        # under a 1k-10k connect storm; match the native listen depth
        self._listener.listen(1024)
        self._listener.setblocking(False)
        self._next_shard = 0
        self._loops = [
            _EventLoop(self, i) for i in range(max(1, int(shards)))
        ]

    @property
    def address(self) -> tuple[str, int]:
        return self._listener.getsockname()

    def start(self) -> "SyncServiceServer":
        for loop in self._loops:
            loop.start()
        S().debug("sync service listening on %s:%d", *self.address)
        return self

    def stop(self) -> None:
        self._stop.set()
        for loop in self._loops:
            loop.post(("stop",))
        try:
            self._listener.close()
        except OSError:
            pass
        for loop in self._loops:
            loop.join(timeout=2)


def _main(argv: list[str] | None = None) -> int:
    """``python -m testground_tpu.sync.server``: the standalone,
    cross-host deployment unit (also behind ``tg sync-service``)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="testground_tpu.sync.server",
        description="standalone sync service (JSON-lines TCP)",
    )
    ap.add_argument("--host", default="127.0.0.1", help="bind address")
    ap.add_argument("--port", type=int, default=0, help="bind port (0=ephemeral)")
    ap.add_argument(
        "--idle-timeout",
        type=float,
        default=0.0,
        help="evict connections silent for this many seconds (0=off)",
    )
    ap.add_argument(
        "--evict-grace",
        type=float,
        default=2.0,
        help="window an abnormally-disconnected instance has to "
        "reconnect before its eviction is published (0=immediate)",
    )
    ap.add_argument(
        "--shards",
        type=int,
        default=1,
        help="event loops to shard connections across (default 1; "
        "under the GIL extra loops buy little — the knob mirrors the "
        "native server's)",
    )
    ap.add_argument(
        "--outq-limit",
        type=int,
        default=DEFAULT_OUTQ_LIMIT,
        help="per-connection outbound-queue bound in bytes; a reader "
        "this far behind is shed (slow-reader backpressure)",
    )
    ap.add_argument(
        "--no-stats",
        action="store_true",
        help="disable the sync-stats plane (sync_stats answers the v1 "
        "occupancy shape) — exists for the fan-in bench's "
        "instrumented-vs-uninstrumented A/B, not for production",
    )
    args = ap.parse_args(argv)

    srv = SyncServiceServer(
        port=args.port,
        host=args.host,
        idle_timeout=args.idle_timeout,
        evict_grace=args.evict_grace,
        stats=not args.no_stats,
        shards=args.shards,
        outq_limit=args.outq_limit,
    ).start()
    return serve_until_signal(srv)


def serve_until_signal(svc) -> int:
    """Announce ``LISTENING <host> <port>`` and serve until
    SIGTERM/SIGINT — the one serve loop behind both ``python -m
    testground_tpu.sync.server`` and ``tg sync-service``. ``svc`` is any
    backend exposing ``.address``/``.stop()``."""
    import signal
    import sys

    host, port = svc.address
    print(f"LISTENING {host} {port}", flush=True)

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    svc.stop()
    print("sync service stopped", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
