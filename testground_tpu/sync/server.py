"""TCP sync service.

The ``local:exec`` runner's infra piece: the analog of the reference's
Redis-backed sync-service container (``pkg/runner/local_common.go:77-104``),
implemented as a newline-delimited-JSON TCP server over
:class:`InMemSyncService`.

Wire protocol (one JSON object per line):

    request:  {"id": N, "op": <op>, ...args}
    reply:    {"id": N, ...result}            exactly one, except:
    subscribe streams {"id": N, "entry": payload, "seq": i} frames until the
    connection closes.

Ops: ``signal_entry(state)``, ``barrier(state, target)``,
``signal_and_wait(state, target)``, ``publish(topic, payload)``,
``subscribe(topic)``, ``counter(state)``.

This Python server is the behavioral spec; a wire-compatible native C++
event-loop implementation lives at ``testground_tpu/native/syncsvc.cc``
and is what the local:exec runner boots by default when a toolchain is
available (runner config ``sync_service``, default "auto"). Either
comfortably covers the local:exec envelope (2-300 real processes,
``README.md:136-139`` — the at-scale path is the on-device sync kernel,
not these servers).
"""

from __future__ import annotations

import json
import socketserver
import threading

from testground_tpu.logging_ import S

from .inmem import InMemSyncService

__all__ = ["SyncServiceServer"]


class _Handler(socketserver.StreamRequestHandler):
    daemon_threads = True

    def handle(self) -> None:
        svc: InMemSyncService = self.server.service  # type: ignore[attr-defined]
        stop: threading.Event = self.server.stop_event  # type: ignore[attr-defined]
        write_lock = threading.Lock()
        pending: list[threading.Thread] = []

        def reply(obj: dict) -> None:
            data = (json.dumps(obj) + "\n").encode("utf-8")
            try:
                with write_lock:
                    self.wfile.write(data)
                    self.wfile.flush()
            except (BrokenPipeError, OSError):
                pass

        def run_async(fn, req_id: int) -> None:
            def runner():
                try:
                    fn()
                except TimeoutError as e:
                    reply({"id": req_id, "error": str(e)})
                except InterruptedError:
                    pass
                except Exception as e:  # noqa: BLE001
                    reply({"id": req_id, "error": str(e)})

            t = threading.Thread(target=runner, daemon=True)
            t.start()
            pending.append(t)

        try:
            for raw in self.rfile:
                try:
                    req = json.loads(raw)
                except json.JSONDecodeError:
                    reply({"id": -1, "error": "malformed request"})
                    continue
                rid = req.get("id", -1)
                op = req.get("op")
                try:
                    if op == "signal_entry":
                        reply({"id": rid, "seq": svc.signal_entry(req["state"])})
                    elif op == "counter":
                        reply({"id": rid, "count": svc.counter(req["state"])})
                    elif op == "publish":
                        reply(
                            {"id": rid, "seq": svc.publish(req["topic"], req["payload"])}
                        )
                    elif op == "barrier":

                        def do_barrier(rid=rid, req=req):
                            svc.barrier(
                                req["state"],
                                int(req["target"]),
                                timeout=req.get("timeout"),
                                cancel=stop,
                            )
                            reply({"id": rid, "ok": True})

                        run_async(do_barrier, rid)
                    elif op == "signal_and_wait":

                        def do_sw(rid=rid, req=req):
                            seq = svc.signal_entry(req["state"])
                            svc.barrier(
                                req["state"],
                                int(req["target"]),
                                timeout=req.get("timeout"),
                                cancel=stop,
                            )
                            reply({"id": rid, "seq": seq, "ok": True})

                        run_async(do_sw, rid)
                    elif op == "subscribe":

                        def do_sub(rid=rid, req=req):
                            for i, entry in enumerate(
                                svc.subscribe(req["topic"], cancel=stop)
                            ):
                                reply({"id": rid, "entry": entry, "seq": i + 1})

                        run_async(do_sub, rid)
                    else:
                        reply({"id": rid, "error": f"unknown op {op!r}"})
                except KeyError as e:
                    reply({"id": rid, "error": f"missing field {e}"})
        except (ConnectionResetError, OSError):
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class SyncServiceServer:
    """Lifecycle wrapper; bind to an ephemeral port with ``port=0``."""

    def __init__(self, service: InMemSyncService | None = None, port: int = 0):
        self.service = service or InMemSyncService()
        self._server = _Server(("127.0.0.1", port), _Handler)
        self._server.service = self.service  # type: ignore[attr-defined]
        self._server.stop_event = threading.Event()  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address  # type: ignore[return-value]

    def start(self) -> "SyncServiceServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="tg-sync-service"
        )
        self._thread.start()
        S().debug("sync service listening on %s:%d", *self.address)
        return self

    def stop(self) -> None:
        self._server.stop_event.set()  # type: ignore[attr-defined]
        # wake blocked barriers/subscribers so handler threads exit
        with self.service._lock:
            self.service._lock.notify_all()
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=2)
