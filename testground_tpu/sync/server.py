"""TCP sync service.

The ``local:exec`` runner's infra piece: the analog of the reference's
Redis-backed sync-service container (``pkg/runner/local_common.go:77-104``),
implemented as a newline-delimited-JSON TCP server over
:class:`InMemSyncService`.

Wire protocol (one JSON object per line):

    request:  {"id": N, "op": <op>, ...args}
    reply:    {"id": N, ...result}            exactly one, except:
    subscribe streams {"id": N, "entry": payload, "seq": i} frames until the
    connection closes.

Ops: ``signal_entry(state[, token])``, ``barrier(state, target)``,
``signal_and_wait(state, target[, token])``, ``publish(topic, payload[,
token])``, ``subscribe(topic)``, ``counter(state)``, plus the liveness/
identity plane (docs/CROSSHOST.md):

- ``ping`` → ``{"pong": true, "boot": <id>}`` — heartbeat + boot-id probe
  (a changed boot id tells a reconnecting client the service restarted
  and lost its state);
- ``hello(events_topic, group, instance)`` — registers the connection's
  instance identity; an ABNORMAL disconnect (anything but ``bye``)
  publishes ``{"type": "evicted", ...}`` to ``events_topic`` so runners
  and surviving instances observe the death;
- ``bye`` — clean-shutdown marker (no eviction event);
- ``sync_stats`` → the wire-versioned stats plane (v2, ``"v": 2``):
  the v1 live-occupancy fields ``{"conns", "waiters", "subs"}`` (the
  observable that pins "a dead client's barrier occupancy is
  released") plus per-op counters, service-time log2 histograms,
  barrier lifecycle timing, pubsub depth/high-water marks, connection
  churn and idempotency-dedup hit counts (``sync/stats.py``,
  docs/INSTANCE_PROTOCOL.md §4.2).

``token`` is an idempotency key: reconnecting clients re-send unacked
mutations with the original token and the service replies with the
original seq instead of mutating twice.

The server binds ``host`` (default loopback; ``0.0.0.0`` opens it to
other hosts — the ``cluster_k8s.go:302`` network-citizen analog) and,
when ``idle_timeout`` is set, sweeps connections that have sent nothing
(not even a heartbeat) for that long: a SIGSTOPped or half-open peer is
evicted, its parked barrier/subscribe waiters released, and its eviction
published, rather than leaking occupancy forever.

This Python server is the behavioral spec; a wire-compatible native C++
event-loop implementation lives at ``testground_tpu/native/syncsvc.cc``
and is what the local:exec runner boots by default when a toolchain is
available (runner config ``sync_service``, default "auto"). Either
comfortably covers the local:exec envelope (2-300 real processes,
``README.md:136-139`` — the at-scale path is the on-device sync kernel,
not these servers).

Runnable standalone (the cross-host deployment unit, also wrapped by
``tg sync-service``)::

    python -m testground_tpu.sync.server --host 0.0.0.0 --port 9042

prints ``LISTENING <host> <port>`` once bound and serves until
SIGTERM/SIGINT.
"""

from __future__ import annotations

import json
import socketserver
import threading
import time
import uuid

from testground_tpu.logging_ import S

from .inmem import InMemSyncService
from .stats import SyncStats

__all__ = ["SyncServiceServer"]


class _AnyEvent:
    """is_set() over several events — lets inmem waits observe both the
    server-wide stop and the per-connection eviction."""

    def __init__(self, *events: threading.Event):
        self._events = events

    def is_set(self) -> bool:
        return any(e.is_set() for e in self._events)


class _Handler(socketserver.StreamRequestHandler):
    daemon_threads = True

    def setup(self) -> None:
        super().setup()
        self.last_activity = time.monotonic()
        self.conn_cancel = threading.Event()
        self.hello: dict | None = None
        self.clean = False
        with self.server.conns_lock:  # type: ignore[attr-defined]
            self.server.conns.add(self)  # type: ignore[attr-defined]
        st: SyncStats | None = self.server.stats  # type: ignore[attr-defined]
        if st is not None:
            st.conn_open()

    def finish(self) -> None:
        with self.server.conns_lock:  # type: ignore[attr-defined]
            self.server.conns.discard(self)  # type: ignore[attr-defined]
        st: SyncStats | None = self.server.stats  # type: ignore[attr-defined]
        if st is not None:
            st.conn_close()
        super().finish()

    def evict(self) -> None:
        """Server-side eviction (idle sweep / stop): release parked
        waiters and unblock the read loop."""
        st: SyncStats | None = self.server.stats  # type: ignore[attr-defined]
        if st is not None:
            st.conn_evicted()
        self.conn_cancel.set()
        svc: InMemSyncService = self.server.service  # type: ignore[attr-defined]
        with svc._lock:
            svc._lock.notify_all()
        try:
            self.connection.shutdown(2)  # SHUT_RDWR: EOFs the read loop
        except OSError:
            pass

    def handle(self) -> None:
        svc: InMemSyncService = self.server.service  # type: ignore[attr-defined]
        stop: threading.Event = self.server.stop_event  # type: ignore[attr-defined]
        occupancy = self.server.occupancy  # type: ignore[attr-defined]
        stats: SyncStats | None = self.server.stats  # type: ignore[attr-defined]
        cancel = _AnyEvent(stop, self.conn_cancel)
        write_lock = threading.Lock()
        pending: list[threading.Thread] = []

        def reply(obj: dict) -> None:
            data = (json.dumps(obj) + "\n").encode("utf-8")
            try:
                with write_lock:
                    self.wfile.write(data)
                    self.wfile.flush()
            except (BrokenPipeError, OSError):
                pass

        def run_async(fn, req_id: int, kind: str, op: str) -> None:
            # service time for parked ops is measured around fn() — for
            # barrier/signal_and_wait that is the full fan-in wait, the
            # latency a client actually observes (subscribe streams
            # until disconnect, so only its registration is timed, at
            # the dispatch site)
            timed = stats is not None and op in ("barrier", "signal_and_wait")
            def runner():
                t0 = time.perf_counter()
                with occupancy.held(kind):
                    try:
                        fn()
                        if timed:
                            stats.time_op(
                                op, (time.perf_counter() - t0) * 1e6
                            )
                    except TimeoutError as e:
                        reply({"id": req_id, "error": str(e)})
                    except InterruptedError:
                        pass
                    except Exception as e:  # noqa: BLE001
                        reply({"id": req_id, "error": str(e)})

            t = threading.Thread(target=runner, daemon=True)
            t.start()
            pending.append(t)

        boot = self.server.boot_id  # type: ignore[attr-defined]
        # hot-path hoists: one bound-method lookup per CONNECTION, not
        # per op (the instrumented-vs-uninstrumented A/B budget is <5%)
        perf = time.perf_counter
        op_done = stats.op_done if stats is not None else None
        try:
            for raw in self.rfile:
                self.last_activity = time.monotonic()
                try:
                    req = json.loads(raw)
                except json.JSONDecodeError:
                    reply({"id": -1, "error": "malformed request"})
                    continue
                rid = req.get("id", -1)
                op = req.get("op")
                t_op = perf()
                out: dict | None = None
                try:
                    if op == "signal_entry":
                        out = {
                            "id": rid,
                            "seq": svc.signal_entry(
                                req["state"], token=req.get("token")
                            ),
                        }
                    elif op == "counter":
                        out = {"id": rid, "count": svc.counter(req["state"])}
                    elif op == "publish":
                        out = {
                            "id": rid,
                            "seq": svc.publish(
                                req["topic"],
                                req["payload"],
                                token=req.get("token"),
                            ),
                        }
                    elif op == "ping":
                        out = {"id": rid, "pong": True, "boot": boot}
                    elif op == "hello":
                        hello = {
                            "events_topic": req.get("events_topic", ""),
                            "group": req.get("group", ""),
                            "instance": req.get("instance", -1),
                        }
                        _ident_retag(self.server, self.hello, hello)
                        self.hello = hello
                        out = {"id": rid, "ok": True, "boot": boot}
                    elif op == "bye":
                        self.clean = True
                        out = {"id": rid, "ok": True}
                    elif op == "sync_stats":
                        with self.server.conns_lock:  # type: ignore[attr-defined]
                            n_conns = len(self.server.conns)  # type: ignore[attr-defined]
                        payload = {
                            "id": rid,
                            "conns": n_conns,
                            "waiters": occupancy.waiters,
                            "subs": occupancy.subs,
                            "boot": boot,
                        }
                        if stats is not None:  # v2: v1 fields preserved
                            # count itself BEFORE snapshotting so the
                            # reply includes this very query — the
                            # conservation accounting the smoke pins
                            stats.op_done(
                                op, (time.perf_counter() - t_op) * 1e6
                            )
                            topics, entries = svc.pubsub_gauges()
                            payload.update(
                                stats.snapshot(
                                    topics=topics, entries=entries
                                )
                            )
                        reply(payload)
                    elif op == "barrier":

                        def do_barrier(rid=rid, req=req):
                            svc.barrier(
                                req["state"],
                                int(req["target"]),
                                timeout=req.get("timeout"),
                                cancel=cancel,
                            )
                            reply({"id": rid, "ok": True})

                        if stats is not None:  # parked ops count at dispatch
                            stats.count_op(op)
                        run_async(do_barrier, rid, "waiters", "barrier")
                    elif op == "signal_and_wait":

                        def do_sw(rid=rid, req=req):
                            seq = svc.signal_entry(
                                req["state"], token=req.get("token")
                            )
                            svc.barrier(
                                req["state"],
                                int(req["target"]),
                                timeout=req.get("timeout"),
                                cancel=cancel,
                            )
                            reply({"id": rid, "seq": seq, "ok": True})

                        if stats is not None:
                            stats.count_op(op)
                        run_async(do_sw, rid, "waiters", "signal_and_wait")
                    elif op == "subscribe":

                        def do_sub(rid=rid, req=req):
                            for i, entry in enumerate(
                                svc.subscribe(req["topic"], cancel=cancel)
                            ):
                                reply({"id": rid, "entry": entry, "seq": i + 1})

                        if stats is not None:
                            stats.op_done(
                                "subscribe",
                                (time.perf_counter() - t_op) * 1e6,
                            )
                        run_async(do_sub, rid, "subs", "subscribe")
                    else:
                        reply({"id": rid, "error": f"unknown op {op!r}"})
                    if out is not None:
                        if op_done is not None:
                            op_done(op, (perf() - t_op) * 1e6)
                        reply(out)
                except KeyError as e:
                    # the op still counts: the native server counts at
                    # dispatch before field extraction, so a malformed
                    # request must not diverge the backends' op counters
                    if stats is not None and out is None:
                        stats.count_op(op)
                    reply({"id": rid, "error": f"missing field {e}"})
        except (ConnectionResetError, OSError):
            pass
        finally:
            # connection gone (EOF, reset, or eviction): release this
            # connection's parked waiters/subscriptions promptly —
            # occupancy must not outlive the client
            self.conn_cancel.set()
            with svc._lock:
                svc._lock.notify_all()
            if self.hello and not stop.is_set():
                _note_disconnect(self.server, self.hello, self.clean)
            for t in pending:
                t.join(timeout=2)


def _ident_key(hello: dict) -> tuple:
    return (
        hello.get("events_topic", ""),
        hello.get("group", ""),
        hello.get("instance", -1),
    )


def _ident_retag(server, old: dict | None, new: dict) -> None:
    """Track live connection count per instance identity (hello)."""
    with server.ident_lock:
        if old is not None:
            k = _ident_key(old)
            n = server.identities.get(k, 0) - 1
            if n <= 0:
                server.identities.pop(k, None)
            else:
                server.identities[k] = n
        k = _ident_key(new)
        server.identities[k] = server.identities.get(k, 0) + 1


def _note_disconnect(server, hello: dict, clean: bool) -> None:
    """Identity bookkeeping + GRACE-windowed eviction: an abnormal
    disconnect only becomes an ``evicted`` event if no connection with
    the same identity is back within ``evict_grace`` seconds — a client
    dropping its socket to RECONNECT (heartbeat force-close, partition
    heal) must not be announced dead to the run."""
    key = _ident_key(hello)
    with server.ident_lock:
        n = server.identities.get(key, 0) - 1
        if n <= 0:
            server.identities.pop(key, None)
        else:
            server.identities[key] = n
    if clean or n > 0 or not hello.get("events_topic"):
        return

    def fire() -> None:
        if server.stop_event.is_set():
            return
        with server.ident_lock:
            if server.identities.get(key, 0) > 0:
                return  # the instance came back inside the grace window
        try:
            server.service.publish(
                hello["events_topic"],
                {
                    "type": "evicted",
                    "group": hello.get("group", ""),
                    "instance": hello.get("instance", -1),
                    "error": "connection lost (killed, partitioned, or "
                    "idle-evicted)",
                },
            )
        except Exception:  # noqa: BLE001 — eviction is best-effort
            pass

    grace = float(getattr(server, "evict_grace", 0.0))
    if grace <= 0:
        fire()
        return
    t = threading.Timer(grace, fire)
    t.daemon = True
    t.start()


class _Occupancy:
    """Live waiter/subscriber accounting exposed via ``sync_stats``."""

    def __init__(self, stats: SyncStats | None = None):
        self._lock = threading.Lock()
        self.stats = stats
        self.waiters = 0
        self.subs = 0

    def held(self, kind: str):
        occ = self

        class _Held:
            def __enter__(self):
                with occ._lock:
                    setattr(occ, kind, getattr(occ, kind) + 1)
                    w, s = occ.waiters, occ.subs
                if occ.stats is not None:  # high-water marks
                    occ.stats.note_occupancy(w, s)

            def __exit__(self, *exc):
                with occ._lock:
                    setattr(occ, kind, getattr(occ, kind) - 1)
                return False

        return _Held()


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    # socketserver's default listen backlog is 5 — a fan-in connect
    # storm (tools/bench_sync_fanin.py drives 1k-10k concurrent
    # clients) overflows that instantly and turns into SYN retransmit
    # stalls; match the native server's listen(1024) depth
    request_queue_size = 1024


class SyncServiceServer:
    """Lifecycle wrapper; bind to an ephemeral port with ``port=0``.

    ``host`` is the bind address (default loopback — pass ``"0.0.0.0"``
    to serve other hosts); ``idle_timeout`` (seconds, 0 = disabled)
    evicts connections that have been silent for that long. Heartbeating
    clients (the SDK's default) are never idle while alive, so only
    dead/partitioned peers trip the sweep.
    """

    def __init__(
        self,
        service: InMemSyncService | None = None,
        port: int = 0,
        host: str = "127.0.0.1",
        idle_timeout: float = 0.0,
        evict_grace: float = 2.0,
        stats: bool = True,
    ):
        self.service = service or InMemSyncService()
        self.idle_timeout = float(idle_timeout)
        # the sync-plane stats recorder (always on by default — it is
        # python-int adds; stats=False exists for the fan-in bench's
        # instrumented-vs-uninstrumented A/B and doubles as the old-
        # server emulation for client version-tolerance tests: with it
        # off, sync_stats answers the v1 shape, no "v" field)
        self.stats: SyncStats | None = SyncStats() if stats else None
        self.service.stats = self.stats
        self._server = _Server((host, port), _Handler)
        self._server.service = self.service  # type: ignore[attr-defined]
        self._server.stats = self.stats  # type: ignore[attr-defined]
        self._server.stop_event = threading.Event()  # type: ignore[attr-defined]
        self._server.conns = set()  # type: ignore[attr-defined]
        self._server.conns_lock = threading.Lock()  # type: ignore[attr-defined]
        self._server.occupancy = _Occupancy(self.stats)  # type: ignore[attr-defined]
        self._server.boot_id = uuid.uuid4().hex  # type: ignore[attr-defined]
        # hello'd-identity → live connection count; disconnects below a
        # count of zero arm the evict_grace timer (see _note_disconnect)
        self._server.identities = {}  # type: ignore[attr-defined]
        self._server.ident_lock = threading.Lock()  # type: ignore[attr-defined]
        self._server.evict_grace = float(evict_grace)  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None
        self._sweeper: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address  # type: ignore[return-value]

    @property
    def boot_id(self) -> str:
        return self._server.boot_id  # type: ignore[attr-defined]

    def start(self) -> "SyncServiceServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="tg-sync-service"
        )
        self._thread.start()
        if self.idle_timeout > 0:
            self._sweeper = threading.Thread(
                target=self._sweep_loop, daemon=True, name="tg-sync-sweep"
            )
            self._sweeper.start()
        S().debug("sync service listening on %s:%d", *self.address)
        return self

    def _sweep_loop(self) -> None:
        stop: threading.Event = self._server.stop_event  # type: ignore[attr-defined]
        interval = max(0.1, self.idle_timeout / 4.0)
        while not stop.wait(interval):
            now = time.monotonic()
            with self._server.conns_lock:  # type: ignore[attr-defined]
                stale = [
                    h
                    for h in self._server.conns  # type: ignore[attr-defined]
                    if now - h.last_activity > self.idle_timeout
                ]
            for h in stale:
                S().debug(
                    "sync service: evicting idle connection (%.1fs silent)",
                    now - h.last_activity,
                )
                h.evict()

    def stop(self) -> None:
        self._server.stop_event.set()  # type: ignore[attr-defined]
        # wake blocked barriers/subscribers so handler threads exit
        with self.service._lock:
            self.service._lock.notify_all()
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=2)
        if self._sweeper:
            self._sweeper.join(timeout=2)


def _main(argv: list[str] | None = None) -> int:
    """``python -m testground_tpu.sync.server``: the standalone,
    cross-host deployment unit (also behind ``tg sync-service``)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="testground_tpu.sync.server",
        description="standalone sync service (JSON-lines TCP)",
    )
    ap.add_argument("--host", default="127.0.0.1", help="bind address")
    ap.add_argument("--port", type=int, default=0, help="bind port (0=ephemeral)")
    ap.add_argument(
        "--idle-timeout",
        type=float,
        default=0.0,
        help="evict connections silent for this many seconds (0=off)",
    )
    ap.add_argument(
        "--evict-grace",
        type=float,
        default=2.0,
        help="window an abnormally-disconnected instance has to "
        "reconnect before its eviction is published (0=immediate)",
    )
    ap.add_argument(
        "--no-stats",
        action="store_true",
        help="disable the sync-stats plane (sync_stats answers the v1 "
        "occupancy shape) — exists for the fan-in bench's "
        "instrumented-vs-uninstrumented A/B, not for production",
    )
    args = ap.parse_args(argv)

    srv = SyncServiceServer(
        port=args.port,
        host=args.host,
        idle_timeout=args.idle_timeout,
        evict_grace=args.evict_grace,
        stats=not args.no_stats,
    ).start()
    return serve_until_signal(srv)


def serve_until_signal(svc) -> int:
    """Announce ``LISTENING <host> <port>`` and serve until
    SIGTERM/SIGINT — the one serve loop behind both ``python -m
    testground_tpu.sync.server`` and ``tg sync-service``. ``svc`` is any
    backend exposing ``.address``/``.stop()``."""
    import signal
    import sys

    host, port = svc.address
    print(f"LISTENING {host} {port}", flush=True)

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    svc.stop()
    print("sync service stopped", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
