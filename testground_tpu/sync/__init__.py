"""Coordination service: Signal/Barrier/Publish/Subscribe primitives.

Twin of the reference's external sync service (Redis-backed
``iptestground/sync-service`` consumed through sdk-go — SURVEY.md §2.6):

- :class:`InMemSyncService` — in-process implementation, the functional twin
  of ``sync.NewInmemClient()`` (``pkg/sidecar/mock.go``); shared by unit
  tests and the ``sim:jax`` runner's host-side coordination.
- :class:`SyncServiceServer` — TCP JSON-lines server exposing the same
  primitives to real-process instances (the ``local:exec`` runner's infra).
- :class:`SyncClient` — blocking socket client used by the SDK inside
  instances.

Event streams (instance lifecycle Success/Failure/Crash consumed by runners
via ``SubscribeEvents``) ride the same pub/sub as a reserved per-run topic.
"""

from .addr import advertise_host, parse_hostport
from .errors import SyncLostError
from .inmem import InMemSyncService
from .client import SyncClient, SyncRetry
from .server import SyncServiceServer

__all__ = [
    "InMemSyncService",
    "SyncClient",
    "SyncLostError",
    "SyncRetry",
    "SyncServiceServer",
    "advertise_host",
    "parse_hostport",
]

# Reserved topic carrying instance lifecycle events for a run; the runner
# subscribes to it to collect outcomes (``local_docker.go:217-256``).
RUN_EVENTS_TOPIC = "__run_events__"
