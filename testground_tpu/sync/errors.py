"""Typed sync-plane errors.

The failure-hardening contract (docs/CROSSHOST.md): a client whose
connection to the sync service cannot be (re)established within the
configured attempt/deadline budget raises :class:`SyncLostError` — a
typed, catchable signal that the host-side control plane is gone —
instead of hanging a barrier or pub/sub waiter indefinitely.
"""

from __future__ import annotations

__all__ = ["SyncLostError"]


class SyncLostError(ConnectionError):
    """The sync service is unreachable (or restarted and lost its state)
    and the client's reconnect budget is exhausted.

    Carries the service address and the attempt history so operators can
    tell *which* endpoint died from the message alone. Classified as
    cohort-fatal by ``sim/cohort.py`` — losing the coordination plane
    poisons a cross-host run the same way a dead ``jax.distributed``
    member does.
    """

    def __init__(
        self,
        message: str,
        *,
        address: tuple[str, int] | None = None,
        attempts: int = 0,
        elapsed_secs: float = 0.0,
    ):
        super().__init__(message)
        self.address = address
        self.attempts = attempts
        self.elapsed_secs = elapsed_secs
