"""The one sync-service backend-selection policy.

Both consumers of "start me a sync service" — the ``local:exec``
runner's per-run server and the standalone ``tg sync-service`` — boot
through this helper, so the auto/native/python selection, the toolchain
probe, and the fallback semantics cannot diverge.
"""

from __future__ import annotations

from typing import Callable

__all__ = ["boot_sync_service"]


def boot_sync_service(
    mode: str,
    host: str,
    port: int,
    idle_timeout: float,
    evict_grace: float,
    bin_dir: str,
    log: Callable[[str], None] | None = None,
    shards: int = 0,
):
    """Start a sync service and return it (``.address`` / ``.stop()``).

    ``mode``: ``"native"`` = the C++ event-loop server (built on demand
    into ``bin_dir``), ``"python"`` = the in-process server, ``"auto"``
    = native when a toolchain is available, falling back to python with
    a ``log`` notice. A forced native mode raises instead of falling
    back. ``shards`` is the event-loop count (0 = backend auto:
    native picks min(4, cores), python runs one loop)."""
    if mode not in ("auto", "python", "native"):
        raise ValueError(f"unknown sync_service mode {mode!r}")
    if mode in ("auto", "native"):
        from testground_tpu.native import (
            NativeSyncService,
            build_syncsvc,
            native_available,
        )

        if native_available():
            try:
                path = build_syncsvc(bin_dir)
                svc = NativeSyncService(
                    path,
                    host=host,
                    port=port,
                    idle_timeout=idle_timeout,
                    evict_grace=evict_grace,
                    shards=shards,
                )
                if log:
                    log(f"sync service: native ({path})")
                return svc
            except Exception as e:  # noqa: BLE001 — auto falls back
                if mode == "native":
                    raise
                if log:
                    log(
                        f"native sync service unavailable ({e}); "
                        "falling back to python"
                    )
        elif mode == "native":
            raise RuntimeError(
                "sync_service='native' but no C++ toolchain (g++) found"
            )
    from .server import SyncServiceServer

    return SyncServiceServer(
        host=host,
        port=port,
        idle_timeout=idle_timeout,
        evict_grace=evict_grace,
        shards=max(1, shards),
    ).start()
