"""Sync-service address plumbing for cross-host runs.

The reference injects the sync service's cluster-reachable address into
every pod (``cluster_k8s.go:302``); the local analog needs two small
pieces of address logic:

- :func:`parse_hostport` — split the ``host:port`` strings runner
  configs declare (``sync_service_address = "10.0.0.5:9042"``);
- :func:`advertise_host` — turn a *bind* host into the address other
  hosts should *dial*: binding ``0.0.0.0`` (all interfaces) must not
  advertise ``0.0.0.0`` to instances on another machine.
"""

from __future__ import annotations

import socket

__all__ = ["advertise_host", "parse_hostport"]

# bind hosts that mean "all interfaces" and are therefore not dialable
WILDCARD_HOSTS = ("", "0.0.0.0", "::")


def parse_hostport(address: str, default_port: int = 0) -> tuple[str, int]:
    """``"host:port"`` → ``(host, port)``; a bare host gets
    ``default_port``. Refuses empty hosts loudly."""
    address = address.strip()
    host, sep, port_s = address.rpartition(":")
    if not sep:
        host, port_s = address, ""
    if not host:
        raise ValueError(f"sync service address {address!r} has no host")
    if port_s:
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(
                f"sync service address {address!r} has a non-numeric port"
            ) from None
    else:
        port = default_port
    if not 0 <= port <= 65535:
        raise ValueError(f"sync service address {address!r}: bad port {port}")
    return host, port


def advertise_host(bind_host: str, explicit: str = "") -> str:
    """The host other machines should dial for a service bound to
    ``bind_host``. An ``explicit`` advertise host (runner config) always
    wins; a concrete bind host advertises itself; a wildcard bind
    resolves this machine's primary outbound interface (the UDP-connect
    trick — no packet is sent), falling back to loopback when the host
    has no route at all."""
    if explicit:
        return explicit
    if bind_host not in WILDCARD_HOSTS:
        return bind_host
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # RFC1918: never actually sent
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
