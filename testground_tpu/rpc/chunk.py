"""Chunk wire format (``pkg/rpc/chunk.go``).

For a given request, clients should expect 0..n ``progress`` chunks and
exactly one ``result`` or ``error`` chunk before EOF. Binary payloads are
base64-encoded strings.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Iterator

CHUNK_PROGRESS = "p"
CHUNK_BINARY = "b"
CHUNK_RESULT = "r"
CHUNK_ERROR = "e"


@dataclass
class Chunk:
    type: str
    payload: Any = None
    error: str | None = None

    def to_json(self) -> str:
        d: dict = {"t": self.type}
        if self.payload is not None:
            d["p"] = self.payload
        if self.error is not None:
            d["e"] = {"m": self.error}
        return json.dumps(d)

    @classmethod
    def from_json(cls, line: str) -> "Chunk":
        d = json.loads(line)
        err = d.get("e")
        return cls(
            type=d["t"],
            payload=d.get("p"),
            error=err["m"] if err else None,
        )


def parse_chunks(stream) -> Iterator[Chunk]:
    """Parse newline-delimited chunks from a text-line iterable."""
    for line in stream:
        if isinstance(line, bytes):
            line = line.decode("utf-8")
        line = line.strip()
        if line:
            yield Chunk.from_json(line)
