"""Streaming response protocol between daemon and client.

Twin of the reference's ``pkg/rpc``: newline-delimited JSON chunks typed
``p`` (progress), ``b`` (binary, base64), ``r`` (result), ``e`` (error).
"""

from .chunk import (
    CHUNK_BINARY,
    CHUNK_ERROR,
    CHUNK_PROGRESS,
    CHUNK_RESULT,
    Chunk,
    parse_chunks,
)
from .writer import OutputWriter, discard_writer

__all__ = [
    "CHUNK_BINARY",
    "CHUNK_ERROR",
    "CHUNK_PROGRESS",
    "CHUNK_RESULT",
    "Chunk",
    "OutputWriter",
    "discard_writer",
    "parse_chunks",
]
