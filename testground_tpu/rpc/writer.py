"""OutputWriter: simultaneously a logger and a chunk emitter
(``pkg/rpc/writer.go``).

Progress output (human log lines) is emitted as ``p`` chunks; binary streams
(e.g. collected-outputs tarballs) as base64 ``b`` chunks; and the terminal
result/error as a single ``r``/``e`` chunk.
"""

from __future__ import annotations

import base64
import json
import threading
from typing import Any, BinaryIO, TextIO

from .chunk import CHUNK_BINARY, CHUNK_ERROR, CHUNK_PROGRESS, CHUNK_RESULT

__all__ = ["OutputWriter", "discard_writer"]


class OutputWriter:
    """Thread-safe chunked writer.

    ``sink`` is a text stream receiving newline-delimited JSON chunks (an HTTP
    response body or a file). ``echo`` optionally mirrors progress lines to a
    local console stream.
    """

    def __init__(self, sink: TextIO | None, echo: TextIO | None = None):
        self._sink = sink
        self._echo = echo
        self._lock = threading.Lock()

    def _emit(self, obj: dict) -> None:
        if self._sink is None:
            return
        with self._lock:
            self._sink.write(json.dumps(obj) + "\n")
            self._sink.flush()

    # ------------------------------------------------------------- log-style

    def _log(self, level: str, msg: str, *args: Any) -> None:
        text = (msg % args) if args else msg
        if self._echo is not None:
            with self._lock:
                self._echo.write(text + "\n")
                self._echo.flush()
        self._emit({"t": CHUNK_PROGRESS, "p": f"{text}\n"})

    def info(self, msg: str, *args: Any) -> None:
        self._log("info", msg, *args)

    def infof(self, msg: str, *args: Any) -> None:
        self._log("info", msg, *args)

    def warn(self, msg: str, *args: Any) -> None:
        self._log("warn", msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self._log("error", msg, *args)

    def debug(self, msg: str, *args: Any) -> None:
        self._log("debug", msg, *args)

    # -------------------------------------------------------------- chunk API

    def write_progress(self, data: str) -> None:
        self._emit({"t": CHUNK_PROGRESS, "p": data})

    def write_binary(self, reader: BinaryIO, chunk_size: int = 1 << 16) -> None:
        """Stream binary data as base64 ``b`` chunks (``writer.go`` binary
        writer)."""
        while True:
            buf = reader.read(chunk_size)
            if not buf:
                break
            self._emit(
                {"t": CHUNK_BINARY, "p": base64.b64encode(buf).decode("ascii")}
            )

    def write_result(self, result: Any) -> None:
        self._emit({"t": CHUNK_RESULT, "p": result})

    def write_error(self, msg: str) -> None:
        self._emit({"t": CHUNK_ERROR, "e": {"m": msg}})


def discard_writer() -> OutputWriter:
    """An OutputWriter that drops everything (``rpc.Discard()``)."""
    return OutputWriter(sink=None, echo=None)
