"""``local:exec`` runner: one OS process per instance on this host.

Twin of the reference's ``pkg/runner/local_exec.go``: spawns one process per
instance with the RunParams env-var contract, no network dataplane
(``TestSidecar=false``, ``local_exec.go:89``), subnet ``127.1.0.0/16``
(``local_exec.go:32``), stdout parsed by the PrettyPrinter, outcomes
collected from sync-service events. The sync-service "infra container"
(``local_common.go:77-104``) is an in-process TCP server started per run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass

from testground_tpu.api import RunInput, RunOutput
from testground_tpu.engine.task import Outcome
from testground_tpu.rpc import OutputWriter
from testground_tpu.sdk.runparams import RunParams
from testground_tpu.sync import RUN_EVENTS_TOPIC

from .base import HealthcheckedRunner, Runner, Terminatable
from .outputs import instance_output_dir
from .pretty import PrettyPrinter
from .result import Result

__all__ = ["LocalExecRunner"]

DEFAULT_SUBNET = "127.1.0.0/16"  # local_exec.go:32
OUTCOME_COLLECTION_TIMEOUT = 45.0  # local_docker.go:94
START_CONCURRENCY = 16  # local_docker.go:512

# terminal lifecycle event types an instance publishes itself; a
# server-side "evicted" event never overrides one of these
_TERMINAL_EVENTS = ("success", "failure", "crash")


class _ExternalSyncService:
    """Address-only handle on a sync service another host (or a
    standalone ``tg sync-service``) owns; lifecycle is not ours."""

    def __init__(self, address: tuple[str, int]):
        self.address = address

    def start(self):
        return self

    def stop(self) -> None:  # the owner stops it
        pass


@dataclass
class LocalExecConfig:
    """Runner config (coalesced from manifest/.env.toml/composition)."""

    keep_outputs: bool = True
    run_timeout_secs: int = 0  # 0 ⇒ rely on task timeout
    # per-run sync service backend: "native" = the C++ event-loop server
    # (testground_tpu/native/syncsvc.cc, built on demand), "python" = the
    # in-process server, "auto" = native when a toolchain is available
    sync_service: str = "auto"
    # --- cross-host sync plane (docs/CROSSHOST.md) -----------------------
    # bind address for the per-run sync service; the loopback default
    # keeps single-host runs private, "0.0.0.0" makes the service a
    # network citizen other hosts can join (cluster_k8s.go:302 analog)
    sync_bind_host: str = "127.0.0.1"
    # what instances (possibly on other hosts) should DIAL; empty =
    # derived from the bind host (a wildcard bind advertises this
    # machine's primary interface)
    sync_advertise_host: str = ""
    # "host:port" of an EXTERNAL sync service (e.g. `tg sync-service` on
    # another host); when set this runner starts no server of its own —
    # the run joins the shared coordination plane by address
    sync_service_address: str = ""
    # client failure budget injected into instances via RunParams
    sync_connect_timeout_secs: float = 30.0
    sync_retry_attempts: int = 8
    sync_retry_deadline_secs: float = 60.0
    sync_heartbeat_secs: float = 5.0
    # server-side liveness: evict connections silent for this long (a
    # heartbeating client is never idle), releasing their barrier/
    # subscribe occupancy; 0 disables the sweep
    sync_idle_timeout_secs: float = 30.0
    # window an abnormally-disconnected instance has to reconnect before
    # its eviction event is published (reconnects are not deaths)
    sync_evict_grace_secs: float = 2.0
    # event-loop shards for the per-run sync server (0 = backend auto:
    # native picks min(4, cores), python runs one loop — see
    # docs/CROSSHOST.md "Server architecture")
    sync_shards: int = 0


class LocalExecRunner(Runner, HealthcheckedRunner, Terminatable):
    def id(self) -> str:
        return "local:exec"

    def compatible_builders(self) -> list[str]:
        # local_exec.go:197 (exec:go in the reference); exec:bin is the
        # any-language path — the instance protocol, not a Python SDK,
        # is the contract
        return ["exec:py", "exec:bin"]

    def config_type(self) -> type:
        return LocalExecConfig

    def healthcheck(self, fix: bool, ow: OutputWriter, env=None):
        """Real environment checks with fixers — the analog of the
        reference's infra healthcheck (``local_exec.go:49-72``), minus the
        external containers: this runner's infra is the directory layout,
        a bindable port for the per-run sync service, and a working
        python to exec instances with."""
        import sys

        from testground_tpu.config import EnvConfig
        from testground_tpu.healthcheck import Helper, checkers, fixers

        if env is None:  # observe the environment, don't repair it
            env = EnvConfig.load(ensure_dirs=False)
        h = Helper()
        for name, d in (
            ("outputs-dir-writable", env.dirs.outputs()),
            ("work-dir-writable", env.dirs.work()),
        ):
            h.enlist(
                name,
                checkers.check_dir_writable(d),
                fixers.create_directory(d),
            )
        # probe the CONFIGURED sync bind host, not a hardcoded loopback:
        # a runner configured to serve other hosts must learn at
        # healthcheck time (not mid-run) that its interface can't bind
        rcfg = env.runner_config("local:exec")
        bind_host = str(rcfg.get("sync_bind_host", "") or "127.0.0.1")
        h.enlist(
            "sync-service-port-bindable",
            checkers.check_port_bindable(bind_host),
            fixers.requires_manual_fixing(
                f"free TCP ports / ulimit on {bind_host}, or fix the "
                "runner's sync_bind_host"
            ),
        )
        # a configured EXTERNAL sync service must answer a real ping RPC
        remote = str(rcfg.get("sync_service_address", "") or "")
        if remote:
            from testground_tpu.sync import parse_hostport

            try:
                rhost, rport = parse_hostport(remote)
            except ValueError as e:
                h.enlist(
                    "sync-service-reachable",
                    lambda e=e: (False, str(e)),
                    fixers.requires_manual_fixing(
                        "fix the runner's sync_service_address"
                    ),
                )
            else:
                h.enlist(
                    "sync-service-reachable",
                    checkers.check_sync_service(rhost, rport),
                    fixers.requires_manual_fixing(
                        "start `tg sync-service` on the sync host / open "
                        "the firewall between the hosts"
                    ),
                )
        h.enlist(
            "python-interpreter-runs",
            checkers.check_command_status(sys.executable, "-c", "pass"),
            fixers.requires_manual_fixing("reinstall the python runtime"),
        )
        return h.run_checks(fix, ow)

    # ------------------------------------------------------------------ run

    def _start_sync_service(self, cfg, job, ow: OutputWriter):
        """Boot (or join) the per-run sync service.

        With ``sync_service_address`` set, the run joins an EXTERNAL
        service by address (the shared coordination plane of a
        cross-host run — docs/CROSSHOST.md) after verifying it answers a
        ping RPC. Otherwise boot the native C++ server when the config
        allows and a toolchain exists, else the Python one (both expose
        .address/.stop and speak the same wire protocol), bound to the
        configured ``sync_bind_host``."""
        remote = getattr(cfg, "sync_service_address", "") or ""
        if remote:
            from testground_tpu.healthcheck.checkers import check_sync_service
            from testground_tpu.sync import parse_hostport

            rhost, rport = parse_hostport(remote)
            ok, msg = check_sync_service(rhost, rport)()
            if not ok:
                raise RuntimeError(
                    f"configured external sync service is not usable: {msg}"
                )
            ow.infof("sync service: external at %s:%d", rhost, rport)
            return _ExternalSyncService((rhost, rport))

        from testground_tpu.sync.boot import boot_sync_service

        return boot_sync_service(
            mode=getattr(cfg, "sync_service", "auto"),
            host=getattr(cfg, "sync_bind_host", "") or "127.0.0.1",
            port=0,
            idle_timeout=float(
                getattr(cfg, "sync_idle_timeout_secs", 30.0) or 0.0
            ),
            evict_grace=float(getattr(cfg, "sync_evict_grace_secs", 2.0)),
            bin_dir=os.path.join(job.env.dirs.work(), "bin"),
            log=lambda msg: ow.infof("%s", msg),
            shards=int(getattr(cfg, "sync_shards", 0) or 0),
        )

    @staticmethod
    def _dep_targets(artifact_path: str, ow: OutputWriter) -> list[str]:
        """Local dependency-override targets from the artifact's deps.json
        (the go.mod `replace` analog, ``composition.go:302-311`` →
        ``exec_go.go:94-118``; e2e'd by ``20_exec_go_mod_rewrites.sh``).
        Best-effort: a missing or malformed file (exec:bin plans may ship
        an unrelated deps.json of their own) yields no targets, never a
        failed run. Relative targets resolve against the snapshot dir —
        absolute paths are what compositions should declare."""
        deps_path = os.path.join(os.path.dirname(artifact_path), "deps.json")
        if not os.path.isfile(deps_path):
            return []
        try:
            with open(deps_path) as df:
                dep_doc = json.load(df)
            deps = (
                dep_doc.get("dependencies")
                if isinstance(dep_doc, dict)
                else None
            )
            if not isinstance(deps, dict):
                return []
            targets = []
            for d in deps.values():
                target = d.get("target") if isinstance(d, dict) else None
                if target:
                    target = str(target)
                    if not os.path.isabs(target):
                        target = os.path.normpath(
                            os.path.join(
                                os.path.dirname(artifact_path), target
                            )
                        )
                    targets.append(target)
            return targets
        except (OSError, json.JSONDecodeError) as e:
            ow.warn("ignoring unusable deps.json %s: %s", deps_path, e)
            return []

    def run(
        self, job: RunInput, ow: OutputWriter, cancel: threading.Event
    ) -> RunOutput:
        cfg = job.runner_config or LocalExecConfig()
        run_timeout = float(cfg.run_timeout_secs or 0)

        result = Result.for_input(job)
        pretty = PrettyPrinter(ow)

        sync_server = self._start_sync_service(cfg, job, ow)
        bind_host, port = sync_server.address
        # instances (possibly on another machine) dial the ADVERTISED
        # host: a wildcard bind must not hand them "0.0.0.0"
        from testground_tpu.sync import advertise_host

        host = advertise_host(
            bind_host, getattr(cfg, "sync_advertise_host", "") or ""
        )

        # runner-side outcome collection: subscribe to the run's lifecycle
        # events before instances start (local_docker.go:217-256). The
        # collector is itself a sync CLIENT over TCP — backend-agnostic
        # (in-process Python server or the native C++ one).
        outcomes: dict[tuple[str, int], str] = {}
        outcomes_lock = threading.Lock()
        expected = sum(g.instances for g in job.groups)
        all_outcomes_in = threading.Event()
        # eviction tally for the result journal (journal.sync.evicted):
        # counted per event, not from the final slot map — a terminal
        # event landing after an eviction overwrites the slot but the
        # eviction still happened and the control plane journals it
        evicted_count = [0]

        from testground_tpu.sync.client import SyncClient

        def collect() -> None:
            topic = f"run:{job.run_id}:{RUN_EVENTS_TOPIC}"
            try:
                for evt in collector_client.subscribe(topic):
                    with outcomes_lock:
                        key = (evt.get("group", ""), int(evt.get("instance", -1)))
                        if evt.get("type") == "evicted":
                            evicted_count[0] += 1
                        # a server-side eviction (killed / partitioned
                        # instance) fills the slot so survivors and the
                        # runner stop waiting — but never rewrites a
                        # terminal event the instance published itself
                        if (
                            evt.get("type") == "evicted"
                            and outcomes.get(key) in _TERMINAL_EVENTS
                        ):
                            continue
                        outcomes[key] = evt.get("type", "")
                        if len(outcomes) >= expected:
                            all_outcomes_in.set()
            except (TimeoutError, RuntimeError, OSError):
                pass

        try:
            collector_client = SyncClient(host, port)
            collector = threading.Thread(target=collect, daemon=True)
            collector.start()
        except Exception:
            # don't leak the just-started sync server (for the native
            # backend that is a real child process holding a port)
            sync_server.stop()
            raise

        procs: list[tuple[str, int, subprocess.Popen]] = []
        start_sem = threading.Semaphore(START_CONCURRENCY)
        start_time = time.time()

        try:
            global_seq = 0
            for g in job.groups:
                dep_targets = self._dep_targets(g.artifact_path, ow)
                for i in range(g.instances):
                    iid = f"{g.id}[{i:03d}]"
                    out_dir = instance_output_dir(
                        job.env.dirs.outputs(),
                        job.test_plan,
                        job.run_id,
                        g.id,
                        i,
                    )
                    os.makedirs(out_dir, exist_ok=True)
                    tmp_dir = os.path.join(
                        job.env.dirs.work(), job.run_id, g.id, str(i)
                    )
                    os.makedirs(tmp_dir, exist_ok=True)

                    params = RunParams(
                        test_plan=job.test_plan,
                        test_case=job.test_case,
                        test_run=job.run_id,
                        test_instance_count=job.total_instances,
                        test_group_id=g.id,
                        test_group_instance_count=g.instances,
                        test_instance_params=dict(g.parameters),
                        test_subnet=DEFAULT_SUBNET,
                        test_sidecar=False,
                        test_outputs_path=out_dir,
                        test_temp_path=tmp_dir,
                        test_start_time=start_time,
                        test_capture_profiles=dict(g.profiles),
                        test_disable_metrics=job.disable_metrics,
                        test_instance_seq=global_seq,
                        test_group_seq=i,
                        sync_service_host=host,
                        sync_service_port=port,
                        sync_connect_timeout=float(
                            getattr(cfg, "sync_connect_timeout_secs", 30.0)
                        ),
                        sync_retry_attempts=int(
                            getattr(cfg, "sync_retry_attempts", 8)
                        ),
                        sync_retry_deadline=float(
                            getattr(cfg, "sync_retry_deadline_secs", 60.0)
                        ),
                        sync_heartbeat=float(
                            getattr(cfg, "sync_heartbeat_secs", 5.0)
                        ),
                        test_traceparent=(
                            getattr(job, "trace_ctx", None) or {}
                        ).get("traceparent", ""),
                    )
                    env = {**os.environ, **params.to_env()}
                    # Instances are plain CPU processes; drop accelerator
                    # hooks (a sitecustomize kegged on PALLAS_AXON_POOL_IPS
                    # imports jax+PJRT into every child, ~4s and ~120MB per
                    # instance — fatal for instance-count scaling).
                    for accel_var in (
                        "PALLAS_AXON_POOL_IPS",
                        "JAX_PLATFORMS",
                        "XLA_FLAGS",
                    ):
                        env.pop(accel_var, None)
                    # plans import the SDK from this checkout; dependency
                    # override targets (read once per group) go FIRST so
                    # the override wins over an installed module
                    pkg_root = os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__))
                    )
                    env["PYTHONPATH"] = os.pathsep.join(
                        dep_targets
                        + [
                            os.path.dirname(pkg_root),
                            env.get("PYTHONPATH", ""),
                        ]
                    ).rstrip(os.pathsep)
                    with start_sem:
                        if cancel.is_set():
                            raise RuntimeError("run canceled during start")
                        # dispatch on the builder that made the artifact:
                        # exec:bin artifacts exec directly, everything
                        # else runs through this interpreter
                        cmd = (
                            [g.artifact_path]
                            if g.builder == "exec:bin"
                            else [sys.executable, g.artifact_path]
                        )
                        try:
                            proc = subprocess.Popen(
                                cmd,
                                env=env,
                                cwd=os.path.dirname(g.artifact_path),
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE,
                                text=True,
                                bufsize=1,
                            )
                        except OSError as e:
                            pretty.fail_start(iid, str(e))
                            global_seq += 1
                            continue
                    pretty.manage(iid, proc.stdout, proc.stderr)
                    procs.append((g.id, i, proc))
                    global_seq += 1

            ow.infof(
                "started %d instances for run %s", len(procs), job.run_id
            )

            # wait for all processes (ContainerWait analog,
            # local_docker.go:618-641)
            deadline = (
                time.time() + run_timeout if run_timeout else None
            )
            for _, _, proc in procs:
                while True:
                    if cancel.is_set():
                        raise RuntimeError("run canceled")
                    if deadline is not None and time.time() > deadline:
                        raise RuntimeError("run timed out")
                    try:
                        proc.wait(timeout=0.2)
                        break
                    except subprocess.TimeoutExpired:
                        continue

            # bounded post-exit outcome collection (local_docker.go:657-682)
            all_outcomes_in.wait(timeout=OUTCOME_COLLECTION_TIMEOUT)
            pretty.wait(timeout=10.0)

        finally:
            for _, _, proc in procs:
                if proc.poll() is None:
                    proc.kill()
            for _, _, proc in procs:  # reap — no zombies until GC
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
            # reader threads drain to EOF after the kill; release the pipe
            # files once they have (closing a file a blocked reader still
            # holds would deadlock — e.g. a grandchild keeping the write
            # end open past the kill — so in that rare case we prefer the
            # bounded fd leak and let GC finish the job)
            pretty.wait(timeout=10.0)
            if pretty.drained():
                for _, _, proc in procs:
                    for f in (proc.stdout, proc.stderr):
                        if f is not None:
                            try:
                                f.close()
                            except OSError:
                                pass
            collector_client.close()  # unblocks the collector's subscribe
            sync_server.stop()

        with outcomes_lock:
            for (group, _), outcome in outcomes.items():
                if group in result.outcomes and outcome == "success":
                    result.add_outcome(group, Outcome.SUCCESS)
            if evicted_count[0]:
                result.journal.setdefault("sync", {})["evicted"] = (
                    evicted_count[0]
                )
        result.update_outcome()
        ow.infof(
            "run %s finished: %s (%s)",
            job.run_id,
            result.outcome.value,
            {k: f"{v.ok}/{v.total}" for k, v in result.outcomes.items()},
        )
        return RunOutput(run_id=job.run_id, result=result)

    def terminate_all(self, ow: OutputWriter) -> None:
        """Processes die with the task's cancel event; nothing persists."""
        ow.infof("local:exec: no persistent resources to terminate")
