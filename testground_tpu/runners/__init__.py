"""Runner plugin registry. Twin of the reference's ``pkg/runner``.

Runners registered here (mirroring ``pkg/engine/engine.go:33-38``):
- ``local:exec`` — one OS process per instance on this host.
- ``sim:jax``   — vectorized discrete-event simulation on TPU/CPU devices.
"""

from .base import HealthcheckedRunner, Runner, RunnerOutcomeError, Terminatable
from .result import GroupOutcome, Result

__all__ = [
    "GroupOutcome",
    "HealthcheckedRunner",
    "Result",
    "Runner",
    "RunnerOutcomeError",
    "Terminatable",
]
