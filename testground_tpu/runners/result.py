"""Run result types (``pkg/runner/common_result.go``)."""

from __future__ import annotations

from dataclasses import dataclass, field

from testground_tpu.api import RunInput
from testground_tpu.engine.task import Outcome

__all__ = ["GroupOutcome", "Result"]


@dataclass
class GroupOutcome:
    total: int = 0
    ok: int = 0

    def to_dict(self) -> dict:
        return {"total": self.total, "ok": self.ok}


@dataclass
class Result:
    """(``common_result.go:8-31``)."""

    outcome: Outcome = Outcome.UNKNOWN
    outcomes: dict[str, GroupOutcome] = field(default_factory=dict)
    journal: dict = field(default_factory=dict)

    @classmethod
    def for_input(cls, inp: RunInput) -> "Result":
        r = cls(journal={"events": {}, "pods_statuses": {}})
        for g in inp.groups:
            r.outcomes[g.id] = GroupOutcome(total=g.instances, ok=0)
        return r

    def add_outcome(self, group_id: str, outcome: Outcome) -> None:
        if outcome == Outcome.SUCCESS:
            self.outcomes[group_id].ok += 1

    def total_instances(self) -> int:
        return sum(g.total for g in self.outcomes.values())

    def update_outcome(self) -> None:
        """All-ok ⇒ success, else failure (``common_result.go:52-59``)."""
        for g in self.outcomes.values():
            if g.total != g.ok:
                self.outcome = Outcome.FAILURE
                return
        self.outcome = Outcome.SUCCESS

    def to_dict(self) -> dict:
        return {
            "outcome": self.outcome.value,
            "outcomes": {k: v.to_dict() for k, v in self.outcomes.items()},
            "journal": self.journal,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Result":
        """Inverse of to_dict — used to marshal a Result across the
        cohort-leader child boundary (``sim/cohort.py``)."""
        return cls(
            outcome=Outcome(d.get("outcome", Outcome.UNKNOWN.value)),
            outcomes={
                k: GroupOutcome(
                    total=int(v.get("total", 0)), ok=int(v.get("ok", 0))
                )
                for k, v in d.get("outcomes", {}).items()
            },
            journal=dict(d.get("journal", {})),
        )
