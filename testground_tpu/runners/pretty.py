"""PrettyPrinter: renders instance event streams to the console and counts
failures.

Twin of the reference's ``pkg/runner/pretty.go:113-180``: structured stdout
lines become classified console events (START/OK/FAIL/CRASH/MESSAGE/METRIC/
OTHER); stderr lines print as ERROR; instances that end without a terminal
event are marked INCOMPLETE and count as failed.
"""

from __future__ import annotations

import threading
import time
from typing import IO

from testground_tpu.rpc import OutputWriter
from testground_tpu.sdk.events import parse_event_line

__all__ = [
    "PrettyPrinter",
    "render_fleet",
    "render_lifecycle_tree",
    "render_netmap",
    "render_netmap_cut",
    "render_perf_summary",
    "render_phase_table",
    "render_run_diff",
    "render_sync_stats",
    "render_telemetry_summary",
]


# the shared ledger-consumer helpers (stdlib-only module, safe here):
# null/NaN/string fields from foreign writers degrade to readable
# placeholders, not TypeErrors or misleading blanks
from testground_tpu.sim.perf import fmt_rate as _fmt_rate
from testground_tpu.sim.perf import num as _num


def _fmt(v, spec: str = "{:.2f}", missing: str = "?") -> str:
    n = _num(v)
    return missing if n is None else spec.format(n)


def _fmt_count(v, missing: str = "?") -> str:
    """An integral count rendered verbatim — ``'{:g}'`` would truncate
    counts >= 1e6 into scientific notation (format(1234567, 'g') ==
    '1.23457e+06'), and tick totals get there routinely."""
    n = _num(v)
    if n is None:
        return missing
    return str(int(n)) if float(n).is_integer() else str(n)


def _fmt_transport(tr: dict) -> str:
    """One-line render of a ``sim.transport`` resolution block: the
    resolved backend, the requested→resolved arrow when they differ (or
    when the cost model decided), and the human-readable reason."""
    req = tr.get("requested", "?")
    res = tr.get("resolved", "?")
    shown = res if req == res else f"{req} → {res}"
    if tr.get("reason") and (req == "auto" or req != res):
        shown += f" ({tr['reason']})"
    return shown


def render_telemetry_summary(stats: dict) -> str:
    """Render a completed task's telemetry summary as an aligned table —
    the console surface of the sim telemetry plane (``tg stats <task>``
    and ``tg status --telemetry``; docs/OBSERVABILITY.md).

    ``stats`` is the /stats payload shape: identity fields plus the
    journal's ``sim`` / ``telemetry`` / ``events`` sections (all
    optional — non-sim tasks render whatever they have)."""
    sim = stats.get("sim") or {}
    tele = stats.get("telemetry") or {}
    trace = stats.get("trace") or {}
    slo = stats.get("slo") or {}
    events = stats.get("events") or {}
    ident = f"{stats.get('plan', '?')}:{stats.get('case', '?')}"
    if stats.get("task_id"):
        ident += f"  ({stats['task_id']})"
    if not (sim or tele or trace or slo or events):
        # e.g. a build task, or a run that recorded nothing
        return f"task  {ident}\nno telemetry recorded for this task"
    rows: list[tuple[str, str]] = [("task", ident)]
    if stats.get("outcome"):
        rows.append(("outcome", str(stats["outcome"])))
    if sim:
        ticks = _num(sim.get("ticks"), 0)
        tick_ms = _num(sim.get("tick_ms"), 0.0)
        rows.append(
            (
                "ticks",
                f"{_fmt_count(ticks)} ({ticks * tick_ms / 1000.0:.2f} "
                f"sim-s at {tick_ms:g} ms/tick)",
            )
        )
        rows.append(
            (
                "wall",
                f"{_fmt(sim.get('wall_secs'))}s (compile "
                f"{_fmt(sim.get('compile_secs'))}s) on "
                f"{_fmt(sim.get('devices'), '{:g}', '1')} device(s) / "
                f"{_fmt(sim.get('processes'), '{:g}', '1')} process(es)",
            )
        )
        carry = _num(sim.get("carry_bytes"))
        if carry is not None:
            rows.append(
                ("carry", f"{carry / 2**20:.2f} MiB device-resident")
            )
        # the mesh plane (journal["sim"]["mesh"]): layout + shard
        # extents + the modeled ICI exchange the transport decision
        # priced — one line, the full rule table stays in the journal
        mh = sim.get("mesh") or {}
        if mh.get("axes"):
            xb = _num(mh.get("cross_shard_bytes_est"))
            rows.append(
                (
                    "mesh",
                    "{a} ({s} peer shard(s) x {r} run shard(s), "
                    "~{x} ICI exchange/commit)".format(
                        a=mh.get("axes"),
                        s=_fmt_count(mh.get("shards")),
                        r=_fmt_count(mh.get("runs"), "1"),
                        x=f"{xb / 2**10:.1f} KiB"
                        if xb is not None
                        else "?",
                    ),
                )
            )
        # transport resolution (journal["sim"]["transport"]): requested
        # vs resolved plus the cost model's reason — e.g. "auto → pallas
        # (commit+deliver bytes 2.1x the single-pass kernel estimate)"
        tr = sim.get("transport") or {}
        if tr.get("resolved"):
            rows.append(("transport", _fmt_transport(tr)))
        # run packing (journal["sim"]["pack"]): a packed member shows
        # its slot; a pack-opted run that executed SOLO shows why — the
        # supervisor journals solo_reason so the tenant never has to
        # guess what kept their run out of a pack
        pk = sim.get("pack") or {}
        if pk.get("solo_reason"):
            rows.append(("pack", f"solo — {pk['solo_reason']}"))
        elif pk.get("width"):
            rows.append(
                (
                    "pack",
                    "member {m}/{n} of a width-{w} pack "
                    "(leader {l})".format(
                        # journal index is 0-based; humans count from 1
                        m=_fmt_count(
                            (_num(pk.get("index"), 0) or 0) + 1, "?"
                        ),
                        n=_fmt_count(pk.get("members")),
                        w=_fmt_count(pk.get("width")),
                        l=pk.get("leader_run", "?"),
                    ),
                )
            )
        # one-line performance-ledger teaser (full view: `tg perf`)
        perf_ex = (sim.get("perf") or {}).get("execute") or {}
        rate = _num(perf_ex.get("steady_peer_ticks_per_sec")) or _num(
            perf_ex.get("peer_ticks_per_sec")
        )
        if rate:
            rows.append(
                ("perf", f"{rate:,.0f} peer·ticks/s (details: tg perf)")
            )
        rows.append(
            (
                "messages",
                "delivered={d} enqueued={e} dropped={x} rejected={r} "
                "in-flight={f}".format(
                    d=sim.get("msgs_delivered", 0),
                    e=sim.get("msgs_enqueued", 0),
                    x=sim.get("msgs_dropped", 0),
                    r=sim.get("msgs_rejected", 0),
                    f=sim.get("msgs_in_flight", 0),
                ),
            )
        )
        for key, label in (
            ("latency_clamped", "horizon-clamped"),
            ("bw_queue_dropped", "bw-queue-dropped"),
        ):
            if sim.get(key):
                rows.append((label, str(sim[key])))
        # fault-injection plane (docs/FAULTS.md): one line when any
        # counter is nonzero — a chaos run's verdict at a glance
        if any(
            sim.get(k)
            for k in (
                "faults_crashed",
                "faults_restarted",
                "msgs_fault_dropped",
            )
        ):
            rows.append(
                (
                    "faults",
                    "crashed={c} restarted={r} fault-dropped={d}".format(
                        c=sim.get("faults_crashed", 0),
                        r=sim.get("faults_restarted", 0),
                        d=sim.get("msgs_fault_dropped", 0),
                    ),
                )
            )
        # checkpoint/resume plane (docs/CHECKPOINT.md): last-snapshot
        # tick + resume provenance at a glance
        ck = sim.get("checkpoint") or {}
        if ck:
            parts = []
            if _num(ck.get("count"), 0):
                parts.append(
                    "{n} snapshot(s), last at tick {t} "
                    "({d}/, {b:.2f} MiB)".format(
                        n=_fmt_count(ck.get("count")),
                        t=_fmt_count(ck.get("last_tick")),
                        d=ck.get("dir", "checkpoints"),
                        b=(_num(ck.get("bytes"), 0) or 0) / 2**20,
                    )
                )
            elif _num(ck.get("every_chunks"), 0):
                parts.append("armed, none written")
            resumed = ck.get("resumed") or {}
            if resumed:
                parts.append(
                    "resumed from tick {t} of run {r}".format(
                        t=_fmt_count(resumed.get("from_tick")),
                        r=resumed.get("from_run", "?"),
                    )
                )
            if parts:
                rows.append(("checkpoint", "; ".join(parts)))
        # per-receiver-group delivery-latency percentiles (telemetry
        # plane histograms, docs/OBSERVABILITY.md) — one line per group
        for gid, pct in sorted((sim.get("latency") or {}).items()):
            if not _num(pct.get("count"), 0):
                rows.append((f"latency {gid}", "no deliveries"))
                continue
            rows.append(
                (
                    f"latency {gid}",
                    "p50={p50}ms p95={p95}ms p99={p99}ms (n={n})".format(
                        p50=pct.get("p50_ms", "?"),
                        p95=pct.get("p95_ms", "?"),
                        p99=pct.get("p99_ms", "?"),
                        n=pct["count"],
                    ),
                )
            )
    if tele:
        shown = f"{tele.get('rows', 0)} per-tick rows"
        if tele.get("file"):  # absent when no outputs dir held the series
            shown += f" ({tele['file']})"
        rows.append(("telemetry", shown))
    if trace:
        shown = (
            f"{trace.get('events', 0)} events from "
            f"{trace.get('instances', 0)} instance(s)"
        )
        files = [trace.get("file"), trace.get("events_file")]
        files = [f for f in files if f]
        if files:
            shown += f" ({', '.join(files)})"
        if trace.get("truncated"):
            shown += f" — {trace['truncated']} past the export cap"
        rows.append(("trace", shown))
    # run health plane (docs/OBSERVABILITY.md "Run health plane"): one
    # verdict line per rule — "ok" or the breach count with the worst
    # observed value, so a soak's health reads at a glance
    for r in slo.get("rules") or []:
        if not isinstance(r, dict):
            continue
        rule = (
            f"{r.get('metric', '?')} {r.get('op', '?')} "
            f"{_fmt(r.get('threshold'), '{:g}')}"
        )
        n = _num(r.get("breaches"), 0)
        if n:
            verdict = (
                f"{rule} — {_fmt_count(n)} breach(es) "
                f"[{r.get('severity', 'warn')}], worst "
                f"{_fmt(r.get('worst'), '{:g}')} "
                f"(ticks {r.get('first_tick', '?')}–{r.get('last_tick', '?')})"
            )
        else:
            verdict = rule + " — ok"
            if _num(r.get("last_observed")) is not None:
                verdict += f" (last {_fmt(r.get('last_observed'), '{:g}')})"
        rows.append((f"slo {r.get('name', '?')}", verdict))
    if slo.get("error"):
        rows.append(("slo FAILED", str(slo["error"])))
    for gid, counts in sorted(events.items()):
        if isinstance(counts, dict):
            shown = ", ".join(
                f"{k}={v}" for k, v in sorted(counts.items()) if v
            )
            rows.append((f"group {gid}", shown or "-"))
    width = max(len(k) for k, _ in rows)
    return "\n".join(f"{k:<{width}}  {v}" for k, v in rows)


def _fmt_us(v) -> str:
    """A µs duration with a readable unit (µs/ms/s)."""
    n = _num(v)
    if n is None:
        return "?"
    if n >= 1e6:
        return f"{n / 1e6:.2f}s"
    if n >= 1e3:
        return f"{n / 1e3:.2f}ms"
    return f"{n:.0f}µs"


def render_sync_stats(stats: dict) -> str:
    """Render a ``sync_stats`` snapshot as an aligned table — the
    console surface of the sync-plane stats tier (``tg sync-stats
    <host:port>``; docs/OBSERVABILITY.md "Sync plane").

    ``stats`` is the wire reply minus ``id`` (v1 or v2): a v1 server
    renders its three occupancy integers plus an upgrade hint; a v2
    server renders op counters with interpolated service-time
    percentiles, barrier lifecycle + release-vs-fan-in timing, pubsub
    depth and connection churn."""
    lines = []
    boot = str(stats.get("boot", "?"))
    head = f"sync service   boot {boot[:12]}"
    if stats.get("v"):
        up = _num(stats.get("uptime_secs"))
        head += f"   stats v{stats['v']}"
        if up is not None:
            head += f"   up {up:.0f}s"
    lines.append(head)
    lines.append(
        f"occupancy      conns {_fmt_count(stats.get('conns'))}   "
        f"waiters {_fmt_count(stats.get('waiters'))}   "
        f"subs {_fmt_count(stats.get('subs'))}"
    )
    if not stats.get("v"):
        lines.append(
            "(v1 server: occupancy only — op-level metrics need a "
            "server with the sync-stats plane)"
        )
        return "\n".join(lines)
    conn = stats.get("conn") or {}
    lines.append(
        f"conn churn     accepts {_fmt_count(conn.get('accepts'))}   "
        f"closes {_fmt_count(conn.get('closes'))}   "
        f"evictions {_fmt_count(conn.get('evictions'))}   "
        f"hwm {_fmt_count(conn.get('hwm'))}"
    )
    bar = stats.get("barriers") or {}
    lines.append(
        f"barriers       parked {_fmt_count(bar.get('parked'))}   "
        f"released {_fmt_count(bar.get('released'))}   "
        f"timed-out {_fmt_count(bar.get('timed_out'))}   "
        f"canceled {_fmt_count(bar.get('canceled'))}"
    )
    ps = stats.get("pubsub") or {}
    lines.append(
        f"pubsub         topics {_fmt_count(ps.get('topics'))}   "
        f"entries {_fmt_count(ps.get('entries'))}   "
        f"published {_fmt_count(ps.get('published'))}   "
        f"depth-hwm {_fmt_count(ps.get('depth_hwm'))}   "
        f"subs-hwm {_fmt_count(ps.get('subs_hwm'))}"
    )
    dd = stats.get("dedup") or {}
    lines.append(
        f"dedup hits     signal {_fmt_count(dd.get('signal_hits'))}   "
        f"publish {_fmt_count(dd.get('publish_hits'))}"
    )
    ops = stats.get("ops") or {}
    op_time = stats.get("op_time_us") or {}
    active = [(op, n) for op, n in ops.items() if _num(n)]
    if active:
        from testground_tpu.sync.stats import hist_quantile_us

        lines.append("")
        lines.append(
            f"{'op':<16}{'count':>10}{'p50':>10}{'p95':>10}"
            f"{'p99':>10}{'max':>10}"
        )
        for op, n in sorted(active, key=lambda kv: -int(_num(kv[1]) or 0)):
            rec = op_time.get(op) or {}
            bins = rec.get("bins") or []
            if bins and sum(bins):
                # clamp to the observed max: log2-bin interpolation can
                # overshoot the slowest real sample inside the top bin
                cap = _num(rec.get("max_us")) or float("inf")
                p50, p95, p99 = (
                    _fmt_us(min(cap, hist_quantile_us(bins, q)))
                    for q in (0.50, 0.95, 0.99)
                )
                mx = _fmt_us(rec.get("max_us"))
            else:
                p50 = p95 = p99 = mx = "-"
            lines.append(
                f"{op:<16}{_fmt_count(n):>10}{p50:>10}{p95:>10}"
                f"{p99:>10}{mx:>10}"
            )
    by_target = ((bar.get("episodes") or {}).get("by_target")) or {}
    if by_target:
        lines.append("")
        lines.append("barrier release vs fan-in width (armed → release):")
        # bucket keys are strings in decoded JSON; a foreign
        # non-numeric key sorts last, never raises
        for bucket in sorted(
            by_target,
            key=lambda b: (
                int(b) if str(b).lstrip("-").isdigit() else float("inf")
            ),
        ):
            rec = by_target[bucket] or {}
            count = _num(rec.get("count")) or 0
            mean = (
                (_num(rec.get("total_ms")) or 0.0) / count if count else 0.0
            )
            lines.append(
                f"  target ≤{bucket:<8} episodes {int(count):<7} "
                f"mean {mean:.2f}ms   max "
                f"{_fmt(rec.get('max_ms'), '{:.2f}')}ms"
            )
    return "\n".join(lines)


def _fmt_bytes(v) -> str:
    n = _num(v)
    if n is None:
        return "?"
    for div, suffix in ((2**30, "GiB"), (2**20, "MiB"), (2**10, "KiB")):
        if abs(n) >= div:
            return f"{n / div:.2f} {suffix}"
    return f"{n:.0f} B"


def render_perf_summary(payload: dict) -> str:
    """Render a task's performance ledger as an aligned table — the
    console surface of the perf plane (``tg perf <task>``;
    docs/OBSERVABILITY.md "Performance ledger").

    ``payload`` is the /perf payload shape (Task.perf_payload): identity
    + ``sim`` + ``perf`` + ``task``, every field optional — absent, zero
    or NaN fields render as ``?`` lines or are dropped, never as
    misleading blanks."""
    sim = payload.get("sim") or {}
    perf = payload.get("perf") or {}
    task = payload.get("task") or {}
    ident = f"{payload.get('plan', '?')}:{payload.get('case', '?')}"
    if payload.get("task_id"):
        ident += f"  ({payload['task_id']})"
    rows: list[tuple[str, str]] = [("task", ident)]
    if payload.get("outcome"):
        rows.append(("outcome", str(payload["outcome"])))
    if not perf and not sim:
        # multi-run compositions journal per-run results (no top-level
        # sim block yet), and disable_metrics / cohorts / perf=false run
        # ledger-free — say so, but still render the scheduler timings
        # the supervisor recorded for exactly this surface
        rows.append(
            (
                "ledger",
                "no performance ledger recorded (a multi-run composition, "
                "disable_metrics, a cohort run, or runner config "
                "perf=false)",
            )
        )
    co = perf.get("compile") or {}
    ex = perf.get("execute") or {}
    if perf or sim:
        # the compile split: the journal's compile_secs (init + first
        # dispatch) beside the AOT pass's true lower-vs-XLA breakdown
        split = (
            f" (AOT lower {_fmt(co.get('lower_secs'))}s + "
            f"xla {_fmt(co.get('compile_secs'))}s)"
            if co
            else ""
        )
        rows.append(
            (
                "compile",
                f"{_fmt(sim.get('compile_secs'))}s first dispatch{split}",
            )
        )
        # the mesh the ledger's rates were measured on — a 4-shard run
        # and a single-device run are different machines, not noise
        mh = sim.get("mesh") or {}
        if mh.get("axes"):
            rows.append(
                (
                    "mesh",
                    f"{mh.get('axes')} "
                    f"({_fmt_count(mh.get('shards'))} peer shard(s))",
                )
            )
        # transport resolution — the backend this ledger measured, and
        # why the gate picked it (the cost model's reason under auto)
        tr = sim.get("transport") or {}
        if tr.get("resolved"):
            rows.append(("transport", _fmt_transport(tr)))
    # ``instances`` in the ledger is the EXACT live count — padded or
    # packed runs must never render inflated peer·ticks/s (the bucket
    # size is a separate annotation line below)
    n_inst = _num(perf.get("instances"), 0)
    bucket = perf.get("bucket") or (sim.get("bucket") or {}).get(
        "padded_instances"
    )
    if _num(bucket) and _num(bucket) != n_inst:
        cache = (sim.get("bucket") or {}).get("compile_cache")
        rows.append(
            (
                "bucket",
                f"{_fmt_count(n_inst)} live instance(s) padded to "
                f"{_fmt_count(bucket)}"
                + (f" — compile cache {cache}" if cache else ""),
            )
        )
    pack = sim.get("pack") or {}
    if pack.get("solo_reason"):
        rows.append(("pack", f"solo — {pack['solo_reason']}"))
    elif _num(pack.get("width")):
        rows.append(
            (
                "pack",
                # journal index is 0-based; humans count from 1
                f"run {_fmt_count(_num(pack.get('index'), 0) + 1)} of a "
                f"{_fmt_count(pack.get('members'))}-member pack "
                f"(vmapped width {_fmt_count(pack.get('width'))})",
            )
        )
    if ex:
        rows.append(
            (
                "execute",
                f"{_fmt_count(ex.get('ticks'))} ticks in "
                f"{_fmt(ex.get('wall_secs'))}s — "
                f"{_fmt_rate(ex.get('ticks_per_sec'))} ticks/s, "
                f"{_fmt_rate(ex.get('peer_ticks_per_sec'))} peer·ticks/s "
                f"({_fmt_count(n_inst)} instance(s), "
                f"{_fmt_count(ex.get('chunks'))} chunk(s))",
            )
        )
        if _num(ex.get("steady_peer_ticks_per_sec")):
            rows.append(
                (
                    "steady",
                    f"{_fmt_rate(ex.get('steady_ticks_per_sec'))} ticks/s, "
                    f"{_fmt_rate(ex.get('steady_peer_ticks_per_sec'))} "
                    f"peer·ticks/s over "
                    f"{_fmt_count(ex.get('steady_chunks'))} steady "
                    "chunk(s)",
                )
            )
    flops = _num(co.get("flops"))
    if flops:
        achieved = (
            f" (achieved {_fmt_rate(ex.get('est_flops_per_sec'))} flop/s)"
            if _num(ex.get("est_flops_per_sec"))
            else ""
        )
        rows.append(
            (
                "cost",
                f"~{_fmt_rate(flops)} flops, "
                f"{_fmt_bytes(co.get('bytes_accessed'))} accessed "
                f"per chunk{achieved}",
            )
        )
    if _num(co.get("peak_bytes")) is not None:
        rows.append(
            (
                "program",
                f"args {_fmt_bytes(co.get('argument_bytes'))} + "
                f"temp {_fmt_bytes(co.get('temp_bytes'))} + "
                f"codegen {_fmt_bytes(co.get('generated_code_bytes'))} "
                f"= peak {_fmt_bytes(co.get('peak_bytes'))}",
            )
        )
    carry = _num(sim.get("carry_bytes"))
    if carry is not None:
        rows.append(("carry", f"{_fmt_bytes(carry)} device-resident"))
    hbm = perf.get("hbm") or {}
    if _num(hbm.get("peak_bytes")):
        limit = (
            f" of {_fmt_bytes(hbm['bytes_limit'])}"
            if _num(hbm.get("bytes_limit"))
            else ""
        )
        rows.append(
            ("hbm", f"high-water {_fmt_bytes(hbm['peak_bytes'])}{limit}")
        )
    elif perf:
        rows.append(("hbm", "no memory stats on this backend"))
    if task:
        bits = []
        if _num(task.get("queued_secs")) is not None:
            bits.append(f"queued {_fmt(task.get('queued_secs'))}s")
        for rid, wall in sorted((task.get("runner_wall_secs") or {}).items()):
            bits.append(f"run {rid} {_fmt(wall)}s")
        if bits:
            rows.append(("sched", ", ".join(bits)))
    series = perf.get("series") or {}
    if _num(series.get("rows")):
        shown = f"{_fmt_count(series['rows'])} per-chunk rows"
        if series.get("file"):
            shown += f" ({series['file']})"
        rows.append(("series", shown))
    width = max(len(k) for k, _ in rows)
    return "\n".join(f"{k:<{width}}  {v}" for k, v in rows)


def _fmt_diff_value(v) -> str:
    """One side of an exact-compared row: scalars verbatim, digested
    objects (the traffic matrix) as their bounded summary."""
    if isinstance(v, dict) and "sha1" in v:
        return f"Σ{_fmt_count(v.get('sum'))} #{v['sha1']}"
    if isinstance(v, float):
        return _fmt(v, "{:g}")
    if v is None:
        return "absent"
    return str(v)


def render_run_diff(doc: dict) -> str:
    """Render a RunDiff document as an aligned table — the console
    surface of the cross-run analysis plane (``tg diff <a> <b>``;
    docs/OBSERVABILITY.md "Run diff").

    Exact planes render their compared/mismatched counts with one line
    per MISMATCH (equality is the expected, quiet case); the perf plane
    renders every judged metric with its verdict, sample counts and
    p-value so the statistics stay auditable; the final line is the
    roll-up verdict."""
    a, b = doc.get("a") or {}, doc.get("b") or {}
    rows: list[tuple[str, str]] = []
    for side, ident in (("a", a), ("b", b)):
        shown = (
            f"{ident.get('plan', '?')}:{ident.get('case', '?')}  "
            f"({ident.get('task_id', '?')})  {ident.get('outcome', '?')}"
        )
        if _num(ident.get("ticks")) is not None:
            shown += f"  {_fmt_count(ident['ticks'])} ticks"
        if _num(ident.get("wall_secs")) is not None:
            shown += f" / {_fmt(ident['wall_secs'])}s"
        rows.append((side, shown))
    setup = doc.get("setup") or {}
    if setup.get("identical"):
        shown = (
            "identical composition + seed — every deterministic counter "
            "must match exactly"
        )
    else:
        diffs = setup.get("diffs") or []
        shown = "setups differ"
        if diffs:
            shown += f" ({', '.join(diffs[:6])}"
            shown += ", …)" if len(diffs) > 6 else ")"
        elif setup.get("note"):
            shown += f" ({setup['note']})"
        shown += " — counter deltas are informational"
    rows.append(("setup", shown))
    # ----- exact planes: compared/mismatched + one line per mismatch
    for plane in ("counters", "latency", "phases", "slo", "netmatrix"):
        block = doc.get(plane)
        if not isinstance(block, dict):
            continue
        if block.get("absent"):
            rows.append((plane, block["absent"]))
            continue
        compared = block.get("compared", 0)
        mismatched = block.get("mismatched", 0)
        verdict = (
            "exact equality"
            if not mismatched
            else f"{mismatched} MISMATCH(ES)"
        )
        rows.append((plane, f"{compared} compared — {verdict}"))
        for row in block.get("rows") or []:
            if row.get("equal"):
                continue
            rows.append(
                (
                    "",
                    f"  {row.get('name')}: "
                    f"a={_fmt_diff_value(row.get('a'))}  "
                    f"b={_fmt_diff_value(row.get('b'))}",
                )
            )
    # ----- perf plane: judged metrics with auditable statistics
    perf = doc.get("perf")
    if isinstance(perf, dict):
        if perf.get("absent"):
            rows.append(("perf", perf["absent"]))
        for m in perf.get("metrics") or []:
            shown = (
                f"{m.get('verdict', '?'):<12} "
                f"a~{_fmt_rate(m.get('median_a'))} "
                f"b~{_fmt_rate(m.get('median_b'))}"
            )
            if _num(m.get("ratio")) is not None:
                shown += f"  x{_fmt(m['ratio'], '{:.3f}')}"
            if _num(m.get("p_value")) is not None:
                shown += f"  p={_fmt(m['p_value'], '{:.4g}')}"
            shown += f"  (n={m.get('n_a', 0)}/{m.get('n_b', 0)})"
            rows.append((str(m.get("metric", "?")), shown))
        for s in perf.get("scalars") or []:
            rows.append(
                (
                    str(s.get("metric", "?")),
                    f"a={_fmt_rate(s.get('a'))} b={_fmt_rate(s.get('b'))} "
                    f"x{_fmt(s.get('ratio'), '{:.3f}')}  "
                    "(summary — one sample, no verdict)",
                )
            )
    # ----- roll-up
    findings = doc.get("findings") or []
    verdict = str(doc.get("verdict", "?"))
    if findings:
        verdict += (
            f" — {len(findings)} CORRECTNESS finding(s): deterministic "
            "counters diverged between identically-seeded runs"
        )
    elif doc.get("regressed"):
        verdict += f" — {', '.join(doc['regressed'])}"
    elif doc.get("improved"):
        verdict += f" — {', '.join(doc['improved'])}"
    rows.append(("verdict", verdict))
    width = max(len(k) for k, _ in rows)
    return "\n".join(
        f"{k:<{width}}  {v}" if k else f"{'':<{width}}  {v}"
        for k, v in rows
    )


def render_phase_table(payload: dict) -> str:
    """Render the phase attribution block as an aligned per-phase table
    (``tg perf --phases``; docs/OBSERVABILITY.md "Phase attribution").

    One row per tick phase (XLA cost-analysis flops / bytes accessed
    per tick, the byte share of the whole program, and the measured
    ms/tick when the run calibrated), then the explicit residual and
    whole-program rows — the rows sum to the whole-program cost BY
    CONSTRUCTION (residual := whole − Σ phases; a negative residual
    means the standalone phases lose fusion the whole program has).
    Shape-tolerant like every payload renderer: absent blocks render a
    hint, never a crash."""
    from testground_tpu.sim.phases import phase_rows

    block = payload.get("phases") or (payload.get("sim") or {}).get(
        "phases"
    )
    if not isinstance(block, dict) or not block.get("phases"):
        return (
            "no phase attribution recorded — run with --run-cfg "
            "phases=true (and phases_measure=K for measured ms/tick); "
            "cohorts and disable_metrics run phase-free"
        )
    rows = phase_rows(block)
    measured = any(_num(r.get("measured_ms")) is not None for r in rows)
    head = ["phase", "flops/tick", "bytes/tick", "byte-share"]
    if measured:
        head.append("ms/tick")
    table = [head]
    for r in rows:
        share = _num(r.get("bytes_frac"))
        line = [
            str(r.get("phase", "?")),
            _fmt_rate(r.get("flops")),
            _fmt_bytes(r.get("bytes_accessed")),
            f"{share * 100:.1f}%" if share is not None else "",
        ]
        if measured:
            ms = _num(r.get("measured_ms"))
            line.append(f"{ms:.3f}" if ms is not None else "")
        table.append(line)
    widths = [
        max(len(row[i]) for row in table) for i in range(len(head))
    ]
    lines = [
        "  ".join(
            cell.ljust(w) if i == 0 else cell.rjust(w)
            for i, (cell, w) in enumerate(zip(row, widths))
        ).rstrip()
        for row in table
    ]
    meta = (
        f"transport={block.get('transport', '?')}  "
        f"chunk={block.get('chunk', '?')}  "
        f"instances={block.get('instances', '?')}"
    )
    cov = block.get("coverage") or {}
    if _num(cov.get("bytes_frac")) is not None:
        meta += f"  byte-coverage=x{cov['bytes_frac']:.2f}"
    return "\n".join([meta] + lines)


def _heat_shade(v, peak) -> str:
    """A 4-step intensity glyph for a heatmap cell — zero-safe (a peak
    of 0, None or NaN renders every cell cold, never divides)."""
    n = _num(v, 0) or 0
    p = _num(peak, 0) or 0
    if n <= 0 or p <= 0:
        return " "
    return "░▒▓█"[min(3, int(3 * n / p))]


def render_netmap(block: dict, ident: str = "") -> str:
    """Render a ``sim.net_matrix`` journal block as the ``tg netmap``
    screen: the src-group × dst-group sent-count heatmap, the per-pair
    problem lines (any drops / rejections / chaos losses), link-shaping
    observables, and the conservation verdict. Shape-tolerant like
    every payload renderer — absent/NaN fields degrade to readable
    placeholders, never a crash (``block`` is decoded JSON from a
    possibly foreign writer)."""
    from testground_tpu.sim.netmatrix import (
        NM_CHANNEL_NAMES,
        NM_MSG_BYTES,
        NM_SENT,
    )

    labels = [str(g) for g in (block.get("labels") or [])]
    mat = block.get("matrix") or []
    gh = len(labels)
    if not gh or len(mat) <= NM_SENT:
        return "no traffic matrix in this block"

    def cell(c, s, t) -> int:
        try:
            return int(_num(mat[c][s][t], 0) or 0)
        except (IndexError, TypeError):
            return 0

    lines = []
    head = "traffic matrix"
    if ident:
        head += f"  {ident}"
    lines.append(head)
    totals = block.get("totals") or {}
    lines.append(
        "totals  "
        + " ".join(
            f"{name}={_fmt_count(totals.get(name), '0')}"
            for name in NM_CHANNEL_NAMES
        )
    )
    if _num(block.get("bytes_total")) is not None:
        lines.append(
            f"bytes   {_fmt_bytes(block['bytes_total'])} enqueued on the "
            f"wire ({NM_MSG_BYTES} B/message)"
        )
    mismatches = block.get("mismatches") or []
    for m in mismatches:
        lines.append(f"CONSERVATION FAILED: {m}")

    # --- the heatmap: sent counts, shaded against the hottest pair
    peak = max(
        (cell(NM_SENT, s, t) for s in range(gh) for t in range(gh)),
        default=0,
    )
    cells = [
        [
            (
                f"{_heat_shade(cell(NM_SENT, s, t), peak)}"
                f"{cell(NM_SENT, s, t)}"
                if cell(NM_SENT, s, t)
                else "·"
            )
            for t in range(gh)
        ]
        for s in range(gh)
    ]
    col_w = [
        max(len(labels[t]), max(len(cells[s][t]) for s in range(gh)))
        for t in range(gh)
    ]
    row_w = max(len("sent ↓src→dst"), max(len(x) for x in labels))
    lines.append("")
    lines.append(
        f"{'sent ↓src→dst':<{row_w}}  "
        + "  ".join(f"{labels[t]:>{col_w[t]}}" for t in range(gh))
    )
    for s in range(gh):
        lines.append(
            f"{labels[s]:<{row_w}}  "
            + "  ".join(f"{cells[s][t]:>{col_w[t]}}" for t in range(gh))
        )

    # --- problem pairs: anything that did not arrive, attributed
    problems = []
    for s in range(gh):
        for t in range(gh):
            lost = [
                (name, cell(c, s, t))
                for c, name in enumerate(NM_CHANNEL_NAMES)
                if name in ("dropped", "rejected", "fault_dropped")
                and cell(c, s, t)
            ]
            if lost:
                problems.append(
                    f"  {labels[s]}→{labels[t]}: "
                    + " ".join(f"{n}={v}" for n, v in lost)
                )
    if problems:
        lines.append("")
        lines.append("lossy pairs:")
        lines.extend(problems)

    # --- link-shaping observables
    hi = block.get("bw_queue_hiwater") or []
    if any((_num(v, 0) or 0) > 0 for v in hi):
        lines.append("")
        lines.append(
            "bandwidth-queue depth high-water (messages, per src group): "
            + "  ".join(
                f"{labels[i]}={_fmt(hi[i], '{:g}')}"
                for i in range(min(gh, len(hi)))
                if (_num(hi[i], 0) or 0) > 0
            )
        )
    fp = block.get("faulted_pairs") or []
    faulted = [
        f"{labels[s]}→{labels[t]} ({int(_num(fp[s][t], 0) or 0)} window(s))"
        for s in range(min(gh, len(fp)))
        for t in range(min(gh, len(fp[s])))
        if (_num(fp[s][t], 0) or 0) > 0
    ]
    if faulted:
        lines.append("")
        lines.append("chaos-degraded pairs: " + ", ".join(faulted))
    if not mismatches:
        lines.append("")
        lines.append("conservation: exact (Σ cells == flow totals)")
    if block.get("file"):
        lines.append(
            f"stream: {block['file']} "
            f"({_fmt_count(block.get('chunks'), '?')} chunk row(s))"
        )
    return "\n".join(lines)


def render_netmap_cut(rec: dict, shards: int) -> str:
    """Render a :func:`~testground_tpu.sim.netmatrix.cut_advisor`
    recommendation (``tg netmap --cut N``): the group→shard assignment
    plus the cross-cut volume it costs — zero-safe when there is no
    cross-group traffic at all."""
    lines = [
        f"cut advisor — {shards} shard(s), "
        f"{rec.get('method', '?')} search"
    ]
    for i, members in enumerate(rec.get("shards") or []):
        lines.append(f"  shard {i}: {', '.join(str(m) for m in members)}")
    cut = _num(rec.get("cut"), 0) or 0
    total = _num(rec.get("total"), 0) or 0
    frac = _num(rec.get("cut_fraction"), 0) or 0
    lines.append(
        f"cross-cut traffic: {_fmt_bytes(cut)} of {_fmt_bytes(total)} "
        f"cross-group bytes ({frac * 100:.1f}%)"
        if total > 0
        else "cross-cut traffic: none (no cross-group traffic measured)"
    )
    return "\n".join(lines)


def render_fleet(payload: dict) -> str:
    """Render a ``GET /fleet`` snapshot (engine.fleet_payload) as the
    ``tg top`` screen: one header block (workers / queue / per-state
    counts over the FULL store) plus one row per live task.
    Shape-tolerant like every payload renderer."""
    workers = payload.get("workers") or {}
    queue = payload.get("queue") or {}
    counts = payload.get("counts") or {}
    lines = [
        "workers {busy}/{total} busy · queue depth {depth} · "
        "tasks {total_tasks} ({states})".format(
            busy=_fmt_count(workers.get("busy"), "0"),
            total=_fmt_count(workers.get("total"), "0"),
            depth=_fmt_count(queue.get("depth"), "0"),
            total_tasks=_fmt_count(payload.get("tasks_total"), "0"),
            states=" ".join(
                f"{k}={v}" for k, v in sorted(counts.items())
            )
            or "none",
        )
    ]
    if payload.get("draining"):
        # graceful drain in progress (docs/FLEET.md): workers park,
        # running tasks checkpoint + requeue
        lines.append("DRAINING — not claiming; running tasks checkpointing")
    by_prio = queue.get("by_priority") or {}
    if by_prio:
        lines.append(
            "queue by priority: "
            + "  ".join(
                f"p{p}={n}"
                for p, n in sorted(
                    by_prio.items(),
                    # priority keys are strings in decoded JSON; a
                    # foreign non-numeric key sorts last, never raises
                    key=lambda kv: -(
                        _num(
                            int(kv[0])
                            if str(kv[0]).lstrip("-").isdigit()
                            else None,
                            float("-inf"),
                        )
                    ),
                )
            )
        )
    packs = (payload.get("pack") or {}).get("running")
    if packs:
        lines.append(f"running packs: {_fmt_count(packs)}")
    rows = payload.get("tasks") or []
    if not rows:
        lines.append("(no queued or running tasks)")
        return "\n".join(lines)
    head = [
        "ID", "STATE", "PRIO", "QUEUED", "RUNNING", "TICKS/S",
        "PACK", "PRE", "BREACH", "NAME",
    ]
    table = [head]
    for r in rows:
        table.append(
            [
                str(r.get("id", "?")),
                str(r.get("state", "?")),
                _fmt_count(r.get("priority"), "0"),
                _fmt(r.get("queued_secs"), "{:.1f}s", "?"),
                _fmt(r.get("running_secs"), "{:.1f}s", ""),
                _fmt_rate(r.get("ticks_per_sec"))
                if r.get("ticks_per_sec") is not None
                else "",
                _fmt_count(r.get("pack_width"), ""),
                # PRE: times this task was preempted/migrated so far
                _fmt_count(r.get("preemptions"), ""),
                _fmt_count(r.get("breaches"), ""),
                str(r.get("name", "")),
            ]
        )
    widths = [max(len(row[i]) for row in table) for i in range(len(head))]
    lines += [
        "  ".join(
            cell.ljust(w) if i in (0, 1, 9) else cell.rjust(w)
            for i, (cell, w) in enumerate(zip(row, widths))
        ).rstrip()
        for row in table
    ]
    return "\n".join(lines)


def render_lifecycle_tree(spans: list) -> str:
    """Render a task's lifecycle span tree (``task_spans.jsonl`` rows —
    engine/tracetree.py) as an indented tree: every child under its
    parent_id, durations in ms, and the control-plane attributes that
    explain scheduling (pack width / solo reason / outcome). Orphan
    spans (parent_id missing from the file) render as extra roots so a
    broken tree is VISIBLE, not silently reshaped."""
    spans = [s for s in spans if isinstance(s, dict) and s.get("span_id")]
    if not spans:
        return "no lifecycle spans"
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str, list] = {}
    roots = []
    for s in spans:
        parent = s.get("parent_id", "")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    for kids in children.values():
        kids.sort(key=lambda s: (s.get("start_ns", 0), s["span_id"]))
    roots.sort(key=lambda s: (s.get("start_ns", 0), s["span_id"]))

    _ATTR_SKIP = (
        "name", "trace_id", "span_id", "parent_id", "start_ns",
        "end_ns", "kind",
    )

    def line(s: dict, depth: int) -> str:
        # explicit nulls from a foreign writer must not TypeError here
        dur_ms = (
            max(
                0,
                (_num(s.get("end_ns"), 0) or 0)
                - (_num(s.get("start_ns"), 0) or 0),
            )
            / 1e6
        )
        text = f"{'  ' * depth}{s.get('name', '?')}"
        if s.get("kind") == "point":
            text += "  ·"
        else:
            text += f"  {dur_ms:.1f}ms"
        attrs = {
            k: v
            for k, v in s.items()
            if k not in _ATTR_SKIP and v not in ("", None)
        }
        if attrs:
            text += "  " + " ".join(
                f"{k}={v}" for k, v in sorted(attrs.items())
            )
        return text

    out: list[str] = []

    def walk(s: dict, depth: int) -> None:
        out.append(line(s, depth))
        for kid in children.get(s["span_id"], []):
            walk(kid, depth + 1)

    root_trace = roots[0].get("trace_id", "")
    if root_trace:
        out.append(f"trace {root_trace}")
    for i, r in enumerate(roots):
        if i:
            out.append("(orphan subtree — parent span missing)")
        walk(r, 0)
    return "\n".join(out)


_CLASS = {
    "error": "ERROR",
    "start": "START",
    "success": "OK",
    "failure": "FAIL",
    "crash": "CRASH",
    "incomplete": "INCOMPLETE",
    "message": "MESSAGE",
    "metric": "METRIC",
    "other": "OTHER",
    "internal_err": "INTERNAL_ERR",
}


class PrettyPrinter:
    def __init__(self, ow: OutputWriter):
        self._ow = ow
        self._start = time.time()
        self._lock = threading.Lock()
        self._failed = 0
        self._count = 0
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------- internal

    def _print(self, idx: int, iid: str, cls: str, msg: str = "") -> None:
        elapsed = max(0.0, time.time() - self._start)
        self._ow.infof(
            "%9.4fs %12s << %s >> %s", elapsed, _CLASS.get(cls, "OTHER"), iid, msg
        )

    def _process_stdout(self, idx: int, iid: str, stream: IO[str]) -> None:
        ok = failed = False
        try:
            for line in stream:
                parsed = parse_event_line(line)
                if parsed is None:
                    if line.strip():
                        self._print(idx, iid, "other", line.rstrip())
                    continue
                _, evt = parsed
                typ = evt.get("type")
                if typ == "success":
                    ok = True
                    self._print(idx, iid, "success")
                elif typ == "failure":
                    failed = True
                    self._print(idx, iid, "failure", evt.get("error", ""))
                elif typ == "crash":
                    failed = True
                    self._print(
                        idx,
                        iid,
                        "crash",
                        f"{evt.get('error', '')} {evt.get('stacktrace', '')}",
                    )
                elif typ == "message":
                    self._print(idx, iid, "message", evt.get("message", ""))
                elif typ == "start":
                    self._print(idx, iid, "start", str(evt.get("runenv", "")))
                elif typ == "metric":
                    self._print(idx, iid, "metric", str(evt.get("metric", "")))
                elif typ in ("stage_start", "stage_end"):
                    pass
                else:
                    self._print(idx, iid, "internal_err", f"unknown event: {evt}")
        finally:
            if not ok and not failed:
                self._print(idx, iid, "incomplete")
            with self._lock:
                if not ok or failed:
                    self._failed += 1

    def _process_stderr(self, idx: int, iid: str, stream: IO[str]) -> None:
        for line in stream:
            if line.strip():
                self._print(idx, iid, "error", line.rstrip())

    # ------------------------------------------------------------------ API

    def fail_start(self, iid: str, message: str) -> None:
        """Report an instance that failed to start (``pretty.go:92-97``)."""
        with self._lock:
            self._count += 1
            idx = self._count - 1
            self._failed += 1
        self._print(idx, iid, "incomplete", f"failed to start: {message}")

    def manage(self, iid: str, stdout: IO[str], stderr: IO[str]) -> None:
        """Consume an instance's streams in the background."""
        with self._lock:
            self._count += 1
            idx = self._count - 1
        for target, stream in (
            (self._process_stdout, stdout),
            (self._process_stderr, stderr),
        ):
            t = threading.Thread(
                target=target, args=(idx, iid, stream), daemon=True
            )
            t.start()
            self._threads.append(t)

    def wait(self, timeout: float | None = None) -> int:
        """Wait for all streams to end; returns the failed count
        (``pretty.go:75-88``)."""
        deadline = None if timeout is None else time.time() + timeout
        for t in self._threads:
            t.join(
                timeout=None if deadline is None else max(0.0, deadline - time.time())
            )
        with self._lock:
            return self._failed

    def drained(self) -> bool:
        """True when every stream reader has exited (hit EOF). Callers
        must check this before closing the underlying pipe files —
        closing a file another thread is blocked reading deadlocks in
        CPython."""
        return not any(t.is_alive() for t in self._threads)
