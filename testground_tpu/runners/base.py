"""Runner interface (``pkg/api/runner.go:17-34``)."""

from __future__ import annotations

import abc
import threading
from typing import BinaryIO

from testground_tpu.api import CollectionInput, RunInput, RunOutput
from testground_tpu.rpc import OutputWriter

__all__ = ["Runner", "Terminatable", "HealthcheckedRunner", "RunnerOutcomeError"]


class RunnerOutcomeError(Exception):
    """Raised by a runner when the run executed but failed."""


class Runner(abc.ABC):
    """A runner takes a test plan in executable form and schedules a run of a
    particular test case within it."""

    @abc.abstractmethod
    def id(self) -> str:
        """Canonical identifier, e.g. ``local:exec``."""

    @abc.abstractmethod
    def run(
        self, job: RunInput, ow: OutputWriter, cancel: threading.Event
    ) -> RunOutput:
        """Run a test case. ``cancel`` is set on kill/timeout; runners must
        poll it (the Python analog of the reference's ctx cancellation)."""

    @abc.abstractmethod
    def compatible_builders(self) -> list[str]:
        """Builder IDs whose artifacts this runner can work with."""

    def config_type(self) -> type | None:
        """Dataclass type for this runner's config, or None."""
        return None

    def collect_outputs(
        self, inp: CollectionInput, w: BinaryIO, ow: OutputWriter
    ) -> None:
        """Gather outputs from a run into a tar.gz written to ``w``
        (default layout collection lives in ``runners.outputs``)."""
        from .outputs import collect_run_outputs

        collect_run_outputs(inp.env.dirs.outputs(), inp.run_id, w)


class Terminatable(abc.ABC):
    """Optional runner capability (``pkg/api/runner.go:117-121``)."""

    @abc.abstractmethod
    def terminate_all(self, ow: OutputWriter) -> None: ...


class HealthcheckedRunner(abc.ABC):
    """Optional runner capability (``pkg/api/engine.go`` Healthchecker)."""

    @abc.abstractmethod
    def healthcheck(self, fix: bool, ow: OutputWriter, env=None):
        """Returns a healthcheck report (``pkg/api/healthcheck.go:17-56``).
        ``env`` is the engine's EnvConfig — checks must validate the home
        the runs will actually use, not re-resolve $TESTGROUND_HOME."""
