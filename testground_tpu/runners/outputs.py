"""Run-output collection.

Twin of the reference's ``pkg/runner/common.go:42-116``: walk
``<outputs>/<plan>/<run-id>`` and stream it as a gzipped tarball. The on-disk
layout written by runners is ``<outputs>/<plan>/<run-id>/<group>/<instance>/``
with ``run.out`` / ``run.err`` / ``metrics.out`` files
(``local_docker.go:258-267``).
"""

from __future__ import annotations

import os
import tarfile
from typing import BinaryIO

__all__ = ["collect_run_outputs", "instance_output_dir", "find_run_dir"]


def instance_output_dir(
    outputs_root: str, plan: str, run_id: str, group: str, instance: int
) -> str:
    return os.path.join(outputs_root, plan, run_id, group, str(instance))


def find_run_dir(outputs_root: str, run_id: str) -> str | None:
    """Locate ``<outputs>/<plan>/<run-id>`` without knowing the plan."""
    if not os.path.isdir(outputs_root):
        return None
    for plan in sorted(os.listdir(outputs_root)):
        cand = os.path.join(outputs_root, plan, run_id)
        if os.path.isdir(cand):
            return cand
    return None


def collect_run_outputs(outputs_root: str, run_id: str, w: BinaryIO) -> None:
    """Write a tar.gz of the run's output tree to ``w``. Entries are rooted
    at ``<run-id>/...`` so extraction produces one directory per run."""
    run_dir = find_run_dir(outputs_root, run_id)
    if run_dir is None:
        raise FileNotFoundError(f"no outputs found for run {run_id}")
    with tarfile.open(fileobj=w, mode="w:gz") as tar:
        tar.add(run_dir, arcname=run_id)
