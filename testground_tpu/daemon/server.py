"""Daemon HTTP server (placeholder; full routes land with the daemon
milestone)."""

from __future__ import annotations


def serve() -> int:
    raise NotImplementedError("daemon HTTP server lands with the daemon milestone")
