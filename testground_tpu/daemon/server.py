"""Daemon HTTP server — the L5 tier (``pkg/daemon/daemon.go``).

A long-lived process owning ONE engine (worker pool + task store) that any
number of CLI clients talk to over HTTP, mirroring the reference's route
surface (``daemon.go:83-101``) and bearer-token auth (``daemon.go:49-70``):

    POST /run /build /tasks /status /logs /outputs /terminate
         /healthcheck /kill /delete /build/purge /plan/import
    GET  / /tasks /logs /outputs /journal /stats /perf /stream /metrics
         /trace /artifact /data /dashboard /describe /kill /delete

The GET tier is the reference's web-dashboard surface (``daemon.go:83-91``,
``dashboard.go:44-75``): ``/journal`` returns a task's result journal,
``/data`` returns one measurement's sampled rows (the InfluxDB-table
analog, served from the metrics viewer), ``/dashboard`` renders the
task list / per-task measurement tables as HTML, ``/describe`` serves a
daemon-hosted plan's manifest to remote CLIs, and ``/kill`` + ``/delete``
are the same state-changing verbs the reference exposes on GET
(``daemon.go:87-88``) — note they mutate on GET exactly like the
reference's, so dashboards must not prefetch links.

Transport notes (deviations are simplifications, not semantics):

- requests are plain JSON bodies, not multipart tar uploads; plan sources
  reach the daemon either via its own ``$TESTGROUND_HOME/plans`` or the
  ``/plan/import`` endpoint, whose body is a raw ``.tar.gz`` of the plan
  directory (the reference tars plan+sdk into the /run request itself,
  ``client.go:84-228``);
- ``/run`` and ``/build`` respond over the rpc chunk protocol (progress
  chunks + a result chunk holding the task id), like the reference;
- ``/logs`` streams the task's chunk-lines until completion when
  ``follow`` is set (``engine.go:461-558`` semantics);
- ``/outputs`` streams the run's tar.gz bytes directly with a gzip
  content type (the reference wraps them in base64 binary chunks).

The server is a stdlib ``ThreadingHTTPServer`` — every connection gets a
thread; the engine's own locks make the shared state safe.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import signal
import tarfile
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from testground_tpu.api import (
    Composition,
    TestPlanManifest,
    generate_default_run,
)
from testground_tpu.config import EnvConfig
from testground_tpu.engine import Engine
from testground_tpu.logging_ import S
from testground_tpu.rpc import OutputWriter

__all__ = ["Daemon", "serve"]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    daemon_ref: "Daemon" = None  # bound per-daemon via a subclass

    # ------------------------------------------------------------ plumbing

    def log_message(self, fmt, *args):  # route http.server logs into ours
        S().debug("daemon http: " + fmt, *args)

    @property
    def engine(self) -> Engine:
        return self.daemon_ref.engine

    def _authed(self) -> bool:
        """Bearer-token middleware (``daemon.go:49-70``): with no tokens
        configured the daemon is open, like the reference's default."""
        tokens = self.daemon_ref.tokens
        if not tokens:
            return True
        hdr = self.headers.get("Authorization", "")
        return hdr.startswith("Bearer ") and hdr[len("Bearer ") :] in tokens

    def _json_body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw or b"{}")

    def _send_json(self, obj, code: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(self, msg: str, code: int = 400) -> None:
        self._send_json({"error": msg}, code)

    def _start_stream(self, content_type: str = "application/x-ndjson"):
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

    def _write_chunked(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")

    def _end_chunked(self) -> None:
        self.wfile.write(b"0\r\n\r\n")

    # ------------------------------------------------------------- routing

    def do_GET(self):  # noqa: N802 — stdlib naming
        if not self._authed():
            return self._send_error_json("unauthorized", 401)
        from urllib.parse import parse_qs, urlparse

        url = urlparse(self.path)
        # states/types are list-valued filters (storage.filter uses `in`
        # membership — a scalar string would substring-match); every other
        # key is a scalar and takes the first occurrence, matching the
        # reference's mux.Vars semantics.
        q = {
            k: (v if k in ("states", "types") else v[0])
            for k, v in parse_qs(url.query).items()
        }
        handlers = {
            "/": self._root_redirect,
            "/tasks": lambda: self._tasks(q),
            "/journal": lambda: self._journal(q),
            "/stats": lambda: self._stats(q),
            "/perf": lambda: self._perf(q),
            "/diff": lambda: self._diff(q),
            "/stream": lambda: self._stream(q),
            "/metrics": lambda: self._metrics(q),
            "/trace": lambda: self._trace(q),
            "/artifact": lambda: self._artifact(q),
            "/data": lambda: self._data(q),
            "/dashboard": lambda: self._dashboard(q),
            "/describe": lambda: self._describe(q),
            # the reference serves kill/delete/logs/outputs on GET too
            # (daemon.go:85-91, dashboard links); the POST forms carry the
            # same semantics
            "/kill": lambda: self._kill(q),
            "/delete": lambda: self._delete(q),
            "/logs": lambda: self._get_logs(q),
            "/outputs": lambda: self._get_outputs(q),
            # control plane (docs/OBSERVABILITY.md "Control plane"):
            # fleet summary for `tg top`, daemon event-journal tail
            "/fleet": lambda: self._fleet(q),
            "/events": lambda: self._events(q),
        }
        h = handlers.get(url.path)
        if h is None:
            return self._send_error_json("not found", 404)
        try:
            return h()
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            S().warning("daemon GET %s failed: %s", url.path, e)
            try:
                self._send_error_json(str(e), 500)
            except Exception:  # noqa: BLE001 — response already started
                pass

    def do_POST(self):  # noqa: N802
        if not self._authed():
            return self._send_error_json("unauthorized", 401)
        route = self.path.split("?")[0]
        handlers = {
            "/run": self._run,
            "/build": self._build,
            "/tasks": self._tasks,
            "/status": self._status,
            "/logs": self._logs,
            "/outputs": self._outputs,
            "/terminate": self._terminate,
            "/healthcheck": self._healthcheck,
            "/kill": self._kill,
            # fleet controller (docs/FLEET.md): checkpoint-and-requeue a
            # running task / drain the whole daemon gracefully
            "/preempt": self._preempt,
            "/drain": self._drain,
            "/delete": self._delete,
            "/build/purge": self._build_purge,
        }
        try:
            if route == "/plan/import":
                return self._plan_import()
            if route not in handlers:
                return self._send_error_json("not found", 404)
            return handlers[route](self._json_body())
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            S().warning("daemon %s failed: %s", route, e)
            try:
                self._send_error_json(str(e), 500)
            except Exception:  # noqa: BLE001 — response already started
                pass

    # ------------------------------------------------------------- handlers

    def _safe_plan_dir(self, name: str) -> str:
        """Resolve a plan name inside the daemon's plans dir, rejecting
        anything that is not a single path component — otherwise a client
        could point plan resolution (manifest read + sources_dir, or the
        rmtree in /plan/import) at arbitrary daemon-writable paths."""
        if (
            not name
            or name != os.path.basename(name)
            or name in (".", "..")
        ):
            raise ValueError(f"invalid plan name {name!r}")
        # a single path component cannot escape the plans dir lexically;
        # no realpath comparison so operator-made symlinked plans keep working
        return os.path.join(self.engine.env.dirs.plans(), name)

    def _load_plan_manifest(self, plan: str):
        """Resolve a daemon-hosted plan → (plan_dir, manifest), or None
        after sending the 400/404 error response. Shared by /run, /build,
        and /describe so the resolution rules cannot drift."""
        try:
            plan_dir = self._safe_plan_dir(plan)
        except ValueError as e:
            self._send_error_json(str(e), 400)
            return None
        manifest_path = os.path.join(plan_dir, "manifest.toml")
        if not os.path.isfile(manifest_path):
            self._send_error_json(
                f"plan {plan!r} not found on the daemon; "
                "import it with `tg plan import` against --endpoint",
                404,
            )
            return None
        return plan_dir, TestPlanManifest.load_file(manifest_path)

    def _queue(self, body: dict, kind: str) -> None:
        comp = Composition.from_dict(body["composition"])
        if kind == "run":
            # server-side run preparation: a raw-client composition may
            # arrive without [[runs]]; synthesize the default run like the
            # reference daemon does during PrepareForRun
            # (composition_preparation.go:93-110 via supervisor.go:494-518)
            comp = generate_default_run(comp)
        resolved = self._load_plan_manifest(comp.global_.plan)
        if resolved is None:
            return
        plan_dir, manifest = resolved
        if kind == "run":
            # admission-at-submit (docs/FLEET.md): the `tg check` rules
            # engine runs server-side BEFORE the task takes a queue
            # slot — a composition that would only fail at claim time
            # is refused now, with every violation and the same rule
            # ids `tg check` reports. Daemon-boundary only: the
            # in-process engine keeps accepting anything, so local
            # experiments (and tests) can still queue bad compositions
            # deliberately.
            findings = self.engine.admission_findings(comp, manifest)
            if findings:
                self.engine.note_refused(
                    comp, [f.rule for f in findings], kind=kind
                )
                return self._send_error_json(
                    "composition refused at submit (tg check): "
                    + "; ".join(
                        f"[{f.rule}] {f.message}" for f in findings
                    ),
                    422,
                )
        queue = (
            self.engine.queue_run if kind == "run" else self.engine.queue_build
        )
        created_by = None
        if isinstance(body.get("created_by"), dict):
            from testground_tpu.engine.task import CreatedBy

            created_by = CreatedBy.from_dict(body["created_by"])
        task_id = queue(
            comp,
            manifest,
            sources_dir=plan_dir,
            priority=int(body.get("priority", 0)),
            created_by=created_by,
            # lifecycle tracing (tracectx.py): adopt the submitter's
            # traceparent so the task's span tree roots at the client's
            # submit span; absent/malformed → the engine mints fresh
            trace_parent=self.headers.get("traceparent", ""),
        )
        # chunked rpc response: progress line + result chunk (the wire
        # shape the reference's ParseRunResponse expects, client.go:402)
        self._start_stream()
        ow = OutputWriter(sink=_ChunkSink(self))
        ow.infof("%s is queued with ID: %s", kind, task_id)
        ow.write_result({"task_id": task_id})
        self._end_chunked()

    def _run(self, body: dict) -> None:
        self._queue(body, "run")

    def _build(self, body: dict) -> None:
        self._queue(body, "build")

    def _tasks(self, body: dict) -> None:
        def when(key):
            v = body.get(key)
            if v is None:
                return None
            try:
                return float(v)
            except (TypeError, ValueError):
                raise ValueError(f"invalid {key}: {v!r}") from None

        try:
            before, after = when("before"), when("after")
        except ValueError as e:
            return self._send_error_json(str(e), 400)
        def listy(key):
            # POST bodies carry JSON lists; a bare string (hand-rolled
            # client) must become a one-element list, not a substring
            # matcher inside storage.filter's `in` membership test.
            v = body.get(key)
            if not v:
                return None
            return [v] if isinstance(v, str) else list(v)

        tasks = self.engine.tasks(
            states=listy("states"),
            types=listy("types"),
            before=before,
            after=after,
            limit=int(body.get("limit") or 0),
        )
        self._send_json({"tasks": [t.to_dict() for t in tasks]})

    def _status(self, body: dict) -> None:
        t = self.engine.get_task(body["task_id"])
        if t is None:
            return self._send_error_json(f"unknown task {body['task_id']}", 404)
        self._send_json({"task": t.to_dict()})

    def _root_redirect(self) -> None:
        """GET / → the dashboard (``daemon.go:91`` redirect)."""
        self.send_response(302)
        self.send_header("Location", "/dashboard")
        # explicit empty body: keep-alive clients (curl, browsers) would
        # otherwise read until timeout waiting for an unframed body
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _get_logs(self, q: dict) -> None:
        if "task_id" not in q:
            return self._send_error_json("task_id is required", 400)
        # never follow on GET: a dashboard link must terminate
        self._logs({"task_id": q["task_id"]})

    def _get_outputs(self, q: dict) -> None:
        if "runner" not in q or "run_id" not in q:
            return self._send_error_json(
                "runner and run_id are required", 400
            )
        self._outputs({"runner": q["runner"], "run_id": q["run_id"]})

    def _logs(self, body: dict) -> None:
        task_id = body["task_id"]
        follow = bool(body.get("follow"))
        # resolve the task BEFORE starting the chunked stream — once chunking
        # begins, a later error response would be written onto the same
        # keep-alive connection as protocol garbage
        if self.engine.get_task(task_id) is None:
            return self._send_error_json(f"unknown task {task_id}", 404)
        self._start_stream()
        try:
            for line in self.engine.logs(task_id, follow=follow):
                self._write_chunked(line.encode())
        finally:
            self._end_chunked()

    def _outputs(self, body: dict) -> None:
        runner = body["runner"]
        run_id = body["run_id"]
        # run ids are single path components (xid-style, engine/task.py);
        # anything else could walk the collection root out of the outputs
        # tree and exfiltrate arbitrary directories as a tgz
        if (
            run_id != os.path.basename(run_id)
            or run_id in ("", ".", "..")
            or "/" in run_id
            or "\\" in run_id
        ):
            return self._send_error_json(
                f"invalid run id {run_id!r}", 400
            )
        # spool to a temp file so HTTP status can still signal failure
        with tempfile.TemporaryFile() as spool:
            from testground_tpu.rpc import discard_writer

            self.engine.do_collect_outputs(
                runner, run_id, spool, discard_writer()
            )
            size = spool.tell()
            spool.seek(0)
            self.send_response(200)
            self.send_header("Content-Type", "application/gzip")
            self.send_header("Content-Length", str(size))
            self.end_headers()
            shutil.copyfileobj(spool, self.wfile)

    def _terminate(self, body: dict) -> None:
        buf = io.StringIO()
        if body.get("builder"):
            ref, ctype = body["builder"], "builder"
        elif body.get("runner"):
            ref, ctype = body["runner"], "runner"
        else:
            return self._send_error_json(
                "specify exactly one of runner or builder", 400
            )
        self.engine.do_terminate(
            ref, OutputWriter(sink=None, echo=buf), ctype=ctype
        )
        self._send_json({"output": buf.getvalue()})

    def _healthcheck(self, body: dict) -> None:
        buf = io.StringIO()
        report = self.engine.do_healthcheck(
            body["runner"], bool(body.get("fix")), OutputWriter(sink=None, echo=buf)
        )
        self._send_json({"report": report.to_dict(), "output": buf.getvalue()})

    def _kill(self, body: dict) -> None:
        task_id = body.get("task_id")
        if not task_id:  # also reachable from the GET form's URL bar
            return self._send_error_json("task_id param required", 400)
        ok = self.engine.kill(task_id)
        self._send_json({"killed": bool(ok)})

    def _preempt(self, body: dict) -> None:
        """Checkpoint-and-requeue one running task (docs/FLEET.md): the
        live-migration verb. The engine answers with queued/refused
        detail; actually stopping happens at the run's next chunk
        boundary."""
        task_id = body.get("task_id")
        if not task_id:
            return self._send_error_json("task_id param required", 400)
        self._send_json(self.engine.preempt(task_id))

    def _drain(self, body: dict) -> None:
        """Graceful drain + shutdown (docs/FLEET.md): stop claiming,
        preempt running runs (checkpointed ones requeue resumable),
        cancel builds, then exit. The drain runs inline so the response
        carries its result; the daemon shutdown runs on a timer thread —
        httpd.shutdown() from a handler thread's request would otherwise
        close the socket under this very response."""
        timeout = float(body.get("timeout_secs", 30.0) or 30.0)
        res = self.engine.drain(timeout_secs=timeout)
        self._send_json(res)
        t = threading.Timer(0.2, self.daemon_ref.stop)
        t.daemon = True
        t.start()

    def _describe(self, q: dict) -> None:
        """GET /describe?plan= — the daemon-side manifest, so a remote CLI
        can fill composition defaults for plans that exist only on the
        daemon (this framework hosts plans daemon-side, where the
        reference ships local sources per request, ``client.go:84-228``)."""
        resolved = self._load_plan_manifest(q.get("plan", ""))
        if resolved is None:
            return
        self._send_json({"manifest": resolved[1].to_dict()})

    def _delete(self, body: dict) -> None:
        """Delete a finished task's record + log (``daemon.go:88``)."""
        task_id = body.get("task_id")
        if not task_id:
            return self._send_error_json("task_id param required", 400)
        try:
            ok = self.engine.delete_task(task_id)
        except ValueError as e:  # task still live
            return self._send_error_json(str(e), 409)
        self._send_json({"deleted": bool(ok)})

    def _build_purge(self, body: dict) -> None:
        buf = io.StringIO()
        self.engine.do_build_purge(
            body["builder"], body.get("testplan", ""), OutputWriter(sink=None, echo=buf)
        )
        self._send_json({"output": buf.getvalue()})

    # ------------------------------------------------- dashboard tier (GET)

    def _send_html(self, body: str, code: int = 200) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _journal(self, q: dict) -> None:
        """GET /journal?task_id= — the task's result journal
        (``daemon.go:90`` getJournalHandler)."""
        task_id = q.get("task_id", "")
        t = self.engine.get_task(task_id)
        if t is None:
            return self._send_error_json(f"unknown task {task_id}", 404)
        journal = (
            t.result.get("journal", {}) if isinstance(t.result, dict) else {}
        )
        self._send_json({"task_id": task_id, "journal": journal})

    def _stats(self, q: dict) -> None:
        """GET /stats?task_id= — the task's sim telemetry summary (the
        ``tg stats`` backend; docs/OBSERVABILITY.md): identity + the
        journal's sim/telemetry/events sections, i.e. everything the
        console table needs in one round trip. The payload shape is
        Task.stats_payload — shared with the in-process CLI."""
        task_id = q.get("task_id", "")
        t = self.engine.get_task(task_id)
        if t is None:
            return self._send_error_json(f"unknown task {task_id}", 404)
        self._send_json(t.stats_payload())

    def _diff(self, q: dict) -> None:
        """GET /diff?a=&b=[&planes=p1,p2] — the differential run
        analysis document (the ``tg diff`` backend; docs/OBSERVABILITY.md
        "Run diff"): deterministic counters compared exactly, throughput
        judged from per-chunk samples. Built by Engine.diff_tasks — the
        one codepath shared with the in-process CLI — so it works
        against archived tasks over HTTP."""
        a, b = q.get("a", ""), q.get("b", "")
        if not a or not b:
            return self._send_error_json("a and b task params required", 400)
        try:
            doc = self.engine.diff_tasks(a, b, planes=q.get("planes"))
        except FileNotFoundError as e:
            return self._send_error_json(str(e), 404)
        except ValueError as e:
            return self._send_error_json(str(e), 400)
        self._send_json(doc)

    def _perf(self, q: dict) -> None:
        """GET /perf?task_id= — the task's performance-ledger payload
        (the ``tg perf`` backend; docs/OBSERVABILITY.md): identity, the
        journal's sim block, the sim.perf ledger, and the supervisor's
        task-level timings. Payload shape is Task.perf_payload — shared
        with the in-process CLI."""
        task_id = q.get("task_id", "")
        t = self.engine.get_task(task_id)
        if t is None:
            return self._send_error_json(f"unknown task {task_id}", 404)
        self._send_json(t.perf_payload())

    def _stream(self, q: dict) -> None:
        """GET /stream?task_id=[&follow=0][&families=perf,slo] — ndjson
        stream of a task's live observability rows (telemetry / perf /
        SLO breaches / run spans), tailed from the run outputs as they
        are appended: the ``tg watch`` backend (docs/OBSERVABILITY.md
        "Run health plane"). Follows by default — an already-finished
        task replays its full history, then the stream closes; a
        running task streams until it completes."""
        task_id = q.get("task_id", "") or q.get("task", "")
        if not task_id:
            return self._send_error_json("task_id is required", 400)
        # resolve BEFORE starting the chunked stream (the /logs rule)
        if self.engine.get_task(task_id) is None:
            return self._send_error_json(f"unknown task {task_id}", 404)
        follow = q.get("follow", "1") not in ("0", "false", "no")
        families = None
        if q.get("families"):
            from testground_tpu.engine.stream import STREAM_FAMILIES

            families = tuple(
                f.strip() for f in q["families"].split(",") if f.strip()
            )
            known = {name for name, _ in STREAM_FAMILIES}
            unknown = sorted(set(families) - known)
            if unknown or not families:
                # a typo'd (or all-blank, e.g. "families=,") family list
                # would otherwise follow silently, row-less, for the
                # task's whole lifetime
                return self._send_error_json(
                    f"unknown stream families {unknown}; families: "
                    f"{sorted(known)}",
                    400,
                )
        self._start_stream()
        try:
            # heartbeat: a blank ndjson line at least every 15 s of
            # idle, so a queued task / long compile / quiet soak cannot
            # trip a follower's socket read timeout
            for row in self.engine.stream_rows(
                task_id, follow=follow, families=families, heartbeat_secs=15.0
            ):
                self._write_chunked(
                    b"\n"
                    if row is None
                    else (json.dumps(row) + "\n").encode()
                )
        finally:
            self._end_chunked()

    # Task-label cardinality bound for one /metrics scrape (most recent
    # first — a scraper watches the daemon's working set, not history).
    # The default; .env.toml ``[daemon] metrics_task_limit`` overrides.
    _METRICS_TASKS_MAX = 200

    def _metrics(self, q: dict) -> None:
        """GET /metrics — Prometheus text exposition (format 0.0.4):
        task gauges, cumulative flow counters, performance-ledger and
        SLO gauges for the most recent tasks, so any standard scraper
        can watch a daemon (docs/OBSERVABILITY.md). Truncation is never
        silent: ``tg_scrape_tasks_total`` / ``tg_scrape_tasks_elided``
        report how much of the task store one scrape covered."""
        from testground_tpu.metrics.prometheus import (
            CONTENT_TYPE,
            render_prometheus,
        )

        limit = (
            int(self.daemon_ref.env.daemon.metrics_task_limit or 0)
            or self._METRICS_TASKS_MAX
        )
        fleet = (
            self.engine.fleet_info()
            if hasattr(self.engine, "fleet_info")
            else None
        )
        body = render_prometheus(
            self.engine.tasks(), per_task_limit=limit, fleet=fleet
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _fleet(self, q: dict) -> None:
        """GET /fleet — the daemon-wide summary behind ``tg top``:
        worker slots, queue depth by priority, per-state counts over
        the FULL task store, pack occupancy, and one row per
        queued/running task with live ticks/s and breach counts."""
        self._send_json(self.engine.fleet_payload())

    def _events(self, q: dict) -> None:
        """GET /events?since=<byte offset>[&follow=1] — tail the daemon
        event journal (engine/events.py) as ndjson. One-shot by
        default: replays complete lines from ``since`` to EOF, then
        sends a ``{"type": "_tail", "offset": N}`` marker whose offset
        resumes the next call. With ``follow=1``, keeps tailing
        (heartbeat blank line every 15 s of idle) until the client
        disconnects. 404 while the journal does not exist yet."""
        from testground_tpu.engine.stream import _Tail

        path = self.engine.events.path
        try:
            since = int(q.get("since") or 0)
        except (TypeError, ValueError):
            return self._send_error_json("invalid since", 400)
        if not os.path.exists(path):
            return self._send_error_json("no events journal yet", 404)
        follow = q.get("follow", "0") not in ("0", "false", "no", "")
        tail = _Tail(path)
        tail.offset = max(0, since)
        self._start_stream()
        try:
            last_data = time.monotonic()
            while True:
                wrote = False
                for row in tail.read_new():
                    self._write_chunked(
                        (json.dumps(row) + "\n").encode()
                    )
                    wrote = True
                if wrote:
                    last_data = time.monotonic()
                if not follow:
                    self._write_chunked(
                        (
                            json.dumps(
                                {"type": "_tail", "offset": tail.offset}
                            )
                            + "\n"
                        ).encode()
                    )
                    break
                if time.monotonic() - last_data >= 15.0:
                    self._write_chunked(b"\n")  # heartbeat
                    last_data = time.monotonic()
                time.sleep(0.15)
        finally:
            self._end_chunked()

    # Event cap for one /trace JSON response (sim_trace.jsonl itself is
    # unbounded; the full file streams via /artifact).
    _TRACE_EVENTS_MAX = 50_000

    def _trace(self, q: dict) -> None:
        """GET /trace?task_id=[&limit=] — the task's flight-recorder
        events (``sim_trace.jsonl``, read back from the outputs tree —
        every run dir of a multi-``[[runs]]`` task contributes) plus the
        journal's trace summary: the ``tg trace`` backend
        (docs/OBSERVABILITY.md). Responses cap at ``_TRACE_EVENTS_MAX``
        events; fetch the whole stream via ``/artifact``."""
        from testground_tpu.sim.trace import read_trace_events

        task_id = q.get("task_id", "")
        t = self.engine.get_task(task_id)
        if t is None:
            return self._send_error_json(f"unknown task {task_id}", 404)
        journal = (
            t.result.get("journal", {}) if isinstance(t.result, dict) else {}
        )
        try:
            limit = int(q.get("limit") or 0)
        except (TypeError, ValueError):
            return self._send_error_json("invalid limit", 400)
        # a JSON response must stay bounded — sim_trace.jsonl is not
        # (see /artifact, which streams the whole file): an absent/0
        # limit gets the server-side default instead of a full slurp
        limit = (
            self._TRACE_EVENTS_MAX
            if limit <= 0
            else min(limit, self._TRACE_EVENTS_MAX)
        )
        # read one past the limit so an exactly-limit-sized stream is
        # not falsely reported as truncated
        events = read_trace_events(
            self.engine.env.dirs.outputs(), t.plan, task_id, limit=limit + 1
        )
        payload = {
            "task_id": task_id,
            "trace": journal.get("trace", {}),
            "events": events[:limit],
        }
        if len(events) > limit:
            # never silently incomplete: a capped response says so, and
            # points at the full stream
            payload["truncated"] = True
            payload["limit"] = limit
        self._send_json(payload)

    # Observability artifacts a dashboard task page may link: file names
    # are a closed whitelist (never client paths) and the run dir must
    # belong to the task, so the route cannot read outside the task's
    # outputs.
    _ARTIFACT_FILES = (
        "timeseries.jsonl",
        "sim_timeseries.jsonl",
        "sim_netmatrix.jsonl",
        "sim_latency.jsonl",
        "sim_perf.jsonl",
        "sim_phases.jsonl",
        "sim_slo.jsonl",
        "run_spans.jsonl",
        "sim_trace.jsonl",
        "trace_events.json",
        # lifecycle span tree (engine/tracetree.py): assembled at
        # archive time; task_trace.json opens in Perfetto directly
        "task_spans.jsonl",
        "task_trace.json",
    )
    # Instance-side artifacts live NESTED under <group>/<instance>/ —
    # still a closed basename whitelist, with every path component
    # validated, so the route cannot read outside the task's outputs.
    _ARTIFACT_NESTED = ("profile-cpu.pstats",)
    # jax.profiler capture layout under the run dir: the xplane protos
    # land at profiles/plugins/profile/<session>/<host>.xplane.pb —
    # served so a remote `tg` session can fetch the capture the phase
    # table (`tg perf --phases`) points at. Suffix-whitelisted (never
    # client paths) with every component validated, like the nested
    # instance artifacts.
    _PROFILE_PREFIX = ("profiles", "plugins", "profile")
    _PROFILE_SUFFIXES = (".xplane.pb",)
    # checkpoint snapshots (docs/CHECKPOINT.md) live at
    # checkpoints/ckpt-<tick>.npz under the run dir — served so an
    # operator can migrate a run between machines (`GET /artifact` →
    # drop into the destination run dir → `tg run resume`). Exact
    # depth + name-shape validated, like the profile captures.
    _CHECKPOINT_PREFIX = "checkpoints"
    _CHECKPOINT_NAME = ("ckpt-", ".npz")

    @classmethod
    def _artifact_relpath(cls, name: str) -> str | None:
        """Validate an artifact name → safe run-dir-relative path, or
        None. Accepts the flat whitelist; a nested path (e.g.
        ``single/0/profile-cpu.pstats`` — the SDK's cProfile dump) whose
        basename is whitelisted and whose every component is a plain
        path segment; or a profiler capture file under
        ``profiles/plugins/profile/<session>/``."""
        if name in cls._ARTIFACT_FILES:
            return name
        parts = name.split("/")
        safe_parts = all(
            p and p not in (".", "..") and p == os.path.basename(p)
            and "\\" not in p
            for p in parts
        )
        if (
            len(parts) in (2, 3, 4)
            and parts[-1] in cls._ARTIFACT_NESTED
            and safe_parts
        ):
            return os.path.join(*parts)
        if (
            len(parts) == len(cls._PROFILE_PREFIX) + 2
            and tuple(parts[: len(cls._PROFILE_PREFIX)])
            == cls._PROFILE_PREFIX
            and parts[-1].endswith(cls._PROFILE_SUFFIXES)
            and safe_parts
        ):
            return os.path.join(*parts)
        if (
            len(parts) == 2
            and parts[0] == cls._CHECKPOINT_PREFIX
            and parts[-1].startswith(cls._CHECKPOINT_NAME[0])
            and parts[-1].endswith(cls._CHECKPOINT_NAME[1])
            and safe_parts
        ):
            return os.path.join(*parts)
        return None

    def _artifact(self, q: dict) -> None:
        """GET /artifact?task_id=&name=[&run=] — serve one whitelisted
        observability artifact from a task's run outputs dir (the
        dashboard's trace/telemetry/profile links)."""
        task_id = q.get("task_id", "")
        t = self.engine.get_task(task_id)
        if t is None:
            return self._send_error_json(f"unknown task {task_id}", 404)
        name = q.get("name", "")
        rel = self._artifact_relpath(name)
        if rel is None:
            return self._send_error_json(
                f"unknown artifact {name!r}; serving only "
                f"{list(self._ARTIFACT_FILES)} and per-instance "
                f"{list(self._ARTIFACT_NESTED)}",
                400,
            )
        rid = q.get("run", task_id)
        if rid != os.path.basename(rid) or not (
            rid == task_id or rid.startswith(task_id + "-")
        ):
            return self._send_error_json(f"invalid run id {rid!r}", 400)
        path = os.path.join(
            self.engine.env.dirs.outputs(), t.plan, rid, rel
        )
        if not os.path.isfile(path):
            return self._send_error_json(
                f"artifact {name} not found for run {rid}", 404
            )
        # stream, never slurp: sim_trace.jsonl is unbounded by design (a
        # long traced run can reach GBs) and the daemon owns every
        # running task — one dashboard click must not balloon its RSS.
        # Copy EXACTLY the declared length: the file may still be
        # growing (a RUNNING traced task flushes every chunk), and extra
        # bytes past Content-Length would corrupt the keep-alive
        # connection's framing for the next pipelined response.
        size = os.path.getsize(path)
        self.send_response(200)
        self.send_header(
            "Content-Type",
            "application/json"
            if name.endswith(".json")
            else "application/octet-stream"
            if name.endswith((".pstats", ".pb", ".npz"))
            else "application/x-ndjson",
        )
        self.send_header("Content-Length", str(size))
        self.end_headers()
        with open(path, "rb") as f:
            remaining = size
            while remaining > 0:
                chunk = f.read(min(1 << 16, remaining))
                if not chunk:  # file truncated underneath us: pad out
                    self.wfile.write(b" " * remaining)
                    break
                self.wfile.write(chunk)
                remaining -= len(chunk)

    def _data(self, q: dict) -> None:
        """GET /data?task_id=&metric= — one measurement's sampled rows
        (``daemon.go:83`` dataHandler; rows are the InfluxDB-table analog).
        ``metric`` accepts the bare metric name or the full
        ``results.<plan>-<case>.<metric>`` measurement string."""
        from testground_tpu.metrics import Viewer, measurement_name

        task_id = q.get("task_id", "")
        t = self.engine.get_task(task_id)
        if t is None:
            return self._send_error_json(f"unknown task {task_id}", 404)
        metric = q.get("metric", "")
        prefix = measurement_name(t.plan, t.case, "")
        if metric.startswith(prefix):
            metric = metric[len(prefix) :]
        if not metric:
            return self._send_error_json("metric query param required", 400)
        rows = Viewer(self.engine.env).get_data(
            t.plan, t.case, metric, run_id=task_id
        )
        self._send_json(
            {
                "measurement": measurement_name(t.plan, t.case, metric),
                "rows": [r.to_dict() for r in rows],
            }
        )

    def _dashboard(self, q: dict) -> None:
        """GET /dashboard[?task_id=] — HTML: the task list (``tmpl/
        tasks.html`` analog) or one task's measurement tables
        (``dashboard.go:44-75`` + ``tmpl/measurements.html``)."""
        import html as _html

        from testground_tpu.metrics import Viewer, measurement_name

        esc = _html.escape
        task_id = q.get("task_id", "")
        if not task_id:
            rows = []
            for t in self.engine.tasks(limit=100):
                rows.append(
                    "<tr>"
                    f'<td><a href="/dashboard?task_id={esc(t.id)}">{esc(t.id)}</a></td>'
                    f"<td>{esc(t.plan)}:{esc(t.case)}</td>"
                    f"<td>{esc(t.type.value)}</td>"
                    f"<td>{esc(t.state().state.value)}</td>"
                    f"<td>{esc(t.outcome().value)}</td>"
                    "</tr>"
                )
            return self._send_html(
                _page(
                    "testground tasks",
                    "<table><tr><th>task</th><th>plan:case</th><th>type</th>"
                    "<th>state</th><th>outcome</th></tr>"
                    + "".join(rows)
                    + "</table>",
                )
            )

        t = self.engine.get_task(task_id)
        if t is None:
            return self._send_html(_page("not found", "Cannot get task"), 404)
        viewer = Viewer(self.engine.env)
        all_data = viewer.get_all_data(t.plan, t.case, run_id=task_id)
        sections = []
        for metric in sorted(all_data):
            m = measurement_name(t.plan, t.case, metric)
            rows = all_data[metric]
            body = "".join(
                "<tr>"
                f"<td>{r.tick}</td><td>{esc(r.group_id)}</td>"
                f"<td>{r.fields.get('count', '')}</td>"
                f"<td>{_fmt(r.fields.get('mean'))}</td>"
                f"<td>{_fmt(r.fields.get('min'))}</td>"
                f"<td>{_fmt(r.fields.get('max'))}</td>"
                "</tr>"
                for r in rows
            )
            sections.append(
                f"<h2>{esc(m)}</h2>"
                "<table><tr><th>tick</th><th>group</th><th>count</th>"
                "<th>mean</th><th>min</th><th>max</th></tr>" + body + "</table>"
            )
        if not sections:
            sections = ["<p>No measurements for this test plan.</p>"]
        # multi-[[runs]] tasks store outputs under <task_id>-<run_id> dirs
        # (supervisor run_id framing); one link per run, else one for the
        # single-run task
        output_links = ""
        artifact_links = ""
        if t.runner:  # build tasks have no run outputs
            run_results = (
                t.result.get("runs") if isinstance(t.result, dict) else None
            )
            if isinstance(run_results, dict) and run_results:
                links = [
                    (f"outputs[{esc(rid)}]", f"{task_id}-{rid}")
                    for rid in run_results
                ]
            else:
                links = [("outputs", task_id)]
            output_links = "".join(
                f' · <a href="/outputs?runner={esc(t.runner)}&amp;run_id='
                f'{esc(rid)}">{label}</a>'
                for label, rid in links
            )
            # telemetry / trace artifacts actually present in the run
            # dir(s) — served by /artifact (whitelisted file names)
            per_run = []
            for _, rid in links:
                run_dir = os.path.join(
                    self.engine.env.dirs.outputs(), t.plan, rid
                )
                present = [
                    name
                    for name in self._ARTIFACT_FILES
                    if os.path.isfile(os.path.join(run_dir, name))
                ]
                # instance-side profiles (sdk/invoke.py cProfile dumps)
                # live under <group>/<instance>/ — link them like the
                # run-level artifacts, capped so a huge fleet of
                # profiled instances cannot flood the page
                import glob as _glob

                for base in self._ARTIFACT_NESTED:
                    hits = sorted(
                        _glob.glob(os.path.join(run_dir, "*", "*", base))
                    )[:16]
                    present.extend(
                        os.path.relpath(p, run_dir).replace(os.sep, "/")
                        for p in hits
                    )
                # profiler captures (profile=true / profile_chunks=N):
                # link the xplane protos so a remote session can fetch
                # the capture the phase table points at, capped like the
                # instance profiles
                for suffix in self._PROFILE_SUFFIXES:
                    hits = sorted(
                        _glob.glob(
                            os.path.join(
                                run_dir, *self._PROFILE_PREFIX, "*",
                                "*" + suffix,
                            )
                        )
                    )[:4]
                    present.extend(
                        os.path.relpath(p, run_dir).replace(os.sep, "/")
                        for p in hits
                    )
                if not present:
                    continue
                tag = (
                    f" [{esc(rid)}]"
                    if rid != task_id
                    else ""
                )
                per_run.append(
                    " · ".join(
                        f'<a href="/artifact?task_id={esc(task_id)}'
                        f"&amp;run={esc(rid)}&amp;name={esc(name)}\">"
                        f"{esc(name)}</a>"
                        for name in present
                    )
                    + tag
                )
            if per_run:
                artifact_links = (
                    "<p>artifacts: " + " &nbsp;|&nbsp; ".join(per_run) + "</p>"
                )
        header = (
            f"<p>task <code>{esc(task_id)}</code> — "
            f"{esc(t.plan)}:{esc(t.case)} — state {esc(t.state().state.value)}, "
            f"outcome {esc(t.outcome().value)} — "
            f'<a href="/journal?task_id={esc(task_id)}">journal</a> · '
            f'<a href="/stats?task_id={esc(task_id)}">stats</a> · '
            f'<a href="/perf?task_id={esc(task_id)}">perf</a> · '
            f'<a href="/trace?task_id={esc(task_id)}">trace</a> · '
            f'<a href="/logs?task_id={esc(task_id)}">logs</a>'
            + output_links
            + "</p>"
            + artifact_links
        )
        self._send_html(
            _page(f"{t.plan}:{t.case}", header + "".join(sections))
        )

    def _plan_import(self) -> None:
        """Body: raw tar.gz of a plan directory; ``?name=`` overrides."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(self.path).query)
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n)
        with tempfile.TemporaryDirectory() as td:
            with tarfile.open(fileobj=io.BytesIO(raw), mode="r:gz") as tar:
                tar.extractall(td, filter="data")
            entries = [e for e in os.listdir(td) if not e.startswith(".")]
            if len(entries) == 1 and os.path.isdir(os.path.join(td, entries[0])):
                src = os.path.join(td, entries[0])
                default_name = entries[0]
            else:
                src = td
                default_name = ""
            name = (q.get("name") or [default_name])[0]
            if not name:
                return self._send_error_json("plan name required", 400)
            if not os.path.isfile(os.path.join(src, "manifest.toml")):
                return self._send_error_json("archive has no manifest.toml", 400)
            try:
                dest = self._safe_plan_dir(name)
            except ValueError as e:
                return self._send_error_json(str(e), 400)
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(src, dest)
        self._send_json({"imported": name})


def _fmt(v) -> str:
    return f"{v:.3f}" if isinstance(v, (int, float)) else ""


def _page(title: str, body: str) -> str:
    """Minimal self-contained page shell (the tmpl/*.html + bootstrap
    analog, without the static asset tree). The title is escaped here (it
    can carry client-supplied plan/case strings); the body is the caller's
    already-escaped markup."""
    import html as _html

    title = _html.escape(title)
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{title}</title>"
        "<style>body{font-family:sans-serif;margin:2rem}"
        "table{border-collapse:collapse;margin:1rem 0}"
        "td,th{border:1px solid #999;padding:.3rem .6rem;text-align:left}"
        "th{background:#eee}</style></head>"
        f"<body><h1>{title}</h1>{body}</body></html>"
    )


class _ChunkSink:
    """File-like adapter: OutputWriter lines → HTTP chunked frames."""

    def __init__(self, handler: _Handler):
        self.h = handler

    def write(self, s: str) -> int:
        self.h._write_chunked(s.encode())
        return len(s)

    def flush(self) -> None:
        pass


class Daemon:
    """Owns the HTTP server + the engine (``daemon.New``,
    ``daemon.go:34-118``)."""

    def __init__(self, env: EnvConfig | None = None, listen: str = ""):
        self.env = env or EnvConfig.load()
        if not self.env.task_repo_explicit:
            self.env.daemon.scheduler.task_repo_type = "disk"
        self.engine = Engine.new_default(self.env)
        self.tokens = list(self.env.daemon.tokens)
        addr = listen or self.env.daemon.listen or "localhost:8042"
        host, _, port = addr.rpartition(":")
        handler = type("BoundHandler", (_Handler,), {"daemon_ref": self})
        self.httpd = ThreadingHTTPServer(
            (host or "localhost", int(port)), handler
        )
        self._thread: threading.Thread | None = None
        self._stop_lock = threading.Lock()
        self._stopped = False

    @property
    def address(self) -> str:
        h, p = self.httpd.server_address[:2]
        return f"http://{h}:{p}"

    def start(self) -> None:
        """Start workers + serve in a background thread (for tests)."""
        self.engine.start_workers()
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self.engine.start_workers()
        S().info("daemon listening on %s", self.address)

        def _on_sigterm(signum, frame):  # noqa: ARG001
            # graceful drain (docs/FLEET.md): checkpoint + requeue the
            # running work, journal daemon.drain, exit 0. Spawns a
            # thread because the handler runs ON the serving thread —
            # calling httpd.shutdown() here would deadlock serve_forever
            threading.Thread(
                target=self._drain_and_stop, daemon=True
            ).start()

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            pass  # not the main thread (embedded use) — no SIGTERM hook
        try:
            self.httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def _drain_and_stop(self) -> None:
        try:
            self.engine.drain()
        except Exception as e:  # noqa: BLE001 — still shut down
            S().warning("drain on SIGTERM failed: %s", e)
        self.stop()

    def stop(self) -> None:
        # idempotent: SIGTERM-drain, /drain's timer, and serve_forever's
        # finally may all reach here
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self.httpd.shutdown()
        self.httpd.server_close()
        self.engine.stop()


def serve(listen: str = "") -> int:
    Daemon(listen=listen).serve_forever()
    return 0
