"""HTTP daemon exposing the engine. Twin of the reference's ``pkg/daemon``."""

from .server import Daemon, serve

__all__ = ["Daemon", "serve"]
