"""HTTP daemon exposing the engine. Twin of the reference's ``pkg/daemon``."""
