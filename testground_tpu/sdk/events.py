"""Structured lifecycle events emitted by instances on stdout.

The reference emits zap-JSON lines parsed by the runner's PrettyPrinter
(``pkg/runner/pretty.go:113-180``: success/failure/crash/message/start/metric
events under an ``event`` key with a nanosecond ``ts``). This framework uses
the same envelope with an explicit ``type`` discriminator:

    {"ts": <ns>, "event": {"type": "success"}}
    {"ts": <ns>, "event": {"type": "failure", "error": "..."}}
    {"ts": <ns>, "event": {"type": "crash", "error": "...", "stacktrace": "..."}}
    {"ts": <ns>, "event": {"type": "message", "message": "..."}}
    {"ts": <ns>, "event": {"type": "start", "runenv": {...}}}
    {"ts": <ns>, "event": {"type": "metric", "metric": {...}}}
    {"ts": <ns>, "event": {"type": "stage_start"|"stage_end", "stage": "..."}}
"""

from __future__ import annotations

import json
import time
from typing import Any, TextIO

__all__ = ["EventEmitter", "parse_event_line"]


class EventEmitter:
    """Writes event lines to a stream (instance stdout and/or run.out)."""

    def __init__(self, *sinks: TextIO | None):
        self._sinks = [s for s in sinks if s is not None]

    def emit(self, event: dict[str, Any]) -> None:
        line = json.dumps({"ts": time.time_ns(), "event": event})
        for s in self._sinks:
            s.write(line + "\n")
            s.flush()

    def success(self) -> None:
        self.emit({"type": "success"})

    def failure(self, error: str) -> None:
        self.emit({"type": "failure", "error": error})

    def crash(self, error: str, stacktrace: str = "") -> None:
        self.emit({"type": "crash", "error": error, "stacktrace": stacktrace})

    def message(self, msg: str) -> None:
        self.emit({"type": "message", "message": msg})

    def start(self, runenv: dict) -> None:
        self.emit({"type": "start", "runenv": runenv})

    def metric(self, metric: dict) -> None:
        self.emit({"type": "metric", "metric": metric})

    def stage_start(self, name: str) -> None:
        self.emit({"type": "stage_start", "stage": name})

    def stage_end(self, name: str) -> None:
        self.emit({"type": "stage_end", "stage": name})


def parse_event_line(line: str) -> tuple[float, dict] | None:
    """Parse one stdout line into (unix_seconds, event) or None if the line
    is not a structured event (the PrettyPrinter prints those as Other)."""
    try:
        d = json.loads(line)
    except (json.JSONDecodeError, ValueError):
        return None
    if not isinstance(d, dict) or "event" not in d:
        return None
    evt = d["event"]
    if not isinstance(evt, dict) or "type" not in evt:
        return None
    return float(d.get("ts", 0)) / 1e9, evt
