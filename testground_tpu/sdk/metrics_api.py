"""Metrics API: ``runenv.R()`` (results) and ``runenv.D()`` (diagnostics).

Twin of sdk-go's runtime metrics (usage: ``plans/example/metrics.go:15-19``,
``plans/benchmarks/benchmarks.go:23,47``): counters, gauges, histograms,
timers, points. Values batch to ``metrics.out`` as JSON lines in the
instance's outputs dir (the reference's file sink; the InfluxDB batcher's
analog is the run-level aggregation in ``testground_tpu.metrics``).
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import TextIO

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram", "Timer", "Point"]


class _Metric:
    def __init__(self, registry: "MetricsRegistry", name: str):
        self._reg = registry
        self.name = name


class Counter(_Metric):
    def __init__(self, registry, name):
        super().__init__(registry, name)
        self.count = 0

    def inc(self, n: int = 1) -> None:
        self.count += n
        self._reg.record(self.name, "counter", {"count": self.count})


class Gauge(_Metric):
    def __init__(self, registry, name):
        super().__init__(registry, name)
        self.value = 0.0

    def update(self, v: float) -> None:
        self.value = v
        self._reg.record(self.name, "gauge", {"value": v})


class Histogram(_Metric):
    """Streaming histogram keeping count/sum/min/max/mean/variance."""

    def __init__(self, registry, name):
        super().__init__(registry, name)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._m2 = 0.0
        self._mean = 0.0

    def update(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        delta = v - self._mean
        self._mean += delta / self.count
        self._m2 += delta * (v - self._mean)
        self._reg.record(self.name, "histogram", self.snapshot())

    def snapshot(self) -> dict:
        var = self._m2 / self.count if self.count > 1 else 0.0
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
            "mean": self._mean,
            "stddev": math.sqrt(var),
        }


class Timer(_Metric):
    """Duration histogram in seconds."""

    def __init__(self, registry, name):
        super().__init__(registry, name)
        self._h = Histogram.__new__(Histogram)
        Histogram.__init__(self._h, registry, name)

    def update(self, seconds: float) -> None:
        self._reg.record(self.name, "timer", {"secs": seconds})

    def update_since(self, start: float) -> None:
        self.update(time.time() - start)

    def time(self):
        """Context manager measuring a block."""
        timer = self

        class _Ctx:
            def __enter__(self):
                self.start = time.time()
                return self

            def __exit__(self, *exc):
                timer.update_since(self.start)
                return False

        return _Ctx()


class Point(_Metric):
    def record(self, value: float) -> None:
        self._reg.record(self.name, "point", {"value": value})


class MetricsRegistry:
    """One registry per kind ('results' for R(), 'diagnostics' for D())."""

    def __init__(self, kind: str, sink: TextIO | None, disabled: bool = False):
        self.kind = kind
        self._sink = sink
        self._disabled = disabled
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def record(self, name: str, typ: str, data: dict) -> None:
        if self._disabled or self._sink is None:
            return
        line = json.dumps(
            {"ts": time.time_ns(), "kind": self.kind, "type": typ, "name": name, **data}
        )
        with self._lock:
            self._sink.write(line + "\n")
            self._sink.flush()

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None or not isinstance(m, cls):
                m = cls(self, name)
                self._metrics[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def resetting_histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def record_point(self, name: str, value: float) -> None:
        self.record(name, "point", {"value": value})

    # sample constructors kept for sdk-go surface parity
    def new_uniform_sample(self, size: int = 1028):
        return size
