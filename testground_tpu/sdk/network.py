"""Network client: link shaping and routing policy requests.

Twin of sdk-go's ``network.Client`` + ``network.Config``/``LinkShape`` as
consumed by ``plans/network/pingpong.go:29-42`` and the sidecar handler
(``pkg/sidecar/sidecar_handler.go:49-82``):

- ``wait_network_initialized``: barrier on the ``network-initialized`` state
  signalled by the dataplane for every instance.
- ``configure_network(cfg)``: publish the config to the per-instance topic
  ``network:<hostname>`` and wait on ``cfg.callback_state`` until the
  dataplane applies it.

Under ``local:exec`` there is no sidecar (``TestSidecar=false``,
``local_exec.go:89``) and shaping requests fail, matching the reference.
Under ``sim:jax`` the "dataplane" is the simulator itself: configs lower to
per-instance link-state tensor updates (``testground_tpu.sim.links``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = [
    "ALLOW_ALL",
    "DENY_ALL",
    "FILTER_ACCEPT",
    "FILTER_DROP",
    "FILTER_REJECT",
    "LinkRule",
    "LinkShape",
    "NetworkClient",
    "NetworkConfig",
]

# Filter actions (reference network.FilterAction: accept/reject/drop)
FILTER_ACCEPT = 0
FILTER_REJECT = 1
FILTER_DROP = 2

# Routing policies (reference network.RoutingPolicyType)
ALLOW_ALL = "allow_all"
DENY_ALL = "deny_all"

NETWORK_INITIALIZED_STATE = "network-initialized"


@dataclass
class LinkShape:
    """(sdk-go network.LinkShape; applied by ``pkg/sidecar/link.go:155-183``).

    latency/jitter in seconds, bandwidth in bits per second, loss/corrupt/
    reorder/duplicate as percentages [0,100] with optional correlations.
    """

    latency: float = 0.0
    jitter: float = 0.0
    bandwidth: float = 0.0
    filter: int = FILTER_ACCEPT
    loss: float = 0.0
    corrupt: float = 0.0
    corrupt_corr: float = 0.0
    reorder: float = 0.0
    reorder_corr: float = 0.0
    duplicate: float = 0.0
    duplicate_corr: float = 0.0

    def to_dict(self) -> dict:
        return {
            "latency": self.latency,
            "jitter": self.jitter,
            "bandwidth": self.bandwidth,
            "filter": self.filter,
            "loss": self.loss,
            "corrupt": self.corrupt,
            "corrupt_corr": self.corrupt_corr,
            "reorder": self.reorder,
            "reorder_corr": self.reorder_corr,
            "duplicate": self.duplicate,
            "duplicate_corr": self.duplicate_corr,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LinkShape":
        return cls(**{k: d[k] for k in cls().to_dict() if k in d})


@dataclass
class LinkRule:
    """Per-subnet override (sdk-go network.LinkRule; splitbrain usage
    ``plans/splitbrain/main.go:117-126``)."""

    subnet: str  # CIDR
    shape: LinkShape = field(default_factory=LinkShape)

    def to_dict(self) -> dict:
        return {"subnet": self.subnet, "shape": self.shape.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "LinkRule":
        return cls(subnet=d["subnet"], shape=LinkShape.from_dict(d.get("shape", {})))


@dataclass
class NetworkConfig:
    """(sdk-go network.Config; handled at ``sidecar_handler.go:49-82``)."""

    network: str = "default"
    enable: bool = True
    default: LinkShape = field(default_factory=LinkShape)
    rules: list[LinkRule] = field(default_factory=list)
    ipv4: str = ""  # requested CIDR address, e.g. "16.0.0.2/16"
    routing_policy: str = ALLOW_ALL
    callback_state: str = ""
    callback_target: int = 0  # 0 ⇒ all instances

    def to_dict(self) -> dict:
        return {
            "network": self.network,
            "enable": self.enable,
            "default": self.default.to_dict(),
            "rules": [r.to_dict() for r in self.rules],
            "ipv4": self.ipv4,
            "routing_policy": self.routing_policy,
            "callback_state": self.callback_state,
            "callback_target": self.callback_target,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NetworkConfig":
        return cls(
            network=d.get("network", "default"),
            enable=d.get("enable", True),
            default=LinkShape.from_dict(d.get("default", {})),
            rules=[LinkRule.from_dict(r) for r in d.get("rules", [])],
            ipv4=d.get("ipv4", ""),
            routing_policy=d.get("routing_policy", ALLOW_ALL),
            callback_state=d.get("callback_state", ""),
            callback_target=int(d.get("callback_target", 0)),
        )


class NetworkClient:
    def __init__(self, sync_client, runenv):
        self._sync = sync_client
        self._env = runenv

    def wait_network_initialized(self, timeout: float | None = 60.0) -> None:
        """Barrier until the dataplane initialized every instance's network
        (``sidecar_handler.go:40-44``)."""
        if not self._env.test_sidecar:
            # no dataplane; nothing will signal (local:exec semantics)
            return
        self._sync.barrier(
            NETWORK_INITIALIZED_STATE,
            self._env.test_instance_count,
            timeout=timeout,
        )

    def configure_network(
        self, cfg: NetworkConfig, timeout: float | None = 60.0
    ) -> None:
        """Publish the config to this instance's topic and await the callback
        state (``sidecar_handler.go:49-82``)."""
        if not self._env.test_sidecar:
            raise RuntimeError(
                "this runner does not support network configuration "
                "(TestSidecar=false)"
            )
        if not cfg.callback_state:
            raise ValueError("network config requires a callback_state")
        hostname = f"instance-{self._env.params.test_instance_seq}"
        self._sync.publish(f"network:{hostname}", cfg.to_dict())
        target = cfg.callback_target or self._env.test_instance_count
        self._sync.barrier(cfg.callback_state, target, timeout=timeout)

    def get_data_network_ip(self) -> str:
        """This instance's data-network address. In simulation and local:exec
        it derives deterministically from the subnet + instance seq."""
        import ipaddress

        net = ipaddress.ip_network(self._env.test_subnet, strict=False)
        return str(net.network_address + 2 + self._env.params.test_instance_seq)
