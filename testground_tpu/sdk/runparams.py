"""The RunParams env-var contract between runners and instances.

Field-for-field twin of the env enumerated at the reference's
``pkg/runner/local_docker.go:325-336`` (TestPlan, TestCase, TestRun,
TestInstanceCount, TestGroupID, TestGroupInstanceCount, TestInstanceParams,
TestSubnet, TestSidecar, TestOutputsPath, TestTempPath, TestStartTime,
TestCaptureProfiles, TestDisableMetrics), plus the sync-service endpoint
(injected via ``SYNC_SERVICE_HOST`` in the reference,
``local_docker.go:153``) and this framework's instance sequence numbers.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

__all__ = ["RunParams"]


def _encode_params(params: dict[str, str]) -> str:
    return "|".join(f"{k}={v}" for k, v in params.items())


def _decode_params(s: str) -> dict[str, str]:
    out: dict[str, str] = {}
    if not s:
        return out
    for kv in s.split("|"):
        if kv:
            k, _, v = kv.partition("=")
            out[k] = v
    return out


@dataclass
class RunParams:
    test_plan: str = ""
    test_case: str = ""
    test_run: str = ""
    test_instance_count: int = 0
    test_group_id: str = ""
    test_group_instance_count: int = 0
    test_instance_params: dict[str, str] = field(default_factory=dict)
    test_subnet: str = "127.1.0.0/16"
    test_sidecar: bool = False
    test_outputs_path: str = ""
    test_temp_path: str = ""
    test_start_time: float = 0.0
    test_capture_profiles: dict[str, str] = field(default_factory=dict)
    test_disable_metrics: bool = False
    # framework extensions
    test_instance_seq: int = 0  # global 0-based index of this instance
    test_group_seq: int = 0  # 0-based index within the group
    sync_service_host: str = "127.0.0.1"
    sync_service_port: int = 0
    # sync-client failure budget (docs/CROSSHOST.md), threaded from the
    # runner config: per-attempt connect timeout (was a hardcoded 30 s),
    # per-outage reconnect attempts/deadline, and the heartbeat cadence
    # that feeds the server's idle sweep
    sync_connect_timeout: float = 30.0
    sync_retry_attempts: int = 8
    sync_retry_deadline: float = 60.0
    sync_heartbeat: float = 5.0
    # control-plane trace context (W3C traceparent) threaded from the
    # task's lifecycle trace so instance-side telemetry can join the
    # daemon's span tree (engine/tracetree.py); empty = untraced
    test_traceparent: str = ""

    def to_env(self) -> dict[str, str]:
        return {
            "TEST_PLAN": self.test_plan,
            "TEST_CASE": self.test_case,
            "TEST_RUN": self.test_run,
            "TEST_INSTANCE_COUNT": str(self.test_instance_count),
            "TEST_GROUP_ID": self.test_group_id,
            "TEST_GROUP_INSTANCE_COUNT": str(self.test_group_instance_count),
            "TEST_INSTANCE_PARAMS": _encode_params(self.test_instance_params),
            "TEST_SUBNET": self.test_subnet,
            "TEST_SIDECAR": "true" if self.test_sidecar else "false",
            "TEST_OUTPUTS_PATH": self.test_outputs_path,
            "TEST_TEMP_PATH": self.test_temp_path,
            "TEST_START_TIME": str(self.test_start_time),
            "TEST_CAPTURE_PROFILES": _encode_params(self.test_capture_profiles),
            "TEST_DISABLE_METRICS": "true" if self.test_disable_metrics else "false",
            "TEST_INSTANCE_SEQ": str(self.test_instance_seq),
            "TEST_GROUP_SEQ": str(self.test_group_seq),
            "SYNC_SERVICE_HOST": self.sync_service_host,
            "SYNC_SERVICE_PORT": str(self.sync_service_port),
            "SYNC_CONNECT_TIMEOUT": str(self.sync_connect_timeout),
            "SYNC_RETRY_ATTEMPTS": str(self.sync_retry_attempts),
            "SYNC_RETRY_DEADLINE": str(self.sync_retry_deadline),
            "SYNC_HEARTBEAT": str(self.sync_heartbeat),
            "TEST_TRACEPARENT": self.test_traceparent,
        }

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None) -> "RunParams":
        e = os.environ if env is None else env
        return cls(
            test_plan=e.get("TEST_PLAN", ""),
            test_case=e.get("TEST_CASE", ""),
            test_run=e.get("TEST_RUN", ""),
            test_instance_count=int(e.get("TEST_INSTANCE_COUNT", "0")),
            test_group_id=e.get("TEST_GROUP_ID", ""),
            test_group_instance_count=int(e.get("TEST_GROUP_INSTANCE_COUNT", "0")),
            test_instance_params=_decode_params(e.get("TEST_INSTANCE_PARAMS", "")),
            test_subnet=e.get("TEST_SUBNET", "127.1.0.0/16"),
            test_sidecar=e.get("TEST_SIDECAR", "false") == "true",
            test_outputs_path=e.get("TEST_OUTPUTS_PATH", ""),
            test_temp_path=e.get("TEST_TEMP_PATH", ""),
            test_start_time=float(e.get("TEST_START_TIME", "0") or 0),
            test_capture_profiles=_decode_params(
                e.get("TEST_CAPTURE_PROFILES", "")
            ),
            test_disable_metrics=e.get("TEST_DISABLE_METRICS", "false") == "true",
            test_instance_seq=int(e.get("TEST_INSTANCE_SEQ", "0")),
            test_group_seq=int(e.get("TEST_GROUP_SEQ", "0")),
            sync_service_host=e.get("SYNC_SERVICE_HOST", "127.0.0.1"),
            sync_service_port=int(e.get("SYNC_SERVICE_PORT", "0")),
            sync_connect_timeout=float(e.get("SYNC_CONNECT_TIMEOUT", "30") or 30),
            sync_retry_attempts=int(e.get("SYNC_RETRY_ATTEMPTS", "8") or 8),
            sync_retry_deadline=float(e.get("SYNC_RETRY_DEADLINE", "60") or 60),
            sync_heartbeat=float(e.get("SYNC_HEARTBEAT", "5") or 5),
            test_traceparent=e.get("TEST_TRACEPARENT", ""),
        )
