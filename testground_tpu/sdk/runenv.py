"""RunEnv: the environment handed to a test-case function.

Twin of sdk-go's ``runtime.RunEnv``: typed param access, message/failure/
crash recording (stdout events + ``run.out``), metrics registries, and the
bound sync client (attached by :func:`testground_tpu.sdk.invoke.invoke_map`).
"""

from __future__ import annotations

import json
import os
import sys
import traceback
from typing import Any

from .events import EventEmitter
from .metrics_api import MetricsRegistry
from .runparams import RunParams

__all__ = ["RunEnv"]


class RunEnv:
    def __init__(self, params: RunParams | None = None):
        self.params = params or RunParams.from_env()

        out_dir = self.params.test_outputs_path
        self._run_out = None
        self._run_err = None
        self._metrics_out = None
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            self._run_out = open(os.path.join(out_dir, "run.out"), "a")
            self._run_err = open(os.path.join(out_dir, "run.err"), "a")
            self._metrics_out = open(os.path.join(out_dir, "metrics.out"), "a")

        self.events = EventEmitter(sys.stdout, self._run_out)
        self._r = MetricsRegistry(
            "results", self._metrics_out, disabled=False
        )
        self._d = MetricsRegistry(
            "diagnostics",
            self._metrics_out,
            disabled=self.params.test_disable_metrics,
        )
        self.sync_client = None  # attached by invoke (AttachSyncClient analog)

    # convenience accessors mirroring sdk-go names
    @property
    def test_plan(self) -> str:
        return self.params.test_plan

    @property
    def test_case(self) -> str:
        return self.params.test_case

    @property
    def test_run(self) -> str:
        return self.params.test_run

    @property
    def test_instance_count(self) -> int:
        return self.params.test_instance_count

    @property
    def test_group_id(self) -> str:
        return self.params.test_group_id

    @property
    def test_group_instance_count(self) -> int:
        return self.params.test_group_instance_count

    @property
    def test_instance_params(self) -> dict[str, str]:
        return self.params.test_instance_params

    @property
    def test_sidecar(self) -> bool:
        return self.params.test_sidecar

    @property
    def test_subnet(self) -> str:
        return self.params.test_subnet

    @property
    def test_start_time(self) -> float:
        return self.params.test_start_time

    @property
    def test_outputs_path(self) -> str:
        return self.params.test_outputs_path

    @property
    def test_temp_path(self) -> str:
        return self.params.test_temp_path

    # ------------------------------------------------------------- params

    def string_param(self, name: str) -> str:
        v = self.params.test_instance_params.get(name)
        if v is None:
            raise KeyError(f"missing param: {name}")
        return v

    def int_param(self, name: str) -> int:
        return int(self.string_param(name))

    def float_param(self, name: str) -> float:
        return float(self.string_param(name))

    def bool_param(self, name: str) -> bool:
        return self.string_param(name).lower() in ("true", "1", "yes")

    def json_param(self, name: str) -> Any:
        return json.loads(self.string_param(name))

    def string_array_param(self, name: str) -> list[str]:
        v = self.json_param(name)
        if not isinstance(v, list):
            raise ValueError(f"param {name} is not an array")
        return [str(x) for x in v]

    # ------------------------------------------------------------- recording

    def record_message(self, msg: str, *args: Any) -> None:
        self.events.message((msg % args) if args else msg)

    def record_start(self) -> None:
        self.events.start(
            {
                "plan": self.test_plan,
                "case": self.test_case,
                "run": self.test_run,
                "instances": self.test_instance_count,
                "group": self.test_group_id,
            }
        )

    def record_success(self) -> None:
        self.events.success()
        self._publish_event("success", "")

    def record_failure(self, err: Exception | str) -> None:
        self.events.failure(str(err))
        self._publish_event("failure", str(err))

    def record_crash(self, err: Exception | str) -> None:
        self.events.crash(str(err), traceback.format_exc())
        self._publish_event("crash", str(err))

    def _publish_event(self, outcome: str, error: str) -> None:
        """Mirror the lifecycle event onto the sync service so the runner's
        outcome collector sees it (``local_docker.go:217-256``
        SubscribeEvents semantics)."""
        if self.sync_client is None:
            return
        from testground_tpu.sync import RUN_EVENTS_TOPIC

        try:
            self.sync_client.publish(
                RUN_EVENTS_TOPIC,
                {
                    "type": outcome,
                    "group": self.test_group_id,
                    "instance": self.params.test_instance_seq,
                    "error": error,
                },
            )
        except Exception:  # noqa: BLE001 — events are best-effort
            pass

    # -------------------------------------------------------------- metrics

    def R(self) -> MetricsRegistry:  # noqa: N802 — sdk-go surface parity
        return self._r

    def D(self) -> MetricsRegistry:  # noqa: N802
        return self._d

    # -------------------------------------------------------------- plumbing

    def attach_sync_client(self, client) -> None:
        """(``plans/placebo/main.go`` AttachSyncClient analog)."""
        self.sync_client = client

    def to_dict(self) -> dict:
        return self.params.to_env()

    def close(self) -> None:
        for f in (self._run_out, self._run_err, self._metrics_out):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
