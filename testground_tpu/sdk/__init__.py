"""Test-plan SDK: the runtime test plans program against.

Twin of the reference's external sdk-go (``run.InvokeMap``,
``runtime.RunEnv``/``RunParams`` env-var contract, ``network.Client``,
``sync.Client`` — SURVEY.md §1 L1). A plan is a Python module calling
:func:`invoke_map` with its testcases; instances receive their parameters via
``TEST_*`` environment variables and report lifecycle events as JSON lines
on stdout plus sync-service events.
"""

from .events import EventEmitter
from .invoke import invoke_map
from .network import (
    FILTER_ACCEPT,
    FILTER_DROP,
    FILTER_REJECT,
    ALLOW_ALL,
    DENY_ALL,
    LinkRule,
    LinkShape,
    NetworkClient,
    NetworkConfig,
)
from .runenv import RunEnv
from .runparams import RunParams

__all__ = [
    "ALLOW_ALL",
    "DENY_ALL",
    "EventEmitter",
    "FILTER_ACCEPT",
    "FILTER_DROP",
    "FILTER_REJECT",
    "LinkRule",
    "LinkShape",
    "NetworkClient",
    "NetworkConfig",
    "RunEnv",
    "RunParams",
    "invoke_map",
]
