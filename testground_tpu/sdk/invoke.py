"""Test-case invocation: the plan's ``main()``.

Twin of sdk-go's ``run.InvokeMap`` (``plans/example/main.go:7-9``): look up
the testcase named by ``TEST_CASE``, build the RunEnv, bind the sync client,
run the function, and record the terminal event (success / failure on error
return / crash on exception).
"""

from __future__ import annotations

import sys
import traceback
from typing import Callable

from .network import NetworkClient
from .runenv import RunEnv

__all__ = ["invoke_map", "InitContext"]


class InitContext:
    """(sdk-go run.InitContext: holds SyncClient + NetClient)."""

    def __init__(self, sync_client, net_client):
        self.sync_client = sync_client
        self.net_client = net_client


def _connect_sync(env: RunEnv):
    from testground_tpu.sync import RUN_EVENTS_TOPIC
    from testground_tpu.sync.client import SyncClient, SyncRetry

    p = env.params
    if p.sync_service_port == 0:
        return None
    return SyncClient(
        p.sync_service_host,
        p.sync_service_port,
        namespace=f"run:{p.test_run}:",
        # failure budget from the runner config (docs/CROSSHOST.md)
        retry=SyncRetry(
            connect_timeout=p.sync_connect_timeout,
            attempts=p.sync_retry_attempts,
            deadline_secs=p.sync_retry_deadline,
            heartbeat_secs=p.sync_heartbeat,
        ),
        # identity for server-side eviction events: if this process dies
        # abnormally, the service tells the run's event stream
        identity={
            "events_topic": f"run:{p.test_run}:{RUN_EVENTS_TOPIC}",
            "group": p.test_group_id,
            "instance": p.test_instance_seq,
            # hello attribution: the run id lets the sync service bucket
            # its per-task op counters (docs/CROSSHOST.md) — old servers
            # ignore unknown identity fields, so the wire stays compatible
            "task": p.test_run,
        },
    )


def invoke_map(testcases: dict[str, Callable]) -> None:
    """Run the testcase selected by the environment and exit.

    Testcase signatures supported (mirroring run.TestCaseFn and
    run.InitializedTestCaseFn):
        fn(runenv) -> None | error-string
        fn(runenv, init_ctx) -> None | error-string
    Raising marks the instance crashed; returning a truthy value or calling
    ``record_failure`` marks it failed; otherwise success.
    """
    env = RunEnv()
    case = env.test_case
    fn = testcases.get(case)
    if fn is None:
        print(f"unknown test case: {case}", file=sys.stderr)
        sys.exit(2)

    try:
        sync_client = _connect_sync(env)
    except Exception as e:  # noqa: BLE001 — SyncLostError et al.
        # the coordination plane is unreachable within the configured
        # budget: crash readably (address is in the message) — never hang
        env.record_crash(e)
        print(f"sync service unreachable: {e}", file=sys.stderr)
        env.close()
        sys.exit(1)
    if sync_client is not None:
        env.attach_sync_client(sync_client)

    def _close_sync() -> None:
        # clean close (sync `bye`): the server must not publish an
        # eviction event for a normally-exiting instance
        if sync_client is not None:
            try:
                sync_client.close()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
    net_client = NetworkClient(sync_client, env)
    init_ctx = InitContext(sync_client, net_client)

    # profile capture (the sdk-go pprof analog, SURVEY §5: a "cpu"
    # profile runs for the whole test): group `profiles = {cpu = "..."}`
    # → TEST_CAPTURE_PROFILES → a cProfile session around the testcase,
    # dumped as pstats into the instance's outputs dir
    profiler = None
    if (
        "cpu" in env.params.test_capture_profiles
        and env.params.test_outputs_path
    ):
        import cProfile

        profiler = cProfile.Profile()

    def _stop_profile():
        # best-effort: a failed dump must never change the instance's
        # outcome (it runs in the finally of every exit path)
        if profiler is None:
            return
        import os

        profiler.disable()
        try:
            profiler.dump_stats(
                os.path.join(
                    env.params.test_outputs_path, "profile-cpu.pstats"
                )
            )
        except OSError as e:
            print(f"could not write cpu profile: {e}", file=sys.stderr)

    env.record_start()
    try:
        # initialized testcases (2-arg) wait for the network first, like
        # run.InitializedTestCaseFn does via MustWaitNetworkInitialized.
        import inspect

        nparams = len(inspect.signature(fn).parameters)
        if profiler is not None:
            profiler.enable()
        try:
            if nparams >= 2:
                net_client.wait_network_initialized()
                err = fn(env, init_ctx)
            else:
                err = fn(env)
        finally:
            _stop_profile()
    except SystemExit:
        _close_sync()
        raise
    except BaseException as e:  # noqa: BLE001 — crash semantics
        env.record_crash(e)
        print(traceback.format_exc(), file=sys.stderr)
        _close_sync()
        env.close()
        sys.exit(1)

    if err:
        env.record_failure(str(err))
        _close_sync()
        env.close()
        sys.exit(1)

    env.record_success()
    _close_sync()
    env.close()
    sys.exit(0)
