"""The engine: builder/runner registries, task queue, worker pool, and the
task APIs the daemon exposes.

Twin of the reference's ``pkg/engine/engine.go`` (registries, storage/queue
init, worker goroutines, queue/kill/logs) with the supervisor loop in
``supervisor.py``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

from testground_tpu.api import (
    Composition,
    TestPlanManifest,
    validate_for_run,
)
from testground_tpu.config import EnvConfig
from testground_tpu.logging_ import S
from testground_tpu.tracectx import TraceContext, new_span_id, new_trace_id

from .controller import pick_eviction_victim
from .events import EVENTS_FILE, EventJournal
from .queue import TaskQueue
from .storage import TaskStorage
from .task import CreatedBy, DatedState, State, Task, TaskType, new_task_id

# Fleet histograms reuse the sync plane's log2 µs binning (one binning
# vocabulary across every tg_* histogram; sync/stats.py is import-light
# by contract so the engine can depend on it).
from testground_tpu.sync.stats import TIME_BINS, time_bin

# Distinct solo_reason labels tracked before overflowing into "other" —
# Prometheus label sets must stay bounded even if a future pack gate
# invents per-task reason strings.
_FLEET_SOLO_REASONS_MAX = 32

__all__ = ["Engine", "EngineConfig"]


@dataclass
class EngineConfig:
    """(``pkg/engine/engine.go:65-77`` EngineConfig)."""

    env: EnvConfig
    builders: list = field(default_factory=list)
    runners: list = field(default_factory=list)


class Engine:
    """Singleton scheduler (``engine.go:41-63``)."""

    def __init__(self, cfg: EngineConfig):
        self.env = cfg.env
        self._builders = {b.id(): b for b in cfg.builders}
        self._runners = {r.id(): r for r in cfg.runners}

        sch = self.env.daemon.scheduler
        if sch.task_repo_type == "disk":
            db_path = os.path.join(self.env.dirs.home, "tasks.db")
        else:
            db_path = ":memory:"
        self.storage = TaskStorage(db_path)
        self.queue = TaskQueue(self.storage, sch.queue_size)

        # per-task cancel signals (``engine.go:59-62``)
        self._cancel_lock = threading.Lock()
        self._cancels: dict[str, threading.Event] = {}
        # per-task preemption signals (fleet controller, docs/FLEET.md):
        # distinct from cancel — a preempted run checkpoints at the next
        # chunk boundary and REQUEUES instead of archiving CANCELED
        self._preempts: dict[str, threading.Event] = {}
        # drain flag: workers stop claiming while set (graceful SIGTERM)
        self._draining = threading.Event()

        self._stop = threading.Event()
        self._queue_kick = threading.Event()
        self._workers: list[threading.Thread] = []

        # Control plane (docs/OBSERVABILITY.md "Control plane"): the
        # append-only daemon event journal plus in-memory fleet
        # counters behind tg_fleet_* and GET /fleet. Counters cover the
        # daemon's lifetime, not the task store's — they reset on
        # restart like every other process-local Prometheus counter.
        self.events = EventJournal(
            os.path.join(self.env.dirs.daemon(), EVENTS_FILE)
        )
        self._fleet_lock = threading.Lock()
        self._worker_task: dict[int, str] = {}  # worker idx -> task id ("" idle)
        self._queue_wait_bins = [0] * TIME_BINS
        self._queue_wait_total_us = 0
        self._claim_latency_bins = [0] * TIME_BINS
        self._claim_latency_total_us = 0
        self._pack_packed_total = 0  # admissions that packed >= 2 runs
        self._pack_packed_runs_total = 0  # member runs admitted via packs
        self._pack_solo: dict[str, int] = {}  # solo_reason -> count
        self._running_packs: dict[str, int] = {}  # leader task id -> width
        # fleet controller decision counters (tg_fleet_*_total)
        self._fleet_preemptions = 0  # preempted runs requeued to resume
        self._fleet_evictions = 0  # preemptions caused by priority arrivals
        self._fleet_refused = 0  # compositions refused at submit

    # ---------------------------------------------------------------- wiring

    @classmethod
    def new_default(cls, env: EnvConfig | None = None) -> "Engine":
        """Default engine with all first-party builders/runners registered
        (``engine.go:127-160`` NewDefaultEngine)."""
        from testground_tpu.builders.exec_bin import ExecBinBuilder
        from testground_tpu.builders.exec_py import ExecPyBuilder
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.runners.local_exec import LocalExecRunner
        from testground_tpu.sim.runner import SimJaxRunner

        env = env or EnvConfig.load()
        return cls(
            EngineConfig(
                env=env,
                builders=[ExecPyBuilder(), ExecBinBuilder(), SimPlanBuilder()],
                runners=[LocalExecRunner(), SimJaxRunner()],
            )
        )

    def start_workers(self) -> None:
        """(``engine.go:120-122``)."""
        from .supervisor import worker

        n = self.env.daemon.scheduler.workers
        for i in range(n):
            t = threading.Thread(
                target=worker, args=(self, i), daemon=True, name=f"tg-worker-{i}"
            )
            t.start()
            self._workers.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._queue_kick.set()
        for t in self._workers:
            t.join(timeout=5)
        # a leader engine drains its multi-host sim-workers on the way
        # out: through the isolated leader child when one exists
        # (sim/cohort.py), or directly if a cohort was joined in this
        # process (isolate_cohort=False)
        try:
            from testground_tpu.sim.cohort import shutdown_leader_child
            from testground_tpu.sim.distributed import (
                broadcast_shutdown_if_leader,
            )

            shutdown_leader_child()
            broadcast_shutdown_if_leader()
        except Exception as e:  # noqa: BLE001 — shutdown is best-effort
            S().warning("cohort shutdown broadcast failed: %s", e)

    # ------------------------------------------------------------- registries

    def builder_by_name(self, name: str):
        return self._builders.get(name)

    def runner_by_name(self, name: str):
        return self._runners.get(name)

    def list_builders(self) -> list[str]:
        return sorted(self._builders)

    def list_runners(self) -> list[str]:
        return sorted(self._runners)

    # -------------------------------------------------------------- queueing

    def _check_run_compat(self, comp: Composition, manifest: TestPlanManifest):
        """Runner exists + every group's builder is compatible with it
        (``engine.go:216-219``)."""
        runner = self.runner_by_name(comp.global_.runner)
        if runner is None:
            raise ValueError(f"unknown runner: {comp.global_.runner}")
        compatible = set(runner.compatible_builders())
        for b in comp.list_builders():
            if b and b not in compatible:
                raise ValueError(
                    f"builder {b} is incompatible with runner "
                    f"{comp.global_.runner} (compatible: {sorted(compatible)})"
                )

    def queue_run(
        self,
        comp: Composition,
        manifest: TestPlanManifest,
        sources_dir: str = "",
        priority: int = 0,
        created_by: CreatedBy | None = None,
        trace_parent: str = "",
    ) -> str:
        """Queue a run task (``engine.go:203-249`` QueueRun)."""
        validate_for_run(comp)
        self._check_run_compat(comp, manifest)
        return self._queue_task(
            TaskType.RUN,
            comp,
            manifest,
            sources_dir,
            priority,
            created_by,
            trace_parent,
        )

    def queue_build(
        self,
        comp: Composition,
        manifest: TestPlanManifest,
        sources_dir: str = "",
        priority: int = 0,
        created_by: CreatedBy | None = None,
        trace_parent: str = "",
    ) -> str:
        """Queue a build task (``engine.go:162-201`` QueueBuild)."""
        return self._queue_task(
            TaskType.BUILD,
            comp,
            manifest,
            sources_dir,
            priority,
            created_by,
            trace_parent,
        )

    def _queue_task(
        self,
        typ: TaskType,
        comp: Composition,
        manifest: TestPlanManifest,
        sources_dir: str,
        priority: int,
        created_by: CreatedBy | None,
        trace_parent: str = "",
    ) -> str:
        # Lifecycle trace ids (tracectx.py): adopt the submitter's
        # traceparent when one arrived (its span becomes the task's
        # root "submit" span), else mint a fresh trace here — every
        # task has a complete id set from birth so the archive-time
        # span tree always connects.
        ctx = TraceContext.from_traceparent(trace_parent)
        if ctx is not None:
            trace = {"trace_id": ctx.trace_id, "root_span_id": ctx.span_id}
        else:
            trace = {"trace_id": new_trace_id(), "root_span_id": new_span_id()}
        trace["queued_span_id"] = new_span_id()
        tsk = Task(
            id=new_task_id(),
            type=typ,
            priority=priority,
            plan=comp.global_.plan,
            case=comp.global_.case,
            runner=comp.global_.runner,
            composition=comp.to_dict(),
            input={
                "manifest": manifest.to_dict(),
                "sources_dir": sources_dir,
            },
            states=[DatedState(state=State.SCHEDULED, created=time.time())],
            created_by=created_by or CreatedBy(),
            trace=trace,
        )
        if tsk.created_by_ci():
            self.queue.push_unique_by_branch(tsk)
        else:
            self.queue.push(tsk)
        self._queue_kick.set()
        self.events.emit(
            "task.scheduled",
            task=tsk.id,
            trace=tsk.trace,
            state=State.SCHEDULED.value,
            task_type=typ.value,
            plan=tsk.plan,
            case=tsk.case,
            priority=priority,
        )
        S().info("queued task %s (%s)", tsk.id, tsk.name())
        # fleet controller (docs/FLEET.md): a high-priority run that
        # cannot be admitted right now evicts the lowest-value running
        # task instead of queueing behind it
        if typ == TaskType.RUN and priority > 0:
            try:
                self._maybe_evict_for(tsk)
            except Exception as e:  # noqa: BLE001 — eviction is an
                # optimization; a policy failure must never fail submit
                S().warning("eviction check failed for %s: %s", tsk.id, e)
        return tsk.id

    # ------------------------------------------------------------ cancel/kill

    def register_cancel(self, task_id: str) -> threading.Event:
        # idempotent: the worker registers at claim time (before the
        # claim bookkeeping) so kill() never races the pop→process
        # window; the later process_task call must return the SAME
        # event or an operator cancel landing in between would be lost
        with self._cancel_lock:
            ev = self._cancels.get(task_id)
            if ev is None:
                ev = threading.Event()
                self._cancels[task_id] = ev
        return ev

    def drop_cancel(self, task_id: str) -> None:
        with self._cancel_lock:
            self._cancels.pop(task_id, None)

    def kill(self, task_id: str) -> bool:
        """Cancel a queued or running task (``engine.go:419-427`` Kill)."""
        if self.queue.cancel_queued(task_id):
            S().info("canceled queued task %s", task_id)
            tsk = self.storage.get(task_id)
            trace = tsk.trace if tsk is not None else None
            self.events.emit(
                "task.cancel_requested", task=task_id, trace=trace, queued=True
            )
            # a queued cancel IS the terminal transition — no worker
            # will ever touch this task, so journal it here
            self.events.emit(
                "task.canceled",
                task=task_id,
                trace=trace,
                state=State.CANCELED.value,
                by="operator",
            )
            return True
        with self._cancel_lock:
            ev = self._cancels.get(task_id)
        if ev is not None:
            ev.set()
            tsk = self.storage.get(task_id)
            self.events.emit(
                "task.cancel_requested",
                task=task_id,
                trace=tsk.trace if tsk is not None else None,
                queued=False,
            )
            return True
        return False

    # -------------------------------------------------- fleet controller
    # (docs/FLEET.md) preemption, eviction, admission, drain — the
    # composition layer over checkpoint/resume + the rules engine.

    def register_preempt(self, task_id: str) -> threading.Event:
        """Idempotent get-or-create of a task's preemption signal —
        same contract as :meth:`register_cancel`: the supervisor arms
        it at dispatch, and a ``preempt()`` landing between queue-pop
        and claim must find (or pre-create) the SAME event."""
        with self._cancel_lock:
            ev = self._preempts.get(task_id)
            if ev is None:
                ev = threading.Event()
                self._preempts[task_id] = ev
        return ev

    def drop_preempt(self, task_id: str) -> None:
        with self._cancel_lock:
            self._preempts.pop(task_id, None)

    def preempt_requested(self, task_id: str) -> bool:
        with self._cancel_lock:
            ev = self._preempts.get(task_id)
        return ev is not None and ev.is_set()

    def preempt(self, task_id: str) -> dict:
        """Request live migration of a running RUN task: checkpoint at
        the next chunk boundary, requeue, resume from the newest
        snapshot (docs/FLEET.md). Idempotent — a double preempt sets an
        already-set event. A still-QUEUED task is a no-op success (it
        is already durably parked). Returns ``{"ok", "queued", ...}``;
        refusals carry ``"error"``."""
        tsk = self.storage.get(task_id)
        if tsk is None:
            return {"ok": False, "error": f"unknown task {task_id}"}
        st = tsk.state().state
        if st == State.SCHEDULED:
            return {"ok": True, "queued": True}
        if st != State.PROCESSING:
            return {
                "ok": False,
                "error": (
                    f"task {task_id} is {st.value}; only running tasks "
                    "can be preempted"
                ),
            }
        if tsk.type != TaskType.RUN:
            return {
                "ok": False,
                "error": (
                    "build tasks are not preemptible (a build has no "
                    "carry to checkpoint — kill it instead)"
                ),
            }
        ev = self.register_preempt(task_id)
        first = not ev.is_set()
        ev.set()
        if first:
            self.events.emit(
                "task.preempt_requested", task=task_id, trace=tsk.trace
            )
        return {"ok": True, "queued": False}

    def _maybe_evict_for(self, tsk: Task) -> None:
        """Priority preemption: when ``tsk`` (a just-queued RUN with
        priority > 0) finds no idle worker, evict the lowest-value
        running task (policy: :func:`controller.pick_eviction_victim`)
        so the arrival is claimed next. Pack members are candidates too
        — storage.processing() lists every claimed task, not just the
        worker-visible pack leaders."""
        with self._fleet_lock:
            busy = sum(1 for t in self._worker_task.values() if t)
            total = max(len(self._workers), len(self._worker_task))
        if total == 0 or busy < total:
            return  # an idle worker will claim the arrival anyway
        candidates = []
        for cur in self.storage.processing():
            if cur.type != TaskType.RUN or cur.id == tsk.id:
                continue  # builds are not preemptible
            cfg = dict(self.env.runners.get(cur.runner) or {})
            cfg.update(
                (cur.composition.get("global") or {}).get("run_config")
                or {}
            )
            candidates.append(
                {
                    "id": cur.id,
                    "priority": cur.priority,
                    "started": cur.state().created,
                    "checkpointed": int(cfg.get("checkpoint_chunks") or 0)
                    > 0,
                }
            )
        victim = pick_eviction_victim(candidates, tsk.priority)
        if victim is None:
            return
        res = self.preempt(victim["id"])
        if not res.get("ok"):
            return
        with self._fleet_lock:
            self._fleet_evictions += 1
        vt = self.storage.get(victim["id"])
        self.events.emit(
            "task.evicted",
            task=victim["id"],
            trace=vt.trace if vt is not None else None,
            by=tsk.id,
            arriving_priority=tsk.priority,
            victim_priority=int(victim["priority"]),
            checkpointed=bool(victim["checkpointed"]),
        )
        S().info(
            "evicted task %s (priority %d) for arrival %s (priority %d)",
            victim["id"],
            victim["priority"],
            tsk.id,
            tsk.priority,
        )

    def fleet_note_preemption(self) -> None:
        """Supervisor hook: one preempted run was requeued to resume."""
        with self._fleet_lock:
            self._fleet_preemptions += 1

    def admission_findings(self, comp, manifest) -> list:
        """Server-side ``tg check``: the error-severity findings the
        rules engine (sim/check.py) raises against a composition — the
        daemon refuses the submit when this is non-empty, with the SAME
        rule ids ``tg check`` reports (docs/FLEET.md "Admission")."""
        from testground_tpu.sim.check import check_composition

        findings = check_composition(
            comp,
            manifest,
            env_layer=self.env.runners.get(comp.global_.runner) or {},
        )
        return [f for f in findings if f.severity == "error"]

    def note_refused(self, comp, rules: list[str], kind: str = "run") -> None:
        """Journal + count one refused-at-submit composition."""
        with self._fleet_lock:
            self._fleet_refused += 1
        self.events.emit(
            "task.refused",
            task_type=kind,
            plan=comp.global_.plan,
            case=comp.global_.case,
            rules=list(rules),
        )

    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, timeout_secs: float = 30.0) -> dict:
        """Graceful drain (docs/FLEET.md): stop claiming new tasks,
        preempt running RUN tasks (checkpoint-enabled ones snapshot at
        the next boundary and requeue to resume; the rest requeue to
        rerun deterministically), cancel running BUILD tasks (a build
        has nothing to checkpoint and is cheap to redo), then wait —
        bounded — for every worker to park. Idempotent; journals
        ``daemon.drain``."""
        already = self._draining.is_set()
        self._draining.set()
        self._queue_kick.set()
        preempted: list[str] = []
        canceled: list[str] = []
        for tsk in self.storage.processing():
            if tsk.type == TaskType.RUN:
                if self.preempt(tsk.id).get("ok"):
                    preempted.append(tsk.id)
            elif self.kill(tsk.id):
                canceled.append(tsk.id)
        deadline = time.monotonic() + max(0.0, timeout_secs)
        drained = False
        while True:
            with self._fleet_lock:
                busy = any(t for t in self._worker_task.values())
            if not busy:
                drained = True
                break
            if time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        self.events.emit(
            "daemon.drain",
            preempted=preempted,
            canceled=canceled,
            drained=drained,
            already_draining=already,
        )
        S().info(
            "drain: %d run(s) preempted, %d build(s) canceled, workers %s",
            len(preempted),
            len(canceled),
            "idle" if drained else "still busy at timeout",
        )
        return {
            "drained": drained,
            "preempted": preempted,
            "canceled": canceled,
        }

    def delete_task(self, task_id: str) -> bool:
        """Delete a FINISHED task's record + log file (the daemon's GET
        ``/delete`` surface, ``pkg/daemon/daemon.go:88``). Live tasks must
        be killed first — deleting a record out from under a worker would
        orphan its cancel channel."""
        tsk = self.storage.get(task_id)
        if tsk is None:
            return False
        if tsk.state().state not in (State.COMPLETE, State.CANCELED):
            raise ValueError(
                f"task {task_id} is {tsk.state().state.value}; kill it "
                "before deleting"
            )
        deleted = self.storage.delete(task_id)
        try:
            os.unlink(self.task_log_path(task_id))
        except FileNotFoundError:
            pass
        return deleted

    # ------------------------------------------------------------------ info

    def get_task(self, task_id: str) -> Task | None:
        return self.storage.get(task_id)

    def tasks(self, **filters: Any) -> list[Task]:
        return self.storage.filter(**filters)

    def task_log_path(self, task_id: str) -> str:
        """Per-task output file (``engine.go:461-558`` Logs tails
        ``<daemon-dir>/<task-id>.out``)."""
        return os.path.join(self.env.dirs.daemon(), f"{task_id}.out")

    def logs(
        self, task_id: str, follow: bool = False, cancel: threading.Event | None = None
    ) -> Iterator[str]:
        """Stream a task's log file; with ``follow``, tail until the task
        completes (``engine.go:461-558``)."""
        path = self.task_log_path(task_id)
        # wait for the file to appear if the task is still queued
        while not os.path.exists(path):
            tsk = self.get_task(task_id)
            if tsk is None:
                raise FileNotFoundError(f"unknown task {task_id}")
            if not follow or tsk.state().state in (State.COMPLETE, State.CANCELED):
                return
            if cancel is not None and cancel.is_set():
                return
            time.sleep(0.1)
        with open(path, "r") as f:
            while True:
                line = f.readline()
                if line:
                    yield line
                    continue
                tsk = self.get_task(task_id)
                done = tsk is None or tsk.state().state in (
                    State.COMPLETE,
                    State.CANCELED,
                )
                if not follow or done:
                    return
                if cancel is not None and cancel.is_set():
                    return
                time.sleep(0.1)

    def stream_rows(
        self,
        task_id: str,
        follow: bool = True,
        cancel: threading.Event | None = None,
        families=None,
        heartbeat_secs: float = 0.0,
    ) -> Iterator[dict]:
        """Stream a task's live observability rows (telemetry / perf /
        SLO breaches / run spans) from its run outputs dirs — the
        backend of the daemon's ``GET /stream`` and ``tg watch``
        (docs/OBSERVABILITY.md "Run health plane"). With ``follow``,
        tails across the queued→running→done lifecycle and closes after
        a final sweep once the task finishes; on an already-finished
        task it replays the full history, then closes (the ``logs``
        follow contract)."""
        tsk = self.get_task(task_id)
        if tsk is None:
            raise FileNotFoundError(f"unknown task {task_id}")
        from .stream import stream_task_rows

        def is_done() -> bool:
            t = self.get_task(task_id)
            return t is None or t.state().state in (
                State.COMPLETE,
                State.CANCELED,
            )

        yield from stream_task_rows(
            self.env.dirs.outputs(),
            tsk.plan,
            task_id,
            is_done,
            follow=follow,
            cancel=cancel,
            families=families,
            heartbeat_secs=heartbeat_secs,
        )

    def diff_tasks(self, a: str, b: str, planes=None) -> dict:
        """Differential run analysis (docs/OBSERVABILITY.md "Run diff"):
        load both tasks' journals + swept ``sim_perf.jsonl`` chunk rows
        and build the RunDiff document — deterministic counters compared
        exactly, throughput judged from the per-chunk samples
        (``analysis/diff.py``). Works on ARCHIVED tasks: everything read
        here (task store + run outputs) survives daemon restarts.

        Raises ``FileNotFoundError`` for an unknown task and
        ``ValueError`` for an unknown plane — the daemon route maps
        these to 404/400; backend of ``tg diff`` and ``Client.diff``.
        """
        from testground_tpu.analysis.diff import (
            build_run_diff,
            task_snapshot,
            validate_planes,
        )

        planes = validate_planes(planes)
        snaps = []
        for tid in (a, b):
            tsk = self.get_task(tid)
            if tsk is None:
                raise FileNotFoundError(f"unknown task {tid}")
            try:
                rows = [
                    r
                    for r in self.stream_rows(
                        tid, follow=False, families=("perf",)
                    )
                    if isinstance(r, dict)
                ]
            except FileNotFoundError:
                rows = []
            snaps.append(task_snapshot(tsk.to_dict(), rows))
        return build_run_diff(snaps[0], snaps[1], planes=planes)

    # ----------------------------------------------------------------- fleet

    def fleet_worker_state(self, idx: int, task_id: str) -> None:
        """Supervisor hook: worker ``idx`` is now busy on ``task_id``
        ("" = idle). Feeds tg_fleet_workers and GET /fleet."""
        with self._fleet_lock:
            self._worker_task[idx] = task_id

    def fleet_note_claim(
        self, queue_wait_secs: float, claim_latency_secs: float
    ) -> None:
        """Supervisor hook: one task left the queue. Records log2
        histograms of how long it waited (scheduled → PROCESSING) and
        how long the claim itself took (PROCESSING stamp → worker
        dispatch, i.e. pack admission + prep overhead)."""
        wait_us = max(0.0, queue_wait_secs) * 1e6
        claim_us = max(0.0, claim_latency_secs) * 1e6
        with self._fleet_lock:
            self._queue_wait_bins[time_bin(wait_us)] += 1
            self._queue_wait_total_us += int(wait_us)
            self._claim_latency_bins[time_bin(claim_us)] += 1
            self._claim_latency_total_us += int(claim_us)

    def fleet_note_pack(self, leader_id: str, width: int) -> None:
        """Supervisor hook: a pack claim admitted ``width`` runs."""
        with self._fleet_lock:
            self._pack_packed_total += 1
            self._pack_packed_runs_total += width
            self._running_packs[leader_id] = width

    def fleet_note_solo(self, reason: str) -> None:
        """Supervisor hook: a pack-eligible run went solo; count by
        reason (bounded label set)."""
        reason = reason or "none"
        with self._fleet_lock:
            if (
                reason not in self._pack_solo
                and len(self._pack_solo) >= _FLEET_SOLO_REASONS_MAX
            ):
                reason = "other"
            self._pack_solo[reason] = self._pack_solo.get(reason, 0) + 1

    def fleet_pack_done(self, leader_id: str) -> None:
        with self._fleet_lock:
            self._running_packs.pop(leader_id, None)

    def fleet_info(self) -> dict:
        """Counter snapshot for the Prometheus ``tg_fleet_*`` family
        (metrics/prometheus.py renders it; task-store gauges are
        computed there from the FULL task list)."""
        with self._fleet_lock:
            busy = sum(1 for t in self._worker_task.values() if t)
            total = max(len(self._workers), len(self._worker_task))
            return {
                "workers": {"total": total, "busy": busy},
                "queue_wait_bins": list(self._queue_wait_bins),
                "queue_wait_total_us": self._queue_wait_total_us,
                "claim_latency_bins": list(self._claim_latency_bins),
                "claim_latency_total_us": self._claim_latency_total_us,
                "pack": {
                    "packed": self._pack_packed_total,
                    "packed_runs": self._pack_packed_runs_total,
                    "solo": dict(self._pack_solo),
                },
                # fleet controller decisions (docs/FLEET.md)
                "preemptions": self._fleet_preemptions,
                "evictions": self._fleet_evictions,
                "refused": self._fleet_refused,
                "draining": self._draining.is_set(),
            }

    @staticmethod
    def _tail_last_row(path: str, tail_bytes: int = 8192) -> dict:
        """Last parseable JSON line of a jsonl file, reading only the
        tail — bounded no matter how long a run has been ticking."""
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                chunk = f.read().decode("utf-8", "replace")
        except OSError:
            return {}
        import json as _json

        for line in reversed(chunk.splitlines()):
            line = line.strip()
            if not line:
                continue
            try:
                row = _json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict):
                return row
        return {}

    @staticmethod
    def _count_lines_bounded(path: str, max_bytes: int = 256 << 10) -> int:
        """Line count of a jsonl file, reading at most ``max_bytes``
        from the head — exact for every sane breach stream, a floor for
        a pathological one (the fleet view needs "how bad", not an
        audit-grade total)."""
        try:
            with open(path, "rb") as f:
                return f.read(max_bytes).count(b"\n")
        except OSError:
            return 0

    def fleet_payload(self) -> dict:
        """The ``GET /fleet`` summary: worker slots, queue depth, pack
        occupancy, and one row per queued/running task with live
        ticks/s (sim_perf.jsonl tail) and SLO breach counts. Counts
        cover the FULL task store; the per-task list is naturally
        bounded by what is actually queued or running."""
        now = time.time()
        all_tasks = self.storage.filter()
        counts: dict[str, int] = {}
        by_priority: dict[int, int] = {}
        rows: list[dict] = []
        outputs = self.env.dirs.outputs()
        with self._fleet_lock:
            worker_task = dict(self._worker_task)
            running_packs = dict(self._running_packs)
            n_workers = max(len(self._workers), len(self._worker_task))
        for tsk in all_tasks:
            st = tsk.state().state
            counts[st.value] = counts.get(st.value, 0) + 1
            if st == State.SCHEDULED:
                by_priority[tsk.priority] = by_priority.get(tsk.priority, 0) + 1
            if st not in (State.SCHEDULED, State.PROCESSING):
                continue
            row = {
                "id": tsk.id,
                "name": tsk.name(),
                "type": tsk.type.value,
                "state": st.value,
                "priority": tsk.priority,
                "queued_secs": round(tsk.queued_secs(), 3),
                "trace_id": tsk.trace.get("trace_id", ""),
                # how many times the fleet controller migrated this
                # task (rides Task.trace so it survives requeues)
                "preemptions": int(tsk.trace.get("preemptions", 0) or 0),
            }
            if st == State.PROCESSING:
                row["running_secs"] = round(
                    max(0.0, now - tsk.state().created), 3
                )
                row["pack_width"] = running_packs.get(tsk.id, 0)
                run_dir = os.path.join(outputs, tsk.plan, tsk.id)
                perf = self._tail_last_row(
                    os.path.join(run_dir, "sim_perf.jsonl")
                )
                if perf:
                    row["ticks_per_sec"] = perf.get("ticks_per_sec", 0)
                row["breaches"] = self._count_lines_bounded(
                    os.path.join(run_dir, "sim_slo.jsonl")
                )
            rows.append(row)
        rows.sort(key=lambda r: (r["state"], -r["priority"], r["id"]))
        busy = sum(1 for t in worker_task.values() if t)
        return {
            "ts_wall_ns": time.time_ns(),
            "workers": {
                "total": n_workers,
                "busy": busy,
                "idle": max(0, n_workers - busy),
            },
            "draining": self._draining.is_set(),
            "queue": {
                "depth": counts.get(State.SCHEDULED.value, 0),
                "by_priority": {str(k): v for k, v in by_priority.items()},
            },
            "counts": counts,
            "tasks_total": len(all_tasks),
            "pack": {"running": running_packs},
            "tasks": rows,
        }

    # -------------------------------------------------------------- actions

    def do_collect_outputs(self, runner_id: str, run_id: str, w, ow) -> None:
        """(``engine.go:251-`` DoCollectOutputs)."""
        from testground_tpu.api import CollectionInput

        runner = self.runner_by_name(runner_id)
        if runner is None:
            raise ValueError(f"unknown runner: {runner_id}")
        runner.collect_outputs(
            CollectionInput(run_id=run_id, runner_id=runner_id, env=self.env), w, ow
        )

    def do_terminate(self, ref: str, ow, ctype: str = "runner") -> None:
        """Terminate all jobs of a runner OR a builder (the reference's
        DoTerminate takes a component type, ``engine.go:285-311``)."""
        from testground_tpu.runners.base import Terminatable

        if ctype == "runner":
            component = self.runner_by_name(ref)
        elif ctype == "builder":
            component = self.builder_by_name(ref)
        else:
            raise ValueError(f"unknown component type: {ctype}")
        if component is None:
            raise ValueError(f"unknown component: {ref} (type: {ctype})")
        if not isinstance(component, Terminatable):
            raise ValueError(f"{ctype} {ref} is not terminatable")
        component.terminate_all(ow)
        ow.infof("all jobs terminated on component: %s", ref)

    def do_healthcheck(self, runner_id: str, fix: bool, ow):
        from testground_tpu.runners.base import HealthcheckedRunner

        runner = self.runner_by_name(runner_id)
        if runner is None:
            raise ValueError(f"unknown runner: {runner_id}")
        if not isinstance(runner, HealthcheckedRunner):
            raise ValueError(f"runner {runner_id} does not support healthchecks")
        return runner.healthcheck(fix, ow, env=self.env)

    def do_build_purge(self, builder_id: str, testplan: str, ow) -> None:
        builder = self.builder_by_name(builder_id)
        if builder is None:
            raise ValueError(f"unknown builder: {builder_id}")
        builder.purge(testplan, ow, env=self.env)
