"""Task-status webhooks: Slack + GitHub commit statuses.

Twin of the reference's ``pkg/engine/supervisor.go:192-296``
(``postStatusToGithub`` / ``postStatusToSlack``): when the daemon config
carries a Slack webhook URL or a GitHub repo-status token, every finished
task posts its outcome. Failures are logged, never raised — notifications
must not affect task processing (``supervisor.go:176-183``).

The endpoints are configurable (``root_url`` gives dashboard links; the
GitHub API base is overridable for tests) and requests use stdlib urllib
with a 10 s timeout, matching the reference's plain http.Client.
"""

from __future__ import annotations

import json
import urllib.request

from testground_tpu.config import EnvConfig
from testground_tpu.logging_ import S

from .task import Outcome, State, Task

__all__ = [
    "notify_task_finished",
    "notify_task_started",
    "post_status_to_github",
    "post_status_to_slack",
]

GITHUB_API = "https://api.github.com"
_TIMEOUT = 10.0


def _post(url: str, payload: dict, headers: dict | None = None) -> None:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={
            "Content-Type": "application/json; charset=UTF-8",
            **(headers or {}),
        },
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=_TIMEOUT):
        pass


def _task_url(env: EnvConfig, tsk: Task) -> str:
    root = env.daemon.root_url or f"http://{env.daemon.listen}"
    return f"{root.rstrip('/')}/dashboard?task_id={tsk.id}"


def post_status_to_slack(env: EnvConfig, tsk: Task) -> None:
    """(``supervisor.go:261-296``)."""
    url = env.daemon.slack_webhook_url
    if not url:
        return
    link = f"<{_task_url(env, tsk)}|{tsk.id}>"
    took = f"{tsk.took():.1f}s"
    outcome = tsk.outcome()
    if outcome == Outcome.SUCCESS:
        text = f"✅ {link} *{tsk.name()}* run succeeded ({took})"
    elif outcome == Outcome.CANCELED:
        text = f"⚪ {link} *{tsk.name()}* run canceled ({took}) ; {tsk.error}"
    elif outcome == Outcome.FAILURE:
        text = f"❌ {link} *{tsk.name()}* run failed ({took}) ; {tsk.error}"
    else:
        text = f"{link} *{tsk.name()}* run completed"
    _post(url, {"text": text})


def post_status_to_github(
    env: EnvConfig, tsk: Task, api_base: str | None = None
) -> None:
    """Commit status for CI-created tasks (``supervisor.go:192-258``)."""
    token = env.daemon.github_repo_status_token
    if not token or not tsk.created_by_ci():
        return
    parts = tsk.created_by.repo.split("/")
    if len(parts) != 2:
        S().warning(
            "github status: malformed repo %r", tsk.created_by.repo
        )
        return
    owner, repo = parts

    st = tsk.state().state
    if st == State.PROCESSING:
        state, msg = "pending", "testground is running your plan"
    elif st in (State.COMPLETE, State.CANCELED):
        outcome = tsk.outcome()
        if outcome == Outcome.SUCCESS:
            state, msg = "success", "Testplan run succeeded!"
        elif outcome == Outcome.CANCELED:
            state, msg = "failure", "Testplan run was canceled!"
        elif outcome == Outcome.FAILURE:
            state, msg = "failure", "Testplan run failed!"
        else:
            return
    else:
        return

    url = (
        f"{(api_base or GITHUB_API).rstrip('/')}/repos/{owner}/{repo}/"
        f"statuses/{tsk.created_by.commit}"
    )
    _post(
        url,
        {
            "state": state,
            "target_url": _task_url(env, tsk),
            "description": msg,
            "context": f"testground/{tsk.plan}/{tsk.case}",
        },
        headers={
            "Authorization": f"Basic {token}",
            "Accept": "application/vnd.github.v3+json",
        },
    )


def notify_task_started(env: EnvConfig, tsk: Task) -> None:
    """Pending commit status when a CI task enters PROCESSING — the
    'pending' branch of ``postStatusToGithub`` (``supervisor.go:213-215``).
    Log-and-continue on failure."""
    try:
        post_status_to_github(env, tsk)
    except Exception as e:  # noqa: BLE001 — notifications are best-effort
        S().error("could not post pending status to github: %s", e)


def notify_task_finished(env: EnvConfig, tsk: Task) -> None:
    """Post everywhere configured; log-and-continue on failure
    (``supervisor.go:176-183``)."""
    for poster, name in (
        (post_status_to_slack, "slack"),
        (post_status_to_github, "github"),
    ):
        try:
            poster(env, tsk)
        except Exception as e:  # noqa: BLE001 — notifications are best-effort
            S().error("could not post task status to %s: %s", name, e)
