"""Live observability stream: tail a task's per-run jsonl families.

The run health plane (docs/OBSERVABILITY.md "Run health plane") needs a
way to WATCH a run, not just autopsy it. Every observability writer in
the sim executor already streams append-only jsonl — per-tick telemetry
(``sim_timeseries.jsonl``), per-chunk perf rows (``sim_perf.jsonl``),
SLO breach records (``sim_slo.jsonl``), host-side run spans
(``run_spans.jsonl``) — flushed once per chunk dispatch. This module is
the read side: a generator that tails those files as they grow and
yields each complete line as a dict tagged with its family, across the
whole queued → running → done lifecycle:

- **queued**: the run dir does not exist yet — with ``follow`` the
  generator polls until it appears (or the task finishes first);
- **running**: new rows stream out within a poll interval of the
  writer's flush, partial trailing lines are never consumed (the writer
  may be mid-``write``);
- **done**: one final sweep after the task reports finished, then the
  stream closes. Following an already-finished task replays the full
  history and closes — the ``engine.logs`` follow contract.

The daemon's ``GET /stream`` route, ``Client.stream`` and ``tg watch``
all sit on this one generator, so the surfaces cannot drift. Import-
light (stdlib + the telemetry/slo file-name constants): no jax.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Iterator

from testground_tpu.sim.slo import SLO_FILE
from testground_tpu.sim.telemetry import (
    NETMATRIX_FILE,
    PERF_FILE,
    PHASES_FILE,
    SIM_SERIES_FILE,
    SPAN_FILE,
)

__all__ = ["STREAM_FAMILIES", "stream_task_rows"]

# family name → per-run file it tails. Ordered: within one sweep,
# telemetry rows precede the perf/slo rows of the same chunk so a
# consumer folding "counters, then the chunk line" sees them in causal
# order (the executor writes them in this order too).
STREAM_FAMILIES = (
    ("telemetry", SIM_SERIES_FILE),
    # traffic-matrix chunk deltas (sim/netmatrix.py) — one sparse row
    # per chunk, the `tg netmap -f` live feed
    ("netmatrix", NETMATRIX_FILE),
    ("perf", PERF_FILE),
    # phase attribution rows (sim/phases.py) — written once at collect
    # time, so a follow replays them right before the task closes
    ("phases", PHASES_FILE),
    ("slo", SLO_FILE),
    ("spans", SPAN_FILE),
)

_POLL_SECS = 0.15

# bytes per read while draining a backlog: a multi-day soak's replay
# (GET /stream on a finished task) must not land its whole multi-GB
# jsonl in one allocation — rows stream out chunk by chunk instead
_READ_CHUNK = 4 << 20


class _Tail:
    """Byte-offset tail over one jsonl file: yields complete lines only
    (the trailing partial line of an in-flight write stays unconsumed
    until its newline lands)."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0

    def read_new(self) -> Iterator[dict]:
        """Yield the rows appended since the last call, reading in
        bounded chunks (memory stays O(_READ_CHUNK) however large the
        backlog)."""
        try:
            size = os.path.getsize(self.path)
            if size <= self.offset:
                return
            with open(self.path, "rb") as f:
                while self.offset < size:
                    f.seek(self.offset)
                    data = f.read(min(_READ_CHUNK, size - self.offset))
                    if not data:
                        return
                    end = data.rfind(b"\n")
                    # a single line longer than the chunk: keep reading
                    # until its newline (degenerate, rows are ~100 B)
                    while end < 0 and self.offset + len(data) < size:
                        more = f.read(
                            min(_READ_CHUNK, size - self.offset - len(data))
                        )
                        if not more:
                            return
                        data += more
                        end = data.rfind(b"\n")
                    if end < 0:
                        return  # no complete line yet
                    self.offset += end + 1
                    for line in data[: end + 1].splitlines():
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            yield json.loads(line)
                        except json.JSONDecodeError:
                            continue  # foreign noise — tolerant reader
        except OSError:
            return


def stream_task_rows(
    outputs_root: str,
    plan: str,
    task_id: str,
    is_done: Callable[[], bool],
    follow: bool = True,
    cancel=None,
    families=None,
    poll_secs: float = _POLL_SECS,
    heartbeat_secs: float = 0.0,
) -> Iterator[dict]:
    """Yield a task's observability rows, each as
    ``{"stream": <family>, "run": <run id>, ...row}``.

    A task's runs live under ``<outputs>/<plan>/<task_id>`` (single run)
    or ``<task_id>-<run_id>`` (multi-``[[runs]]``); every matching run
    dir contributes, tagged with its run id (rows that already carry a
    ``run`` key keep it — it is the same id). ``is_done()`` is the
    task-finished probe (COMPLETE/CANCELED); without ``follow`` the
    generator performs one sweep of everything written so far and
    closes. ``families`` narrows to a subset of
    :data:`STREAM_FAMILIES` names (e.g. ``("perf",)`` for ``tg perf
    -f``). ``heartbeat_secs`` > 0 yields ``None`` whenever that long
    passes with no rows — the daemon turns it into a blank ndjson line
    so an idle follow (queued task, long compile, quiet soak) cannot
    trip a client's socket read timeout."""
    fams = [
        (name, fname)
        for name, fname in STREAM_FAMILIES
        if families is None or name in families
    ]
    root = os.path.join(outputs_root, plan)
    tails: dict[tuple[str, str], _Tail] = {}

    def sweep() -> Iterator[dict]:
        run_ids = []
        try:
            run_ids = sorted(
                rid
                for rid in os.listdir(root)
                if rid == task_id or rid.startswith(task_id + "-")
            )
        except OSError:
            return
        for rid in run_ids:
            for fam, fname in fams:
                path = os.path.join(root, rid, fname)
                key = (rid, fam)
                tail = tails.get(key)
                if tail is None:
                    if not os.path.isfile(path):
                        continue
                    tail = tails[key] = _Tail(path)
                for row in tail.read_new():
                    yield {"stream": fam, "run": rid, **row}

    last_row = time.monotonic()
    while True:
        done = is_done()  # probe BEFORE the sweep: rows written before
        # the probe are guaranteed to be in this (or a prior) sweep, so
        # a done task never closes with unread rows
        for row in sweep():
            last_row = time.monotonic()
            yield row
        if not follow or done:
            return
        if cancel is not None and cancel.is_set():
            return
        if heartbeat_secs and time.monotonic() - last_row >= heartbeat_secs:
            last_row = time.monotonic()
            yield None
        time.sleep(poll_secs)
