"""Persistent priority queue for tasks.

Twin of the reference's ``pkg/task/queue.go``: an in-memory heap ordered by
priority (descending) then creation time (FIFO), write-through to storage, a
bounded size, rehydration from storage on restart, and CI dedup via
``push_unique_by_branch``.
"""

from __future__ import annotations

import heapq
import threading
import time

from .storage import TaskStorage
from .task import DatedState, State, Task

__all__ = ["QueueFullError", "QueueEmptyError", "TaskQueue"]


class QueueFullError(Exception):
    """(``queue.go:15``)."""


class QueueEmptyError(Exception):
    """(``queue.go:14``)."""


class _Entry:
    """Heap entry: priority desc, then FIFO by creation time
    (``queue.go:178-189``)."""

    __slots__ = ("task",)

    def __init__(self, task: Task):
        self.task = task

    def __lt__(self, other: "_Entry") -> bool:
        a, b = self.task, other.task
        if a.priority != b.priority:
            return a.priority > b.priority
        return a.created() < b.created()


class TaskQueue:
    """Thread-safe bounded priority queue, write-through persisted."""

    def __init__(self, storage: TaskStorage, max_size: int):
        self._storage = storage
        self._max = max_size
        self._lock = threading.Lock()
        self._heap: list[_Entry] = []
        # Rehydrate scheduled + interrupted-processing tasks from storage
        # (``queue.go:18-31``).
        for tsk in storage.recover_processing():
            heapq.heappush(self._heap, _Entry(tsk))
        for tsk in storage.scheduled():
            if not any(e.task.id == tsk.id for e in self._heap):
                heapq.heappush(self._heap, _Entry(tsk))

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)

    def push(self, tsk: Task) -> None:
        """(``queue.go:52-76``)."""
        with self._lock:
            self._push_locked(tsk)

    def _push_locked(self, tsk: Task) -> None:
        if len(self._heap) >= self._max:
            raise QueueFullError("queue full")
        self._storage.persist_scheduled(tsk)
        heapq.heappush(self._heap, _Entry(tsk))

    def requeue(self, tsk: Task) -> None:
        """Put a claimed (PROCESSING) task back on the queue — the fleet
        controller's preempt/drain/evict path (docs/FLEET.md). Bypasses
        the size bound: the task already held a queue slot once, and a
        full queue must never strand a checkpointed evictee in limbo.
        The caller appends the SCHEDULED state first; storage moves the
        record current → queue atomically."""
        with self._lock:
            self._storage.persist_rescheduled(tsk)
            heapq.heappush(self._heap, _Entry(tsk))

    def push_unique_by_branch(self, tsk: Task) -> None:
        """Cancel queued tasks from the same repo+branch, then push
        (``queue.go:79-96``)."""
        with self._lock:
            if tsk.created_by.repo and tsk.created_by.branch:
                self._remove_existing_locked(
                    tsk.created_by.branch, tsk.created_by.repo
                )
            self._push_locked(tsk)

    def _remove_existing_locked(self, branch: str, repo: str) -> None:
        keep: list[_Entry] = []
        for e in self._heap:
            cb = e.task.created_by
            if cb.repo == repo and cb.branch == branch:
                self._cancel_locked(e.task)
            else:
                keep.append(e)
        self._heap = keep
        heapq.heapify(self._heap)

    def _cancel_locked(self, tsk: Task) -> None:
        """(``queue.go:146-170``)."""
        tsk.states.append(DatedState(state=State.CANCELED, created=time.time()))
        self._storage.archive(tsk)

    def pop(self) -> Task:
        """Pop highest-priority task and mark it processing in storage
        (``queue.go:101-117``)."""
        with self._lock:
            if not self._heap:
                raise QueueEmptyError("queue empty")
            tsk = heapq.heappop(self._heap).task
            tsk.states.append(
                DatedState(state=State.PROCESSING, created=time.time())
            )
            self._storage.persist_processing(tsk)
            return tsk

    def claim_matching(self, match, limit: int) -> list[Task]:
        """Pop up to ``limit`` queued tasks satisfying ``match(task)``,
        in heap order (priority desc, then FIFO) — the pack-admission
        claim (``engine/pack.py``). Each claimed task transitions to
        PROCESSING exactly like :meth:`pop`; the caller owns its
        lifecycle from here."""
        if limit <= 0:
            return []
        claimed: list[Task] = []
        with self._lock:
            keep: list[_Entry] = []
            # heap order = sorted entries (priority desc, FIFO)
            for e in sorted(self._heap):
                if len(claimed) < limit and match(e.task):
                    e.task.states.append(
                        DatedState(
                            state=State.PROCESSING, created=time.time()
                        )
                    )
                    self._storage.persist_processing(e.task)
                    claimed.append(e.task)
                else:
                    keep.append(e)
            if claimed:
                self._heap = keep
                heapq.heapify(self._heap)
        return claimed

    def cancel_queued(self, task_id: str) -> bool:
        """Cancel a still-queued task by id (used by the engine's kill path
        for tasks that never started)."""
        with self._lock:
            for i, e in enumerate(self._heap):
                if e.task.id == task_id:
                    del self._heap[i]
                    heapq.heapify(self._heap)
                    self._cancel_locked(e.task)
                    return True
        return False
