"""Task model: the unit of scheduled work.

Twin of the reference's ``pkg/task/task.go``: a task moves through
scheduled → processing → complete (or canceled), carries its composition and
input, and ends with an outcome (unknown/success/failure/canceled).
"""

from __future__ import annotations

import enum
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any

__all__ = [
    "CreatedBy",
    "DatedState",
    "Outcome",
    "State",
    "Task",
    "TaskType",
    "new_task_id",
]


class State(str, enum.Enum):
    """(``task.go:13-20``)."""

    SCHEDULED = "scheduled"
    PROCESSING = "processing"
    COMPLETE = "complete"
    CANCELED = "canceled"


class Outcome(str, enum.Enum):
    """(``task.go:22-29``)."""

    UNKNOWN = "unknown"
    SUCCESS = "success"
    FAILURE = "failure"
    CANCELED = "canceled"


class TaskType(str, enum.Enum):
    """(``task.go:31-40``)."""

    BUILD = "build"
    RUN = "run"


# xid-style ids: 20 lowercase base32hex chars, time-prefixed so they sort by
# creation (the reference uses rs/xid; integration_tests/header.sh asserts
# run-id length == 20).
_B32HEX = "0123456789abcdefghijklmnopqrstuv"
_counter = [secrets.randbelow(1 << 24)]
_counter_lock = threading.Lock()


def _b32(n: int, width: int) -> str:
    out = []
    for _ in range(width):
        out.append(_B32HEX[n & 31])
        n >>= 5
    return "".join(reversed(out))


def new_task_id() -> str:
    with _counter_lock:
        _counter[0] = (_counter[0] + 1) & 0xFFFFFF
        cnt = _counter[0]
    ts = int(time.time())
    rnd = (os.getpid() & 0xFFFF) ^ secrets.randbelow(1 << 16)
    # 7 chars time + 4 chars pid/random + 4 chars random + 5 chars counter = 20
    return (
        _b32(ts, 7) + _b32(rnd, 4) + _b32(secrets.randbelow(1 << 20), 4) + _b32(cnt, 5)
    )


@dataclass
class DatedState:
    """A state with a timestamp (``task.go:43-46``)."""

    state: State
    created: float  # unix seconds

    def to_dict(self) -> dict:
        return {"state": self.state.value, "created": self.created}

    @classmethod
    def from_dict(cls, d: dict) -> "DatedState":
        return cls(state=State(d["state"]), created=float(d["created"]))


@dataclass
class CreatedBy:
    """Who created the task (``task.go:48-53``)."""

    user: str = ""
    repo: str = ""
    branch: str = ""
    commit: str = ""

    def to_dict(self) -> dict:
        return {
            "user": self.user,
            "repo": self.repo,
            "branch": self.branch,
            "commit": self.commit,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CreatedBy":
        return cls(
            user=d.get("user", ""),
            repo=d.get("repo", ""),
            branch=d.get("branch", ""),
            commit=d.get("commit", ""),
        )


@dataclass
class Task:
    """(``task.go:55-74``)."""

    id: str
    type: TaskType
    priority: int = 0
    version: int = 0
    runner: str = ""
    plan: str = ""
    case: str = ""
    states: list[DatedState] = field(default_factory=list)
    composition: Any = None  # dict form of the composition
    input: Any = None
    result: Any = None
    error: str = ""
    created_by: CreatedBy = field(default_factory=CreatedBy)
    # Causal lifecycle-trace ids (tracectx.py): trace_id plus the span
    # ids of the lifecycle phases minted so far (root/queued/claim/
    # execute). NOT the flight recorder — that lives in the result
    # journal under "trace"; this keyspace is control-plane only.
    trace: dict = field(default_factory=dict)

    def created(self) -> float:
        if not self.states:
            raise ValueError("task must have a state")
        return self.states[0].created

    def state(self) -> DatedState:
        if not self.states:
            raise ValueError("task must have a state")
        return self.states[-1]

    def is_canceled(self) -> bool:
        return self.state().state == State.CANCELED

    def name(self) -> str:
        if self.type == TaskType.BUILD:
            return "build"
        return f"{self.plan}:{self.case}"

    def took(self) -> float:
        """Seconds from creation to last state transition (``task.go:98-100``)."""
        return self.state().created - self.created()

    def queued_secs(self) -> float:
        """Seconds the task spent (or has spent so far) in the queue:
        scheduled → first PROCESSING transition, or scheduled → now for
        a task still waiting. The same quantity the supervisor reports
        in the perf payload, computable for every task in the store."""
        if not self.states:
            return 0.0
        t0 = self.states[0].created
        for ds in self.states[1:]:
            if ds.state == State.PROCESSING:
                return max(0.0, ds.created - t0)
        if self.states[-1].state == State.SCHEDULED:
            return max(0.0, time.time() - t0)
        return 0.0

    def created_by_ci(self) -> bool:
        cb = self.created_by
        return bool(cb.repo and cb.commit and cb.branch)

    def outcome(self) -> Outcome:
        """Map task state + result to an outcome — the semantics of
        ``pkg/data/result.go:17-51``."""
        st = self.state().state
        if st == State.CANCELED:
            return Outcome.CANCELED
        if st != State.COMPLETE:
            return Outcome.UNKNOWN
        if self.error:
            return Outcome.FAILURE
        if isinstance(self.result, dict) and "outcome" in self.result:
            try:
                return Outcome(self.result["outcome"])
            except ValueError:
                return Outcome.UNKNOWN
        return Outcome.UNKNOWN

    def stats_payload(self) -> dict:
        """The telemetry-summary payload (``tg stats`` / GET /stats):
        identity plus the result journal's sim/telemetry/events sections.
        ONE builder for the daemon route and the in-process CLI, so the
        two surfaces cannot drift."""
        journal = (
            self.result.get("journal", {})
            if isinstance(self.result, dict)
            else {}
        )
        return {
            "task_id": self.id,
            "plan": self.plan,
            "case": self.case,
            "state": self.state().state.value,
            "outcome": self.outcome().value,
            "sim": journal.get("sim", {}),
            "telemetry": journal.get("telemetry", {}),
            # flight-recorder summary (docs/OBSERVABILITY.md) — the
            # events themselves are served by `tg trace` / GET /trace
            "trace": journal.get("trace", {}),
            # run health plane (docs/OBSERVABILITY.md "Run health
            # plane"): rule verdicts + bounded breach records
            "slo": journal.get("slo", {}),
            "events": journal.get("events", {}),
        }

    def perf_payload(self) -> dict:
        """The performance-ledger payload (``tg perf`` / GET /perf):
        identity, the journal's sim block, its nested perf ledger
        (surfaced at top level for consumers), and the supervisor's
        task-level timings (queue wait, per-run runner wall). ONE
        builder for the daemon route and the in-process CLI — same rule
        as :meth:`stats_payload`."""
        result = self.result if isinstance(self.result, dict) else {}
        journal = result.get("journal", {})
        if not isinstance(journal, dict):
            journal = {}
        sim = journal.get("sim", {})
        if not isinstance(sim, dict):
            sim = {}
        return {
            "task_id": self.id,
            "plan": self.plan,
            "case": self.case,
            "state": self.state().state.value,
            "outcome": self.outcome().value,
            "sim": {
                k: v for k, v in sim.items() if k not in ("perf", "phases")
            },
            "perf": sim.get("perf", {}),
            # phase attribution plane (sim/phases.py) — surfaced at top
            # level beside the ledger for `tg perf --phases` consumers
            "phases": sim.get("phases", {}),
            "task": result.get("perf", {})
            if isinstance(result.get("perf"), dict)
            else {},
        }

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "priority": self.priority,
            "id": self.id,
            "type": self.type.value,
            "runner": self.runner,
            "plan": self.plan,
            "case": self.case,
            "states": [s.to_dict() for s in self.states],
            "composition": self.composition,
            "input": self.input,
            "result": self.result,
            "error": self.error,
            "outcome": self.outcome().value,
            "created_by": self.created_by.to_dict(),
            "trace": dict(self.trace),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Task":
        return cls(
            id=d["id"],
            type=TaskType(d["type"]),
            priority=int(d.get("priority", 0)),
            version=int(d.get("version", 0)),
            runner=d.get("runner", ""),
            plan=d.get("plan", ""),
            case=d.get("case", ""),
            states=[DatedState.from_dict(s) for s in d.get("states", [])],
            composition=d.get("composition"),
            input=d.get("input"),
            result=d.get("result"),
            error=d.get("error", ""),
            created_by=CreatedBy.from_dict(d.get("created_by", {})),
            trace=dict(d.get("trace") or {}),
        )
