"""Fleet controller: the preemption/eviction/admission decision layer.

Composes the robustness organs the repo grew separately — bit-identical
checkpoint/resume (sim/checkpoint.py), SLO cancel-at-boundary
(sim/slo.py), pack admission (engine/pack.py), the `tg check` rules
engine (sim/check.py), and the daemon event journal (engine/events.py) —
into one controller loop that keeps runs alive through preemption
(docs/FLEET.md):

- **live migration**: a preemption signal (``POST /preempt``, ``tg
  preempt``, priority eviction, daemon drain) checkpoints the running
  task at the next chunk boundary and requeues it to resume from its
  own newest snapshot, completing bit-equal to an uninterrupted run;
- **priority eviction**: a high-priority arrival that cannot be
  admitted evicts the lowest-value running task instead of queueing
  behind it (:func:`pick_eviction_victim`);
- **admission-at-submit**: the daemon refuses a composition the
  ``tg check`` rules engine rejects, at submit time, with the same
  rule ids.

Import-light on purpose (stdlib only): the executor raises
:class:`TaskPreemptedError` from inside the jax-heavy sim module, and
the supervisor's worker thread catches it without loading jax — the
same contract ``sim/slo.py`` keeps for :class:`SloBreachError`.
"""

from __future__ import annotations

__all__ = ["TaskPreemptedError", "pick_eviction_victim"]


class TaskPreemptedError(RuntimeError):
    """A run stopped at a chunk boundary because its preemption signal
    was set — not a failure: the supervisor requeues the task to resume
    from its newest snapshot (``resumable=True``) or to rerun from
    scratch deterministically (``resumable=False`` — the run never
    wrote a snapshot, e.g. checkpointing was off).

    Ordering contract (executor tail, ``sim/executor.py``): an operator
    cancel wins over preemption (the task archives CANCELED), and a
    fail-severity SLO breach wins too (the breach IS the run's verdict;
    resuming a run the health plane already condemned would launder the
    failure).
    """

    def __init__(
        self,
        run_id: str,
        *,
        tick: int = 0,
        snapshot_tick: int = 0,
        snapshots: int = 0,
        resumable: bool = False,
    ):
        self.run_id = run_id
        self.tick = int(tick)
        self.snapshot_tick = int(snapshot_tick)
        self.snapshots = int(snapshots)
        self.resumable = bool(resumable)
        super().__init__(
            f"run {run_id} preempted at tick {tick}"
            + (
                f" (snapshot at tick {snapshot_tick}, will resume)"
                if resumable
                else " (no snapshot — will rerun from scratch)"
            )
        )


def pick_eviction_victim(
    candidates: list[dict], arriving_priority: int
) -> dict | None:
    """Choose which running task a high-priority arrival evicts, or
    None when nothing should be (every candidate is at least as
    important as the arrival).

    ``candidates`` rows: ``{"id", "priority", "started" (epoch secs),
    "checkpointed" (bool)}`` — one per running preemptible task.

    Policy (lowest value lost first):

    1. only tasks with ``priority < arriving_priority`` are evictable —
       eviction must never be a lateral move, or two equal-priority
       tenants would evict each other forever;
    2. among those, the LOWEST priority loses first;
    3. tie-break: prefer a checkpointed victim (it resumes from its
       snapshot, so eviction costs at most one checkpoint interval of
       replay — an uncheckpointed victim reruns from scratch);
    4. final tie-break: the most recently started (least work lost).
    """
    evictable = [
        c
        for c in candidates
        if int(c.get("priority", 0)) < int(arriving_priority)
    ]
    if not evictable:
        return None
    return min(
        evictable,
        key=lambda c: (
            int(c.get("priority", 0)),
            # False < True: uncheckpointed sorts first at equal
            # priority — invert so checkpointed wins the min()
            not bool(c.get("checkpointed")),
            -float(c.get("started", 0.0)),
        ),
    )
