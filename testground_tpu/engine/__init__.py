"""The scheduler core: task model, persistent priority queue, worker
supervisor, and the engine facade tying builders/runners together.

Twin of the reference's ``pkg/engine`` + ``pkg/task``.
"""

from .task import (
    CreatedBy,
    DatedState,
    Outcome,
    State,
    Task,
    TaskType,
)
from .storage import TaskStorage
from .queue import QueueFullError, TaskQueue
from .engine import Engine, EngineConfig

__all__ = [
    "CreatedBy",
    "DatedState",
    "Engine",
    "EngineConfig",
    "Outcome",
    "QueueFullError",
    "State",
    "Task",
    "TaskQueue",
    "TaskStorage",
    "TaskType",
]
