"""Task storage.

Twin of the reference's ``pkg/task/storage.go`` (LevelDB with ``queue`` /
``current`` / ``archive`` prefixes) on sqlite3: one table keyed by
(bucket, task id), with date-ordered iteration for filtering. A ``:memory:``
path gives the reference's in-memory storage mode.
"""

from __future__ import annotations

import json
import sqlite3
import threading

from .task import DatedState, State, Task

__all__ = ["TaskStorage"]

BUCKET_QUEUE = "queue"
BUCKET_CURRENT = "current"
BUCKET_ARCHIVE = "archive"

_SCHEMA = """
CREATE TABLE IF NOT EXISTS tasks (
    bucket  TEXT NOT NULL,
    id      TEXT NOT NULL,
    created REAL NOT NULL,
    data    TEXT NOT NULL,
    PRIMARY KEY (bucket, id)
);
CREATE INDEX IF NOT EXISTS tasks_by_created ON tasks (bucket, created);
"""


class TaskStorage:
    """Persist tasks through their lifecycle. Thread-safe."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._db.executescript(_SCHEMA)
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()

    # -------------------------------------------------------------- persists

    def _move(self, tsk: Task, to_bucket: str, from_buckets: tuple[str, ...]) -> None:
        """Atomically move a task between buckets: one transaction, so a
        concurrent ``get()`` never observes the task in no bucket."""
        with self._lock:
            for b in from_buckets:
                self._db.execute(
                    "DELETE FROM tasks WHERE bucket = ? AND id = ?", (b, tsk.id)
                )
            self._db.execute(
                "INSERT OR REPLACE INTO tasks (bucket, id, created, data) "
                "VALUES (?, ?, ?, ?)",
                (to_bucket, tsk.id, tsk.created(), json.dumps(tsk.to_dict())),
            )
            self._db.commit()

    def _delete(self, bucket: str, task_id: str) -> None:
        with self._lock:
            self._db.execute(
                "DELETE FROM tasks WHERE bucket = ? AND id = ?", (bucket, task_id)
            )
            self._db.commit()

    def persist_scheduled(self, tsk: Task) -> None:
        """Task entered the queue (``storage.go:140-145``)."""
        self._move(tsk, BUCKET_QUEUE, ())

    def persist_processing(self, tsk: Task) -> None:
        """Task moved queue → current (``storage.go:147-151``)."""
        self._move(tsk, BUCKET_CURRENT, (BUCKET_QUEUE,))

    def persist_rescheduled(self, tsk: Task) -> None:
        """A preempted/drained task moved current → queue (the fleet
        controller's requeue, docs/FLEET.md). Clearing the CURRENT row
        in the same transaction matters: ``get()`` prefers CURRENT over
        QUEUE, so a plain ``persist_scheduled`` would leave a stale
        PROCESSING record shadowing the requeued one."""
        self._move(tsk, BUCKET_QUEUE, (BUCKET_CURRENT,))

    def update_current(self, tsk: Task) -> None:
        self._move(tsk, BUCKET_CURRENT, ())

    def archive(self, tsk: Task) -> None:
        """Task finished; move current → archive (``storage.go:153-158``)."""
        self._move(tsk, BUCKET_ARCHIVE, (BUCKET_QUEUE, BUCKET_CURRENT))

    # ---------------------------------------------------------------- reads

    def get(self, task_id: str) -> Task | None:
        """Look up a task in any bucket (archive > current > queue wins so the
        most-final record is returned)."""
        with self._lock:
            rows = self._db.execute(
                "SELECT bucket, data FROM tasks WHERE id = ?", (task_id,)
            ).fetchall()
        by_bucket = {b: d for b, d in rows}
        for bucket in (BUCKET_ARCHIVE, BUCKET_CURRENT, BUCKET_QUEUE):
            if bucket in by_bucket:
                return Task.from_dict(json.loads(by_bucket[bucket]))
        return None

    def list_bucket(self, bucket: str, newest_first: bool = True) -> list[Task]:
        order = "DESC" if newest_first else "ASC"
        with self._lock:
            rows = self._db.execute(
                f"SELECT data FROM tasks WHERE bucket = ? ORDER BY created {order}",
                (bucket,),
            ).fetchall()
        return [Task.from_dict(json.loads(r[0])) for r in rows]

    def scheduled(self) -> list[Task]:
        return self.list_bucket(BUCKET_QUEUE, newest_first=False)

    def processing(self) -> list[Task]:
        return self.list_bucket(BUCKET_CURRENT, newest_first=False)

    def archived(self) -> list[Task]:
        return self.list_bucket(BUCKET_ARCHIVE)

    def filter(
        self,
        types: list[str] | None = None,
        states: list[str] | None = None,
        before: float | None = None,
        after: float | None = None,
        limit: int = 0,
    ) -> list[Task]:
        """Date-range + type/state filtered listing, newest first
        (``storage.go:188-232`` semantics)."""
        out: list[Task] = []
        for bucket, state in (
            (BUCKET_QUEUE, State.SCHEDULED),
            (BUCKET_CURRENT, State.PROCESSING),
            (BUCKET_ARCHIVE, State.COMPLETE),
        ):
            if states and state.value not in states:
                continue
            for tsk in self.list_bucket(bucket):
                if types and tsk.type.value not in types:
                    continue
                if before is not None and tsk.created() >= before:
                    continue
                if after is not None and tsk.created() <= after:
                    continue
                out.append(tsk)
        out.sort(key=lambda t: t.created(), reverse=True)
        if limit:
            out = out[:limit]
        return out

    def delete(self, task_id: str) -> bool:
        """Remove a task's records from every bucket (the reference daemon's
        GET ``/delete`` surface, ``pkg/daemon/daemon.go:88``). Returns True
        if anything was deleted."""
        with self._lock:
            cur = self._db.execute(
                "DELETE FROM tasks WHERE id = ?", (task_id,)
            )
            self._db.commit()
            return cur.rowcount > 0

    # ------------------------------------------------------------- recovery

    def recover_processing(self) -> list[Task]:
        """Tasks that were mid-processing when the daemon died; the engine
        re-queues them on boot (``queue.go:18-31`` rehydration covers queue +
        current)."""
        tasks = self.processing()
        for tsk in tasks:
            tsk.states.append(
                DatedState(state=State.SCHEDULED, created=tsk.state().created)
            )
            self._move(tsk, BUCKET_QUEUE, (BUCKET_CURRENT,))
        return tasks
