"""Pack admission: group queued compatible sim runs into one device
program (PERF.md "Serving: buckets + packing"; the device half is
``sim/pack.py``).

A worker that pops a pack-opted task (``--run-cfg pack=true``) asks the
queue for other QUEUED tasks with the same **pack signature** — the
host-side compatibility key over everything that shapes the compiled
program or the deterministic loop:

- plan, case, group structure + parameters;
- the padded bucket layout when shape bucketing is on (members may then
  differ in EXACT instance count within a bucket — seeds and live
  counts are runtime inputs), or the exact counts when it is off;
- the program gates: transport, telemetry, validate, chunk, tick_ms,
  max_ticks, disable_metrics;
- and the structural exclusions: no faults, no flight recorder, no
  additional hosts, no cohort, no checkpoint/resume, no profiles —
  compositions carrying those run solo.

Claiming respects queue priority: candidates are taken in heap order
(priority desc, FIFO), so a high-priority tenant is packed first, never
skipped — the per-tenant ordering PR 6's SLO rules feed.

Import-light on purpose (stdlib + the composition model): the worker
thread decides admission without touching jax.
"""

from __future__ import annotations

import hashlib
import json

from testground_tpu.logging_ import S

__all__ = [
    "claim_pack",
    "pack_signature",
    "pack_solo_reason",
    "solo_reason_for_composition",
]


def _cfg_get(run_config: dict, key: str, default=None):
    v = (run_config or {}).get(key, default)
    return default if v is None else v


def _truthy(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


# tenant-facing (journal sim.pack.solo_reason + the checker's pack.solo
# finding); the global-run and per-group chaos/trace exclusions share
# one wording
_CHAOS_TRACE_SOLO = (
    "a declared chaos schedule or flight-recorder table bakes "
    "per-program tensors a shared vmapped program cannot carry"
)


def pack_signature(tsk, env=None) -> str | None:
    """The compatibility key of a queued task, or None when the task
    must run solo. Works on the raw task record (composition dict +
    coalesced-ish run config) — no plan loading, no jax.

    The runner-level ``.env.toml`` layer is coalesced in by the caller
    passing ``env`` so two tasks differing only in where a knob was
    set (composition vs daemon config) still pack together.
    """
    from testground_tpu.engine.task import TaskType

    if tsk.type != TaskType.RUN or tsk.runner != "sim:jax":
        return None
    sig, _ = _signature_or_reason(
        tsk.composition or {}, env, tsk.input or {}
    )
    return sig


def pack_solo_reason(tsk, env=None) -> str | None:
    """Why a pack-OPTED task runs solo, or None (pack not requested, or
    the task is packable — a packable task that still ran solo simply
    found no queued partner at claim time; the caller words that case).
    The journal's ``sim.pack.solo_reason`` and the checker's
    ``pack.solo`` finding both read this classification."""
    from testground_tpu.engine.task import TaskType

    if tsk.type != TaskType.RUN or tsk.runner != "sim:jax":
        return None
    return solo_reason_for_composition(
        tsk.composition or {}, env, tsk.input or {}
    )


def solo_reason_for_composition(
    comp: dict, env=None, input_rec: dict | None = None
) -> str | None:
    """Composition-dict variant of :func:`pack_solo_reason` (the static
    checker has a composition, not a task). Returns the human-readable
    solo cause when ``pack=true`` was requested but admission would
    refuse a signature; None when pack was not requested or the
    composition is packable."""
    sig, reason = _signature_or_reason(comp or {}, env, input_rec or {})
    if sig is not None:
        return None
    return reason


def _signature_or_reason(
    comp: dict, env, input_rec: dict
) -> tuple[str | None, str | None]:
    """The ONE admission walk: returns ``(signature, None)`` for a
    packable composition, ``(None, reason)`` when pack was requested
    but the composition must run solo, and ``(None, None)`` when pack
    was not requested at all."""
    runs = comp.get("runs") or []
    glob = comp.get("global") or {}
    grun = glob.get("run") or {}
    cfgs = [dict(env or {}), dict(glob.get("run_config") or {})]
    cfg: dict = {}
    for layer in cfgs:
        cfg.update(layer)
    requested = _truthy(cfg.get("pack"))

    def solo(reason: str):
        return None, (reason if requested else None)

    if len(runs) != 1:
        # multi-[[runs]] compositions keep their own loop
        return solo(
            f"multi-[[runs]] composition ({len(runs)} runs — each "
            "[[runs]] entry keeps its own run loop)"
        )
    run = runs[0]
    # structural exclusions: program-shaping declarations that cannot
    # share a vmapped program (or whose host planes are per-run device
    # reads the pack cannot demux). Queued compositions are
    # PRE-preparation, so backing-group [groups.run] tables — which
    # merge_group only folds into the run groups at prepare time — must
    # be checked here too, or a group-level chaos/trace declaration
    # would slip past admission and silently never be injected.
    if grun.get("faults") or grun.get("trace"):
        return solo(_CHAOS_TRACE_SOLO)
    groups_decl = {g.get("id"): g for g in comp.get("groups") or []}
    backing_runs = {}
    for rg in run.get("groups") or []:
        decl = groups_decl.get(rg.get("group_id") or rg.get("id")) or {}
        brun = decl.get("run") or {}
        if (
            rg.get("faults")
            or rg.get("trace")
            or brun.get("faults")
            or brun.get("trace")
        ):
            return solo(_CHAOS_TRACE_SOLO)
        backing_runs[rg.get("id")] = brun
    if not requested:
        return None, None
    if cfg.get("coordinator_address"):
        return solo("a multi-host cohort config cannot join a pack")
    if cfg.get("resume_from"):
        return solo("resume_from seeds this run's own carry snapshot")
    if _truthy(cfg.get("profile")):
        return solo("profiler capture is a per-run device session")
    if _truthy(cfg.get("phases")):
        return solo("phase attribution lowers per-run programs")
    if _truthy(cfg.get("netmatrix")):
        return solo("the traffic matrix is a per-run device carry read")
    if cfg.get("additional_hosts"):
        return solo("additional_hosts adds per-program echo lanes")
    if int(cfg.get("checkpoint_chunks") or 0) > 0:
        return solo("checkpointing reads this run's own carry per chunk")

    # instance counts: the padded bucket layout when bucketing is on
    # (the shared-program identity), exact counts otherwise. Queued
    # compositions are pre-preparation, so resolve the explicit count
    # (run group, else backing group); percentage-based groups resolve
    # only at prepare time — those run solo.
    counts = []
    for rg in run.get("groups") or []:
        inst = rg.get("instances") or {}
        c = inst.get("count") if isinstance(inst, dict) else inst
        if not c:
            decl = groups_decl.get(
                rg.get("group_id") or rg.get("id"), {}
            )
            dinst = decl.get("instances") or {}
            c = (
                dinst.get("count")
                if isinstance(dinst, dict)
                else dinst
            )
        if not c:
            return solo(
                "percentage-based group instances resolve only at "
                "prepare time"
            )
        counts.append(int(c))
    from testground_tpu.sim.buckets import (
        bucketed_counts,
        parse_bucket_mode,
        parse_ladder,
    )

    try:
        mode = parse_bucket_mode(cfg.get("bucket"))
        ladder = parse_ladder(cfg.get("bucket_ladder") or None)
    except ValueError:
        # a bad knob fails in the executor, readably
        return solo("invalid bucket/bucket_ladder knob")
    padded = (
        bucketed_counts(counts, mode, ladder)
        if mode != "off"
        else None
    )
    sig = {
        "plan": glob.get("plan"),
        "case": glob.get("case"),
        # plan identity: two tasks queued around a plan edit (different
        # manifest or sources snapshot) must not share a program
        "manifest": hashlib.sha256(
            json.dumps(
                (input_rec or {}).get("manifest") or {}, sort_keys=True
            ).encode()
        ).hexdigest()[:16],
        "sources_dir": (input_rec or {}).get("sources_dir") or "",
        "groups": [
            {
                "id": rg.get("id"),
                # the EFFECTIVE parameter view: prepare_for_run fills
                # missing run-group params from the backing group's
                # [groups.run] and the global [global.run] tables, so
                # all three layers key the signature — two tasks whose
                # merged params differ must never share a program
                "params": dict(rg.get("test_params") or {}),
                "backing_params": dict(
                    (backing_runs.get(rg.get("id")) or {}).get(
                        "test_params"
                    )
                    or {}
                ),
            }
            for rg in run.get("groups") or []
        ],
        "global_params": dict(grun.get("test_params") or {}),
        "counts": list(padded) if padded is not None else counts,
        "bucketed": padded is not None,
        "disable_metrics": bool(glob.get("disable_metrics")),
        # program gates — defaults mirror SimJaxConfig
        "tick_ms": float(cfg.get("tick_ms") or 1.0),
        "chunk": int(cfg.get("chunk") or 128),
        "max_ticks": int(cfg.get("max_ticks") or 100_000),
        "transport": str(cfg.get("transport") or "xla").lower(),
        "telemetry": _truthy(cfg.get("telemetry")),
        "validate": _truthy(cfg.get("validate")),
        "pack_max": int(cfg.get("pack_max") or 8),
        # the mesh layout shapes the packed program (the stacked carry
        # shards over it — sim/meshplan.py), so meshed and unmeshed
        # members never share a pack
        "mesh": str(cfg.get("mesh") or ""),
    }
    return (
        hashlib.sha256(
            json.dumps(sig, sort_keys=True).encode()
        ).hexdigest()[:32],
        None,
    )


def claim_pack(engine, tsk) -> list:
    """Given a just-popped task, claim every queued compatible task (in
    priority order) up to ``pack_max`` and return the pack — ``[tsk]``
    alone when packing does not apply. Claimed tasks are marked
    processing exactly like a pop; the caller owns their lifecycle."""
    env_layer = engine.env.runners.get("sim:jax") or {}
    try:
        sig = pack_signature(tsk, env_layer)
    except Exception as e:  # noqa: BLE001 — admission must never wedge
        S().warning("pack admission failed for %s: %s", tsk.id, e)
        return [tsk]
    if sig is None:
        return [tsk]
    cfg = dict(env_layer)
    cfg.update((tsk.composition.get("global") or {}).get("run_config") or {})
    pack_max = max(2, int(cfg.get("pack_max") or 8))

    def match(other) -> bool:
        try:
            return pack_signature(other, env_layer) == sig
        except Exception:  # noqa: BLE001
            return False

    extras = engine.queue.claim_matching(match, pack_max - 1)
    if extras:
        S().info(
            "packed %d queued run(s) onto task %s (signature %s)",
            len(extras),
            tsk.id,
            sig[:8],
        )
    return [tsk] + extras
