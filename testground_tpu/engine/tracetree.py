"""Archive-time assembly of a task's lifecycle span tree.

The control plane stamps ids as the task moves (``Task.trace``: trace_id
plus root/queued/claim/execute span ids) and the executor's
``SpanTracer`` writes run-phase spans with the same vocabulary
(``run_spans.jsonl`` rows carry trace_id/span_id/parent_id/wall_ns).
Nobody holds the whole tree in memory — this module derives it once,
when the task archives, from the state timestamps + those files:

- ``task_spans.jsonl`` — one JSON record per span:
  ``{"name", "trace_id", "span_id", "parent_id", "start_ns",
  "end_ns", "kind": "lifecycle" | "run" | "point", ...attrs}``.
  Every parent_id resolves to another record's span_id (or "" for the
  root ``submit`` span) — the connectivity contract tests pin.
- ``task_trace.json`` — the same tree as Chrome/Perfetto trace-event
  JSON ("X" complete events, µs timestamps), so ``chrome://tracing``
  or ui.perfetto.dev opens a task's submit→archive timeline directly.

Both land in the task's run output dir and are served by
``GET /artifact`` (daemon/server.py whitelists them). Export is
best-effort: a failure here must never fail the task it describes.
"""

from __future__ import annotations

import glob
import json
import os

from testground_tpu.sim.telemetry import SPAN_FILE, iter_jsonl

from .task import State, Task

__all__ = [
    "TASK_SPANS_FILE",
    "TASK_TRACE_FILE",
    "export_task_trace",
    "load_task_spans",
    "lifecycle_spans",
]

TASK_SPANS_FILE = "task_spans.jsonl"
TASK_TRACE_FILE = "task_trace.json"

_NS = 1_000_000_000


def lifecycle_spans(tsk: Task) -> list[dict]:
    """The control-plane half of the tree, derived from ``Task.trace``
    ids and the persisted state timestamps. Returns [] when the task
    has no trace ids (pre-upgrade rows) — the export then skips."""
    tr = tsk.trace or {}
    trace_id = tr.get("trace_id", "")
    root = tr.get("root_span_id", "")
    if not trace_id or not root or not tsk.states:
        return []
    t0 = int(tsk.states[0].created * _NS)
    t_final = int(tsk.states[-1].created * _NS)
    # PROCESSING episodes: a preempted task re-queues (SCHEDULED) and is
    # claimed again, so one task can hold several [claim..requeue) spans
    episodes: list[tuple[int, int]] = []
    ep_start = None
    for ds in tsk.states[1:]:
        ts = int(ds.created * _NS)
        if ds.state == State.PROCESSING:
            if ep_start is not None:
                episodes.append((ep_start, ts))
            ep_start = ts
        elif ds.state == State.SCHEDULED and ep_start is not None:
            episodes.append((ep_start, ts))
            ep_start = None
    if ep_start is not None:
        episodes.append((ep_start, t_final))
    t_proc = episodes[0][0] if episodes else None

    def span(name, sid, parent, start, end, kind="lifecycle", **attrs):
        return {
            "name": name,
            "trace_id": trace_id,
            "span_id": sid,
            "parent_id": parent,
            "start_ns": start,
            "end_ns": end,
            "kind": kind,
            **attrs,
        }

    out = [
        span(
            "submit",
            root,
            "",
            t0,
            t_final,
            task=tsk.id,
            plan=tsk.plan,
            case=tsk.case,
            task_type=tsk.type.value,
            state=tsk.states[-1].state.value,
            outcome=tsk.outcome().value,
        )
    ]
    queued = tr.get("queued_span_id", "")
    if queued:
        out.append(
            span("queued", queued, root, t0, t_proc or t_final,
                 priority=tsk.priority)
        )
    claim = tr.get("claim_span_id", "")
    if claim and t_proc is not None:
        attrs = {}
        if tr.get("pack_leader"):
            attrs["pack_leader"] = tr["pack_leader"]
            attrs["pack_width"] = tr.get("pack_width", 0)
        if tr.get("solo_reason"):
            attrs["solo_reason"] = tr["solo_reason"]
        # one claim/execute pair per attempt — earlier (preempted)
        # attempts kept their span ids in trace["prior_attempts"] so
        # the executor spans they parented still join the tree
        attempts = list(tr.get("prior_attempts") or [])
        attempts.append(
            {"claim": claim, "execute": tr.get("execute_span_id", "")}
        )
        eps = episodes[-len(attempts):]
        while len(eps) < len(attempts):
            eps.insert(0, (t_proc, t_final))
        for i, (att, (ep_s, ep_e)) in enumerate(zip(attempts, eps)):
            last = i == len(attempts) - 1
            a = dict(attrs) if last else {"preempted": True}
            if len(attempts) > 1:
                a["attempt"] = i + 1
            out.append(
                span(
                    "claim", att.get("claim", ""), queued or root,
                    ep_s, ep_e, **a,
                )
            )
            if att.get("execute"):
                out.append(
                    span(
                        "execute", att["execute"], att.get("claim", ""),
                        ep_s, ep_e,
                    )
                )
    out.append(
        span(
            "archive",
            tr.get("archive_span_id") or root + "-archive",
            root,
            t_final,
            t_final,
            kind="point",
        )
    )
    return out


def _run_span_rows(run_dir: str) -> list[dict]:
    """Executor spans for this task, read back from run_spans.jsonl in
    the task's run dir plus any multi-[[runs]] sibling dirs
    (``<task>-<run>``). start/end rows pair by span_id; an unmatched
    start (crashed run) closes at its own timestamp; points become
    zero-length spans."""
    paths = [os.path.join(run_dir, SPAN_FILE)]
    paths += sorted(
        glob.glob(os.path.join(run_dir + "-*", SPAN_FILE))
    )
    open_spans: dict[str, dict] = {}
    out: list[dict] = []
    for path in paths:
        for line in iter_jsonl(path):
            ev = line.get("event")
            if not isinstance(ev, dict):
                continue
            sid = ev.get("span_id", "")
            wall = int(ev.get("wall_ns") or line.get("ts") or 0)
            typ = ev.get("type")
            attrs = {
                k: v
                for k, v in ev.items()
                if k
                not in (
                    "type",
                    "span",
                    "trace_id",
                    "span_id",
                    "parent_id",
                    "wall_ns",
                )
            }
            if typ == "span_start" and sid:
                open_spans[sid] = {
                    "name": ev.get("span", ""),
                    "trace_id": ev.get("trace_id", ""),
                    "span_id": sid,
                    "parent_id": ev.get("parent_id", ""),
                    "start_ns": wall,
                    "end_ns": wall,
                    "kind": "run",
                    **attrs,
                }
            elif typ == "span_end":
                rec = open_spans.pop(sid, None) if sid else None
                if rec is None:
                    # ends without a matched start (legacy rows with no
                    # span_id): skip rather than invent a node
                    continue
                rec["end_ns"] = wall
                rec.update(attrs)
                out.append(rec)
            elif typ == "point" and sid:
                out.append(
                    {
                        "name": ev.get("span", ""),
                        "trace_id": ev.get("trace_id", ""),
                        "span_id": sid,
                        "parent_id": ev.get("parent_id", ""),
                        "start_ns": wall,
                        "end_ns": wall,
                        "kind": "point",
                        **attrs,
                    }
                )
    # crashed runs leave spans open — close them at their start so the
    # tree stays connected and Perfetto still renders them
    out.extend(open_spans.values())
    return out


def _perfetto_events(spans: list[dict]) -> list[dict]:
    events = []
    for s in spans:
        ts_us = s["start_ns"] / 1000.0
        dur_us = max(0.0, (s["end_ns"] - s["start_ns"]) / 1000.0)
        args = {
            k: v
            for k, v in s.items()
            if k not in ("name", "start_ns", "end_ns", "kind")
        }
        if s["kind"] == "point":
            events.append(
                {
                    "name": s["name"],
                    "cat": s["kind"],
                    "ph": "i",
                    "s": "t",
                    "ts": ts_us,
                    "pid": 1,
                    "tid": 1 if s["kind"] == "lifecycle" else 2,
                    "args": args,
                }
            )
        else:
            events.append(
                {
                    "name": s["name"],
                    "cat": s["kind"],
                    "ph": "X",
                    "ts": ts_us,
                    "dur": dur_us,
                    "pid": 1,
                    "tid": 1 if s["kind"] == "lifecycle" else 2,
                    "args": args,
                }
            )
    return events


def export_task_trace(outputs_root: str, tsk: Task) -> str | None:
    """Write ``task_spans.jsonl`` + ``task_trace.json`` for an archived
    task into its run output dir. Returns the spans path, or None when
    the task carries no trace ids or the write fails (best-effort — the
    archive itself already succeeded)."""
    try:
        life = lifecycle_spans(tsk)
        if not life:
            return None
        run_dir = os.path.join(outputs_root, tsk.plan, tsk.id)
        os.makedirs(run_dir, exist_ok=True)
        spans = life + _run_span_rows(run_dir)
        spans.sort(key=lambda s: (s["start_ns"], s["span_id"]))
        spans_path = os.path.join(run_dir, TASK_SPANS_FILE)
        with open(spans_path, "w", encoding="utf-8") as f:
            for s in spans:
                f.write(json.dumps(s, default=str) + "\n")
        trace = {
            "displayTimeUnit": "ms",
            "traceEvents": _perfetto_events(spans),
        }
        with open(
            os.path.join(run_dir, TASK_TRACE_FILE), "w", encoding="utf-8"
        ) as f:
            json.dump(trace, f)
        return spans_path
    except (OSError, ValueError, TypeError, KeyError):
        return None


def load_task_spans(path: str) -> list[dict]:
    """Read a ``task_spans.jsonl`` back (tolerant, like every other
    observability reader)."""
    return [r for r in iter_jsonl(path) if isinstance(r, dict)]
