"""Daemon event journal: the control plane's append-only audit log.

Every task state transition, claim, pack admission, SLO cancel,
operator cancel, checkpoint and sync eviction lands here as one JSON
line in ``daemon_events.jsonl`` (under the daemon state dir, next to
``tasks.db``). Records carry both clocks — wall ns for cross-host
correlation, monotonic ns for intra-daemon ordering that survives NTP
slew — plus the task's trace ids so the journal joins the lifecycle
span tree.

This is the audit stream a future fleet controller (ROADMAP item 2)
consumes to answer "why did the daemon do that": admission decisions,
preemptions and migrations become replayable from the journal alone.
Served live by ``GET /events?since=<byte offset>`` (daemon/server.py),
which reuses the byte-offset tail machinery from ``engine/stream.py``.

Bounded by size-based rotation: when the journal exceeds ``max_bytes``
it is renamed to ``daemon_events.jsonl.1`` (replacing any previous
rotation) and a fresh file begins — the journal is an operational
tail, not an unbounded archive. Emission never raises: observability
must not fail the daemon it observes.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["EVENTS_FILE", "EventJournal"]

EVENTS_FILE = "daemon_events.jsonl"

# Rotation threshold. 4 MiB of ~250-byte records is ~16k events — hours
# of busy-daemon history, small enough to tail over HTTP in one read.
_MAX_BYTES_DEFAULT = 4 << 20


class EventJournal:
    """Thread-safe append-only jsonl journal with single-slot rotation.

    Record shape (every record, extra keys per event type):

    ``{"seq": n, "ts_wall_ns": ..., "ts_mono_ns": ..., "type": "...",
    "task": "<task id>", "trace_id": "...", "span_id": "...", ...}``

    ``seq`` increases monotonically for the journal's lifetime (it does
    NOT reset on rotation), so consumers detect gaps after a rotation
    they slept through.
    """

    def __init__(self, path: str, max_bytes: int = _MAX_BYTES_DEFAULT):
        self.path = path
        self.max_bytes = max(1, int(max_bytes))
        self._lock = threading.Lock()
        self._seq = 0
        self._size = 0
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._size = os.path.getsize(path)
        except OSError:
            self._size = 0
        # resume seq from the existing journal so a daemon restart
        # keeps the file monotonic (consumers detect gaps, not resets)
        if self._size:
            try:
                with open(path, "rb") as f:
                    f.seek(max(0, self._size - 8192))
                    tail = f.read().decode("utf-8", "replace")
                for line in reversed(tail.splitlines()):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        self._seq = int(json.loads(line).get("seq", 0))
                        break
                    except (ValueError, TypeError):
                        continue
            except OSError:
                pass

    def emit(
        self,
        type_: str,
        task: str = "",
        trace: dict | None = None,
        **attrs,
    ) -> None:
        """Append one event. ``trace`` is a Task.trace-shaped dict; its
        trace_id and the most specific span id minted so far are copied
        onto the record. Never raises."""
        trace = trace or {}
        rec = {
            "seq": 0,  # patched under the lock
            "ts_wall_ns": time.time_ns(),
            "ts_mono_ns": time.monotonic_ns(),
            "type": type_,
            "task": task,
            "trace_id": trace.get("trace_id", ""),
            "span_id": (
                trace.get("claim_span_id")
                or trace.get("queued_span_id")
                or trace.get("root_span_id", "")
            ),
        }
        rec.update(attrs)
        try:
            with self._lock:
                self._seq += 1
                rec["seq"] = self._seq
                line = json.dumps(rec, default=str) + "\n"
                if self._size + len(line) > self.max_bytes:
                    self._rotate_locked()
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
                self._size += len(line)
        except (OSError, ValueError, TypeError):
            pass

    def _rotate_locked(self) -> None:
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass
        self._size = 0
