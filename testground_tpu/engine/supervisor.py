"""The worker loop: pops tasks off the queue and executes builds and runs.

Twin of the reference's ``pkg/engine/supervisor.go``: state transitions are
persisted at each step, builds are deduplicated by ``Group.build_key()``,
config coalesces with precedence composition > .env.toml > manifest, runs are
dispatched to the runner, and the result is archived.
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
import traceback

from testground_tpu.api import (
    Composition,
    RunGroup,
    RunInput,
    TestPlanManifest,
    prepare_for_build,
    prepare_for_run,
    validate_for_build,
    validate_for_run,
    BuildInput,
)
from testground_tpu.config import CoalescedConfig
from testground_tpu.logging_ import S
from testground_tpu.rpc import OutputWriter

# import-light on purpose (numpy + stdlib — sim/slo.py's contract): the
# typed SLO failure must be catchable here without loading jax
from testground_tpu.sim.slo import SloBreachError
from testground_tpu.tracectx import new_span_id, new_trace_id

from .controller import TaskPreemptedError
from .engine import Engine
from .notify import notify_task_finished, notify_task_started
from .pack import _truthy
from .queue import QueueEmptyError
from .task import DatedState, Outcome, State, Task, TaskType
from .tracetree import export_task_trace

__all__ = ["worker", "do_build", "do_run"]

DEFAULT_TASK_TIMEOUT_SECS = 10 * 60  # supervisor.go:49-52


def worker(engine: Engine, idx: int) -> None:
    """One worker loop (``supervisor.go:47-190``). A popped task that
    opted into run packing (``--run-cfg pack=true``) additionally
    claims every queued compatible run — the whole pack then executes
    as ONE vmapped device program (engine/pack.py, sim/pack.py)."""
    from .pack import claim_pack

    S().debug("supervisor worker %d started", idx)
    while not engine._stop.is_set():
        # graceful drain (docs/FLEET.md): a draining daemon stops
        # claiming — requeued/queued tasks stay durably parked for the
        # restarted daemon to rehydrate
        if engine._draining.is_set():
            engine._queue_kick.wait(timeout=0.2)
            engine._queue_kick.clear()
            continue
        try:
            tsk = engine.queue.pop()
        except QueueEmptyError:
            engine._queue_kick.wait(timeout=0.2)
            engine._queue_kick.clear()
            continue
        pack = claim_pack(engine, tsk)
        # close the kill()/preempt() race before any claim bookkeeping:
        # the tasks are already stamped PROCESSING (queue.pop), so an
        # operator cancel OR a preemption arriving now must find a
        # registered event, not fall between cancel_queued and
        # process_task's registration
        for member in pack:
            engine.register_cancel(member.id)
            engine.register_preempt(member.id)
        _note_claim(engine, idx, pack)
        engine.fleet_worker_state(idx, tsk.id)
        try:
            if len(pack) > 1:
                process_task_pack(engine, pack)
            else:
                process_task(engine, tsk)
        finally:
            engine.fleet_worker_state(idx, "")
            if len(pack) > 1:
                engine.fleet_pack_done(tsk.id)


def _note_claim(engine: Engine, idx: int, pack: list[Task]) -> None:
    """Claim bookkeeping for a freshly-popped task (or pack): mint the
    claim and execute span ids — the pack-claim span is minted ONCE and
    shared by every member, so each member's tree hangs off the same
    span — feed the fleet claim histograms, and journal the claims.
    Tasks pushed straight into the queue (tests, future federation)
    get trace ids filled in here so every archive still exports a
    connected tree."""
    now = time.time()
    claim_sid = new_span_id()
    leader = pack[0]
    for tsk in pack:
        tr = tsk.trace
        tr.setdefault("trace_id", new_trace_id())
        tr.setdefault("root_span_id", new_span_id())
        tr.setdefault("queued_span_id", new_span_id())
        if tr.get("claim_span_id") and tr.get("execute_span_id"):
            # a re-claim (preemption requeue or restart rehydration):
            # keep the prior attempt's ids so the executor spans it
            # parented still resolve in the archived tree (bounded —
            # a chaos soak must not grow the trace without limit)
            prior = tr.setdefault("prior_attempts", [])
            prior.append(
                {
                    "claim": tr["claim_span_id"],
                    "execute": tr["execute_span_id"],
                }
            )
            del prior[:-16]
        tr["claim_span_id"] = claim_sid
        tr["execute_span_id"] = new_span_id()
        if len(pack) > 1:
            tr["pack_leader"] = leader.id
            tr["pack_width"] = len(pack)
        queue_wait = (
            max(0.0, tsk.states[-1].created - tsk.states[0].created)
            if len(tsk.states) >= 2
            else 0.0
        )
        claim_latency = (
            max(0.0, now - tsk.states[-1].created) if tsk.states else 0.0
        )
        engine.fleet_note_claim(queue_wait, claim_latency)
        engine.events.emit(
            "task.claimed",
            task=tsk.id,
            trace=tr,
            state=State.PROCESSING.value,
            worker=idx,
            queue_wait_secs=round(queue_wait, 6),
            pack_width=len(pack),
        )
    if len(pack) > 1:
        engine.fleet_note_pack(leader.id, len(pack))
        engine.events.emit(
            "pack.admitted",
            task=leader.id,
            trace=leader.trace,
            width=len(pack),
            members=[t.id for t in pack],
        )


def _run_trace_ctx(tsk: Task) -> dict:
    """The RunInput.trace_ctx the executor and sync client carry: the
    task's trace with the execute span as parent, plus the ready-made
    traceparent wire form."""
    tr = tsk.trace or {}
    trace_id = tr.get("trace_id", "")
    if not trace_id:
        return {}
    parent = (
        tr.get("execute_span_id")
        or tr.get("claim_span_id")
        or tr.get("root_span_id", "")
    )
    return {
        "trace_id": trace_id,
        "parent_id": parent,
        "task_id": tsk.id,
        "traceparent": f"00-{trace_id}-{parent}-01",
    }


def _post_run_events(engine: Engine, tsk: Task) -> None:
    """Journal the run-derived control-plane events an archived result
    reveals: checkpoint/resume activity and sync-service evictions.
    Best-effort — a malformed result journal must not fail the task."""
    try:
        result = tsk.result if isinstance(tsk.result, dict) else {}
        journal = result.get("journal")
        if not isinstance(journal, dict):
            return
        sim = journal.get("sim")
        if isinstance(sim, dict) and isinstance(sim.get("checkpoint"), dict):
            ck = sim["checkpoint"]
            if ck.get("count"):
                engine.events.emit(
                    "task.checkpoint",
                    task=tsk.id,
                    trace=tsk.trace,
                    count=int(ck.get("count", 0)),
                    last_tick=int(ck.get("last_tick", 0) or 0),
                )
            if ck.get("resumed"):
                engine.events.emit(
                    "task.resumed",
                    task=tsk.id,
                    trace=tsk.trace,
                    resumed=ck["resumed"],
                )
                fb = (
                    ck["resumed"].get("fallback")
                    if isinstance(ck["resumed"], dict)
                    else None
                )
                if isinstance(fb, dict):
                    engine.events.emit(
                        "task.resume_fallback",
                        task=tsk.id,
                        trace=tsk.trace,
                        skipped=list(fb.get("skipped", [])),
                        error=str(fb.get("error", ""))[:200],
                    )
        sync = journal.get("sync")
        if isinstance(sync, dict) and sync.get("evicted"):
            engine.events.emit(
                "task.sync_evicted",
                task=tsk.id,
                trace=tsk.trace,
                count=int(sync["evicted"]),
            )
    except (TypeError, ValueError):
        pass


def _finish_task(engine: Engine, tsk: Task) -> None:
    """Shared archive-time tail for solo and packed paths: journal the
    terminal transition plus run-derived events, then export the task's
    span tree (task_spans.jsonl + task_trace.json)."""
    _post_run_events(engine, tsk)
    engine.events.emit(
        "task.finished",
        task=tsk.id,
        trace=tsk.trace,
        state=tsk.states[-1].state.value,
        outcome=tsk.outcome().value,
        error=tsk.error[:200] if tsk.error else "",
    )
    export_task_trace(engine.env.dirs.outputs(), tsk)


def _requeue_preempted(
    engine: Engine, tsk: Task, e: TaskPreemptedError
) -> None:
    """Live migration's requeue half (docs/FLEET.md): the executor
    stopped the run at a chunk boundary and raised; put the task back on
    the queue pointing at its own newest snapshot so the next claim
    resumes instead of restarting. NO terminal state, NO archive, NO
    webhook — the task never finished. When the preemption is not
    resumable (no snapshots: ckpt_every=0, or a pack member — packed
    lanes freeze on-device, never on disk) the composition is left
    untouched and the rerun starts from scratch; determinism still
    yields the bit-equal result."""
    if e.resumable:
        glob = tsk.composition.setdefault("global", {})
        rc = glob.setdefault("run_config", {})
        # own-snapshot preference (sim/executor.py): even if this run
        # itself resumed from another task, its own snapshots are newer
        rc["resume_from"] = tsk.id
    tsk.trace["preemptions"] = int(tsk.trace.get("preemptions", 0) or 0) + 1
    tsk.error = ""
    tsk.result = None
    tsk.states.append(DatedState(state=State.SCHEDULED, created=time.time()))
    engine.queue.requeue(tsk)
    engine.fleet_note_preemption()
    engine.events.emit(
        "task.preempted",
        task=tsk.id,
        trace=tsk.trace,
        tick=e.tick,
        snapshot_tick=e.snapshot_tick,
        snapshots=e.snapshots,
        resumable=e.resumable,
        preemptions=int(tsk.trace["preemptions"]),
    )
    engine.events.emit(
        "task.migrated",
        task=tsk.id,
        trace=tsk.trace,
        resume_from=tsk.id if e.resumable else "",
        from_tick=e.snapshot_tick if e.resumable else 0,
    )
    engine._queue_kick.set()
    S().info(
        "task %s preempted at tick %d (%s) — requeued",
        tsk.id,
        e.tick,
        f"resume from tick {e.snapshot_tick}" if e.resumable else "rerun",
    )


def process_task(engine: Engine, tsk: Task) -> None:
    """Execute one task end-to-end, with timeout and cancellation."""
    timeout = engine.env.daemon.scheduler.task_timeout_min * 60 or (
        DEFAULT_TASK_TIMEOUT_SECS
    )
    cancel = engine.register_cancel(tsk.id)
    timer = threading.Timer(timeout, cancel.set)
    timer.daemon = True
    timer.start()

    log_path = engine.task_log_path(tsk.id)
    preempted: TaskPreemptedError | None = None
    try:
        with open(log_path, "w") as log_file:
            ow = OutputWriter(sink=log_file)
            try:
                engine.storage.update_current(tsk)
                # pending commit status for CI tasks (supervisor.go:213-215)
                notify_task_started(engine.env, tsk)
                engine.events.emit(
                    "task.started",
                    task=tsk.id,
                    trace=tsk.trace,
                    state=State.PROCESSING.value,
                    task_type=tsk.type.value,
                )
                if tsk.type == TaskType.RUN:
                    result = do_run(engine, tsk, ow, cancel)
                elif tsk.type == TaskType.BUILD:
                    result = do_build_task(engine, tsk, ow, cancel)
                else:
                    raise ValueError(f"unsupported task type {tsk.type}")
                tsk.result = result
            except TaskPreemptedError as e:
                # not a failure: the fleet controller stopped the run at
                # a chunk boundary — the finally branch requeues it
                preempted = e
                ow.infof("%s", e)
            except Exception as e:  # noqa: BLE001 — task errors become results
                S().error("task %s failed: %s", tsk.id, e)
                ow.write_error(str(e))
                tsk.error = str(e)
                tsk.result = {
                    "outcome": (
                        Outcome.CANCELED.value
                        if cancel.is_set()
                        else Outcome.FAILURE.value
                    )
                }
                S().debug("%s", traceback.format_exc())
            else:
                ow.write_result(tsk.result)
    finally:
        timer.cancel()
        engine.drop_cancel(tsk.id)
        engine.drop_preempt(tsk.id)
        if preempted is not None:
            _requeue_preempted(engine, tsk, preempted)
        else:
            final = (
                State.CANCELED
                if cancel.is_set() and tsk.error
                else State.COMPLETE
            )
            tsk.states.append(DatedState(state=final, created=time.time()))
            # journal + span-tree export BEFORE the archive makes the
            # terminal state visible: a client polling for COMPLETE must
            # find task_spans.jsonl already on disk
            _finish_task(engine, tsk)
            engine.storage.archive(tsk)
            # status webhooks: log-and-continue, never affect the task
            # (supervisor.go:176-183)
            notify_task_finished(engine.env, tsk)
            S().info("task %s finished: %s", tsk.id, tsk.outcome().value)


def _prepare_pack_run_input(
    engine: Engine, tsk: Task, ow: OutputWriter, cancel: threading.Event
) -> RunInput:
    """The head of :func:`do_run` for a single-[[runs]] pack member:
    build missing artifacts (BuildKey-deduped, so N members of one pack
    build once), prepare + validate, coalesce the runner config, and
    assemble the RunInput. Raises on any refusal — the member then
    fails alone and the pack continues without it."""
    comp = Composition.from_dict(tsk.composition)
    manifest = TestPlanManifest.from_dict(tsk.input["manifest"])
    sources_dir = tsk.input.get("sources_dir", "")
    runner_id = comp.global_.runner
    if engine.env.runner_is_disabled(runner_id):
        raise ValueError(f"runner {runner_id} is disabled in .env.toml")
    if any(not g.run.artifact for g in comp.groups):
        comp = do_build(engine, comp, manifest, sources_dir, tsk.id, ow, cancel)
        tsk.composition = comp.to_dict()
        engine.storage.update_current(tsk)
    comp = prepare_for_run(comp, manifest)
    validate_for_run(comp)
    coalesced = (
        CoalescedConfig()
        .append(engine.env.runners.get(runner_id))
        .append(comp.global_.run_config)
    )
    runner = engine.runner_by_name(runner_id)
    cfg_type = runner.config_type()
    runner_cfg = (
        coalesced.coalesce_into(cfg_type)
        if cfg_type is not None
        else coalesced.flatten()
    )
    run = comp.runs[0]
    artifacts = {g.id: g.run.artifact for g in comp.groups}
    groups = []
    for rg in run.groups:
        backing = comp.get_group(rg.effective_group_id())
        groups.append(
            RunGroup(
                id=rg.id,
                instances=rg.calculated_instance_count,
                artifact_path=artifacts[backing.id],
                builder=backing.builder or comp.global_.builder,
                parameters=dict(rg.test_params),
                profiles=dict(rg.profiles),
                resources=rg.resources,
                slo=[dict(s) for s in getattr(rg, "slo", [])],
            )
        )
    return RunInput(
        run_id=tsk.id,
        test_plan=comp.global_.plan,
        test_case=comp.global_.case,
        total_instances=run.total_instances,
        groups=groups,
        runner_config=runner_cfg,
        disable_metrics=comp.global_.disable_metrics,
        slo=[
            dict(s)
            for s in (
                comp.global_.run.slo
                if comp.global_.run is not None
                else []
            )
        ],
        trace_ctx=_run_trace_ctx(tsk),
        env=engine.env,
        # eviction of a pack member stops its lanes at the next chunk
        # boundary via the same in-program freeze cancellation uses;
        # the requeued member reruns from scratch (no disk snapshots
        # inside a pack — engine/pack.py excludes checkpointing)
        preempt=engine.register_preempt(tsk.id),
    )


def process_task_pack(engine: Engine, tasks: list[Task]) -> None:
    """Execute a claimed pack end-to-end: each task keeps its own log
    file, cancel channel, timeout timer, result, and archive record —
    only the device program is shared (one vmapped dispatch per chunk,
    ``sim/pack.py``). A member whose preparation or collection fails
    fails ALONE; if the pack shrinks below two members the survivors
    run the ordinary solo path."""
    timeout = engine.env.daemon.scheduler.task_timeout_min * 60 or (
        DEFAULT_TASK_TIMEOUT_SECS
    )
    ctxs = []
    for tsk in tasks:
        cancel = engine.register_cancel(tsk.id)
        timer = threading.Timer(timeout, cancel.set)
        timer.daemon = True
        timer.start()
        log_file = open(engine.task_log_path(tsk.id), "w")
        ctxs.append(
            {
                "tsk": tsk,
                "cancel": cancel,
                "timer": timer,
                "log": log_file,
                "ow": OutputWriter(sink=log_file),
                "result": None,
                "error": "",
                "preempted": None,
            }
        )
        engine.storage.update_current(tsk)
        notify_task_started(engine.env, tsk)
        engine.events.emit(
            "task.started",
            task=tsk.id,
            trace=tsk.trace,
            state=State.PROCESSING.value,
            task_type=tsk.type.value,
            pack_width=len(tasks),
        )

    try:
        # ---------------------------------------------------- preparation
        ready = []
        for ctx in ctxs:
            try:
                ctx["job"] = _prepare_pack_run_input(
                    engine, ctx["tsk"], ctx["ow"], ctx["cancel"]
                )
                ready.append(ctx)
            except Exception as e:  # noqa: BLE001 — member-local failure
                S().error("pack member %s failed: %s", ctx["tsk"].id, e)
                ctx["ow"].write_error(str(e))
                ctx["error"] = str(e)
                ctx["result"] = {"outcome": Outcome.FAILURE.value}

        if len(ready) >= 2:
            from testground_tpu.sim.executor import (
                execute_packed_sim_runs,
            )
            from testground_tpu.sim.slo import SloBreachError as _Slo

            try:
                outs = execute_packed_sim_runs(
                    [c["job"] for c in ready],
                    [c["ow"] for c in ready],
                    [c["cancel"] for c in ready],
                )
            except Exception as e:  # noqa: BLE001 — whole-pack failure
                S().error("pack execution failed: %s", e)
                S().debug("%s", traceback.format_exc())
                for ctx in ready:
                    ctx["ow"].write_error(str(e))
                    ctx["error"] = str(e)
                    ctx["result"] = {
                        "outcome": (
                            Outcome.CANCELED.value
                            if ctx["cancel"].is_set()
                            else Outcome.FAILURE.value
                        )
                    }
            else:
                for ctx, out in zip(ready, outs):
                    comp_dict = ctx["tsk"].composition
                    if isinstance(out, _Slo):
                        bo = out.run_output
                        rd = (
                            bo.result.to_dict()
                            if bo is not None
                            and hasattr(bo.result, "to_dict")
                            else {"outcome": Outcome.FAILURE.value}
                        )
                        ctx["ow"].write_error(str(out))
                        ctx["error"] = str(out)
                        ctx["result"] = {
                            **rd,
                            "outcome": Outcome.FAILURE.value,
                            "composition": comp_dict,
                        }
                    elif isinstance(out, TaskPreemptedError):
                        # evicted pack member: the finally loop requeues
                        # it instead of archiving (never resumable — no
                        # disk snapshots inside a pack)
                        ctx["preempted"] = out
                        ctx["ow"].infof("%s", out)
                    elif isinstance(out, Exception):
                        ctx["ow"].write_error(str(out))
                        ctx["error"] = str(out)
                        ctx["result"] = {
                            "outcome": Outcome.FAILURE.value,
                            "composition": comp_dict,
                        }
                    else:
                        rd = (
                            out.result.to_dict()
                            if hasattr(out.result, "to_dict")
                            else (out.result or {})
                        )
                        ctx["result"] = {
                            **rd,
                            "outcome": rd.get(
                                "outcome", Outcome.FAILURE.value
                            ),
                            "composition": comp_dict,
                        }
        elif len(ready) == 1:
            # the pack shrank to one — run the ordinary solo path so
            # the member loses nothing (full plane support)
            ctx = ready[0]
            try:
                ctx["result"] = do_run(
                    engine, ctx["tsk"], ctx["ow"], ctx["cancel"]
                )
            except TaskPreemptedError as e:
                ctx["preempted"] = e
                ctx["ow"].infof("%s", e)
            except Exception as e:  # noqa: BLE001
                ctx["ow"].write_error(str(e))
                ctx["error"] = str(e)
                ctx["result"] = {
                    "outcome": (
                        Outcome.CANCELED.value
                        if ctx["cancel"].is_set()
                        else Outcome.FAILURE.value
                    )
                }
    finally:
        for ctx in ctxs:
            tsk = ctx["tsk"]
            if ctx["preempted"] is not None:
                ctx["timer"].cancel()
                engine.drop_cancel(tsk.id)
                engine.drop_preempt(tsk.id)
                _requeue_preempted(engine, tsk, ctx["preempted"])
                try:
                    ctx["log"].close()
                except OSError:
                    pass
                continue
            tsk.result = ctx["result"] or {
                "outcome": Outcome.FAILURE.value
            }
            if ctx["error"]:
                tsk.error = ctx["error"]
            else:
                try:
                    ctx["ow"].write_result(tsk.result)
                except Exception:  # noqa: BLE001 — log-only
                    pass
            ctx["timer"].cancel()
            engine.drop_cancel(tsk.id)
            engine.drop_preempt(tsk.id)
            final = (
                State.CANCELED
                if ctx["cancel"].is_set() and tsk.error
                else State.COMPLETE
            )
            tsk.states.append(
                DatedState(state=final, created=time.time())
            )
            # same ordering contract as the solo path: spans on disk
            # before COMPLETE is observable
            _finish_task(engine, tsk)
            engine.storage.archive(tsk)
            notify_task_finished(engine.env, tsk)
            try:
                ctx["log"].close()
            except OSError:
                pass
            S().info(
                "task %s finished: %s (packed)",
                tsk.id,
                tsk.outcome().value,
            )


# ----------------------------------------------------------------- builds


def do_build(
    engine: Engine,
    comp: Composition,
    manifest: TestPlanManifest,
    sources_dir: str,
    build_id: str,
    ow: OutputWriter,
    cancel: threading.Event,
) -> Composition:
    """Build all groups, deduplicating by build key; returns a clone with
    per-group ``run.artifact`` filled in (``supervisor.go:298-493``)."""
    comp = prepare_for_build(comp, manifest)
    validate_for_build(comp)

    # dedup groups by BuildKey (supervisor.go:359-364)
    by_key: dict[str, list[int]] = {}
    for i, g in enumerate(comp.groups):
        if g.run.artifact:
            continue  # reuse previously built artifact
        by_key.setdefault(g.build_key(), []).append(i)

    limit = comp.global_.concurrent_builds or 4
    results: dict[str, str] = {}

    def build_one(key: str, group_idx: int) -> tuple[str, str]:
        g = comp.groups[group_idx]
        builder = engine.builder_by_name(g.builder)
        if builder is None:
            raise ValueError(f"unknown builder: {g.builder}")
        cfg = (
            CoalescedConfig()
            .append(engine.env.builders.get(g.builder))
            .append(g.build_config)
        )
        inp = BuildInput(
            build_id=f"{build_id}-{group_idx}",
            test_plan=comp.global_.plan,
            unpacked_plan_dir=sources_dir,
            selectors=list(g.build.selectors),
            dependencies={
                d.module: (d.target, d.version) for d in g.build.dependencies
            },
            build_config=cfg.flatten(),
            env=engine.env,
        )
        out = builder.build(inp, ow, cancel)
        return key, out.artifact_path

    if by_key:
        with concurrent.futures.ThreadPoolExecutor(max_workers=limit) as pool:
            futs = [
                pool.submit(build_one, key, idxs[0]) for key, idxs in by_key.items()
            ]
            for fut in concurrent.futures.as_completed(futs):
                key, artifact = fut.result()
                results[key] = artifact

    for g in comp.groups:
        if not g.run.artifact:
            g.run.artifact = results[g.build_key()]
            ow.infof("group %s built: artifact %s", g.id, g.run.artifact)
    return comp


def do_build_task(
    engine: Engine, tsk: Task, ow: OutputWriter, cancel: threading.Event
) -> dict:
    comp = Composition.from_dict(tsk.composition)
    manifest = TestPlanManifest.from_dict(tsk.input["manifest"])
    built = do_build(
        engine, comp, manifest, tsk.input.get("sources_dir", ""), tsk.id, ow, cancel
    )
    # build = compile: an explicit build task additionally precompiles the
    # composition's programs into the persistent XLA cache (the analog of
    # the reference's build-time image production, supervisor.go:359-364).
    # The implicit build inside a run task skips this — the run compiles
    # (and populates the same cache) immediately afterwards anyway.
    from testground_tpu.builders.base import Precompiler

    for builder_id in built.list_builders():
        builder = engine.builder_by_name(builder_id)
        if isinstance(builder, Precompiler) and not cancel.is_set():
            try:
                builder.precompile(built, manifest, engine.env, ow, cancel)
            except Exception as e:  # noqa: BLE001 — precompile is an
                # optimization; the snapshot artifact above is already valid
                ow.warn("%s precompile failed (build still ok): %s", builder_id, e)
    return {
        "outcome": Outcome.SUCCESS.value,
        "artifacts": {g.id: g.run.artifact for g in built.groups},
        "composition": built.to_dict(),
    }


# ------------------------------------------------------------------- runs


def do_run(
    engine: Engine, tsk: Task, ow: OutputWriter, cancel: threading.Event
) -> dict:
    """(``supervisor.go:494-656``)."""
    comp = Composition.from_dict(tsk.composition)
    manifest = TestPlanManifest.from_dict(tsk.input["manifest"])
    sources_dir = tsk.input.get("sources_dir", "")

    # refuse disabled runners (supervisor.go:568-571)
    runner_id = comp.global_.runner
    if engine.env.runner_is_disabled(runner_id):
        raise ValueError(f"runner {runner_id} is disabled in .env.toml")
    runner = engine.runner_by_name(runner_id)
    if runner is None:
        raise ValueError(f"unknown runner: {runner_id}")

    # build any groups missing artifacts (supervisor.go:495-518)
    needs_build = any(not g.run.artifact for g in comp.groups)
    if needs_build:
        comp = do_build(engine, comp, manifest, sources_dir, tsk.id, ow, cancel)
        tsk.composition = comp.to_dict()
        engine.storage.update_current(tsk)

    comp = prepare_for_run(comp, manifest)
    validate_for_run(comp)

    # healthcheck with fix (supervisor.go:541-553)
    from testground_tpu.runners.base import HealthcheckedRunner

    if isinstance(runner, HealthcheckedRunner):
        report = runner.healthcheck(fix=True, ow=ow, env=engine.env)
        if report is not None and not report.ok():
            raise RuntimeError(f"runner {runner_id} failed healthcheck: {report}")

    # coalesce runner config: composition > .env.toml > manifest-applied
    # defaults already in run_config (supervisor.go:563-581)
    coalesced = CoalescedConfig().append(engine.env.runners.get(runner_id)).append(
        comp.global_.run_config
    )
    cfg_type = runner.config_type()
    runner_cfg = (
        coalesced.coalesce_into(cfg_type)
        if cfg_type is not None
        else coalesced.flatten()
    )

    # Execute each run in the composition sequentially; the task result
    # aggregates per-run results (multi-run [[runs]] support).
    run_results: dict[str, dict] = {}
    outcome = Outcome.SUCCESS
    artifacts_by_group = {g.id: g.run.artifact for g in comp.groups}

    # task-level performance ledger (docs/OBSERVABILITY.md): the queue
    # wait and per-run runner wall are only visible HERE — the executor
    # measures inside a run, the engine's /metrics surface needs what
    # happened around it (scheduled → processing is appended by
    # queue.pop, so the state timestamps carry the wait)
    task_perf: dict = {"runner_wall_secs": {}}
    if len(tsk.states) >= 2:
        task_perf["queued_secs"] = round(
            max(0.0, tsk.states[-1].created - tsk.states[0].created), 3
        )

    for run in comp.runs:
        if cancel.is_set():
            raise RuntimeError("task canceled")
        run_id = tsk.id if len(comp.runs) == 1 else f"{tsk.id}-{run.id}"
        groups = []
        for rg in run.groups:
            backing = comp.get_group(rg.effective_group_id())
            groups.append(
                RunGroup(
                    id=rg.id,
                    instances=rg.calculated_instance_count,
                    artifact_path=artifacts_by_group[backing.id],
                    builder=backing.builder or comp.global_.builder,
                    parameters=dict(rg.test_params),
                    profiles=dict(rg.profiles),
                    resources=rg.resources,
                    faults=[dict(f) for f in getattr(rg, "faults", [])],
                    trace=dict(getattr(rg, "trace", {}) or {}),
                    slo=[dict(s) for s in getattr(rg, "slo", [])],
                )
            )
        rinput = RunInput(
            run_id=run_id,
            test_plan=comp.global_.plan,
            test_case=comp.global_.case,
            total_instances=run.total_instances,
            groups=groups,
            runner_config=runner_cfg,
            disable_metrics=comp.global_.disable_metrics,
            # run-global chaos schedule ([[global.run.faults]]) — the
            # per-group schedules ride on each RunGroup above
            faults=[
                dict(f)
                for f in (
                    comp.global_.run.faults
                    if comp.global_.run is not None
                    else []
                )
            ],
            # run-global flight-recorder table ([global.run.trace])
            trace=dict(
                comp.global_.run.trace
                if comp.global_.run is not None
                else {}
            ),
            # run-global SLO assertions ([[global.run.slo]])
            slo=[
                dict(s)
                for s in (
                    comp.global_.run.slo
                    if comp.global_.run is not None
                    else []
                )
            ],
            trace_ctx=_run_trace_ctx(tsk),
            env=engine.env,
            # live migration (docs/FLEET.md): single-[[runs]] tasks only —
            # a multi-run task's partial results have no requeue story
            preempt=(
                engine.register_preempt(tsk.id)
                if len(comp.runs) == 1
                else None
            ),
        )
        ow.infof(
            "executing run %s: plan=%s case=%s instances=%d runner=%s",
            run_id,
            comp.global_.plan,
            comp.global_.case,
            run.total_instances,
            runner_id,
        )
        t_run = time.monotonic()
        try:
            out = runner.run(rinput, ow, cancel)
        except TaskPreemptedError:
            # never a per-run failure: only armed for single-[[runs]]
            # tasks, and process_task's dedicated handler requeues
            raise
        except SloBreachError as e:
            # typed run-health failure (docs/OBSERVABILITY.md "Run health
            # plane"): the run was canceled at a chunk boundary because a
            # severity="fail" SLO breached. The exception carries the
            # fully-assembled RunOutput — journal (telemetry, perf, slo
            # breach records) included — so the archived task keeps the
            # failed soak's complete record instead of a bare error
            # string. The task-level cancel event was NOT set (the SLO
            # plane cancels through its own wrapper), so later [[runs]]
            # still execute, mirroring the continue-on-failure rule.
            ow.write_error(f"run {run.id} failed: {e}")
            engine.events.emit(
                "task.slo_canceled",
                task=tsk.id,
                trace=tsk.trace,
                run=run.id,
                rule=e.breach.get("rule", ""),
                metric=e.breach.get("metric", ""),
                observed=e.breach.get("observed"),
            )
            bo = e.run_output
            result_dict = (
                bo.result.to_dict()
                if bo is not None and hasattr(bo.result, "to_dict")
                else {"outcome": Outcome.FAILURE.value}
            )
            run_results[run.id] = {**result_dict, "error": str(e)}
            outcome = Outcome.FAILURE
            continue
        except Exception as e:  # noqa: BLE001 — per-run isolation
            # single-run: the exception IS the task error (existing path).
            # multi-[[runs]]: record it on THIS run and keep going — the
            # reference's MultiRunStrategy continues past a failed run
            # (run.go:281-336, 1493_continue_on_failure.sh), and the CSV
            # attributes the error to the run that raised it, not to all.
            # Cancellation is not a per-run failure: re-raise so the task
            # archives as CANCELED, not COMPLETE/FAILURE.
            if len(comp.runs) == 1 or cancel.is_set():
                raise
            ow.write_error(f"run {run.id} failed: {e}")
            run_results[run.id] = {
                "outcome": Outcome.FAILURE.value,
                "error": str(e),
            }
            outcome = Outcome.FAILURE
            continue
        finally:
            task_perf["runner_wall_secs"][run.id] = round(
                time.monotonic() - t_run, 3
            )
        result = out.result if out is not None else None
        result_dict = (
            result.to_dict() if hasattr(result, "to_dict") else (result or {})
        )
        run_results[run.id] = result_dict
        if result_dict.get("outcome") != Outcome.SUCCESS.value:
            outcome = Outcome.FAILURE

    # run packing requested but executed solo: this code path IS the
    # solo path (packed tasks run through process_task_pack), so when
    # the composition opted in with pack=true the journal must say WHY
    # it did not pack — `tg stats` renders sim.pack.solo_reason so a
    # tenant sees the cause instead of guessing (the same
    # classification `tg check` previews as rule pack.solo)
    if runner_id == "sim:jax" and _truthy(getattr(runner_cfg, "pack", False)):
        from .pack import pack_solo_reason

        solo_reason = (
            pack_solo_reason(tsk, engine.env.runners.get(runner_id) or {})
            or "no compatible queued run to pack with at claim time"
        )
        # control plane: the solo cause rides on the claim span, the
        # journal, and the tg_fleet_pack_solo_total counter
        tsk.trace["solo_reason"] = solo_reason
        engine.fleet_note_solo(solo_reason)
        engine.events.emit(
            "pack.solo",
            task=tsk.id,
            trace=tsk.trace,
            solo_reason=solo_reason,
        )
        for rres in run_results.values():
            journal = rres.get("journal") if isinstance(rres, dict) else None
            if isinstance(journal, dict) and isinstance(
                journal.get("sim"), dict
            ):
                journal["sim"]["pack"] = {
                    "requested": True,
                    "packed": False,
                    "solo_reason": solo_reason,
                }

    base = (
        run_results[comp.runs[0].id]
        if len(comp.runs) == 1
        else {"runs": run_results}
    )
    return {
        **base,
        "outcome": outcome.value,
        "composition": comp.to_dict(),
        "perf": task_perf,
    }
