"""Command-line interface. Twin of the reference's ``pkg/cmd``."""
