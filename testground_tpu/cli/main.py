"""``tg`` CLI entry point.

Command surface mirrors the reference's ``pkg/cmd/root.go:10-24``: run,
build, plan, check, describe, daemon, collect, terminate, healthcheck,
tasks, status, stats, perf, watch, netmap, top, trace, logs, version. The
engine
runs in-process unless ``--endpoint`` points at a daemon (the reference's
client↔daemon hop is transport, not semantics).
"""

from __future__ import annotations

import argparse
import sys

from testground_tpu import __version__
from testground_tpu.logging_ import set_level


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tg",
        description=(
            "testground-tpu: a TPU-native platform for testing, benchmarking "
            "and simulating distributed and p2p systems at scale"
        ),
    )
    p.add_argument("-v", "--verbose", action="store_true", help="verbose logging")
    p.add_argument(
        "--endpoint",
        default="",
        help="daemon endpoint (default: in-process engine)",
    )
    sub = p.add_subparsers(dest="command")

    from . import commands

    commands.register_run(sub)
    commands.register_build(sub)
    commands.register_plan(sub)
    commands.register_check(sub)
    commands.register_describe(sub)
    commands.register_tasks(sub)
    commands.register_status(sub)
    commands.register_stats(sub)
    commands.register_perf(sub)
    commands.register_watch(sub)
    commands.register_netmap(sub)
    commands.register_diff(sub)
    commands.register_top(sub)
    commands.register_trace(sub)
    commands.register_logs(sub)
    commands.register_collect(sub)
    commands.register_healthcheck(sub)
    commands.register_preempt(sub)
    commands.register_terminate(sub)
    commands.register_daemon(sub)
    commands.register_sync_service(sub)
    commands.register_sync_stats(sub)
    commands.register_sim_worker(sub)
    commands.register_version(sub)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.verbose:
        set_level("debug")
    if args.command is None:
        build_parser().print_help()
        return 0
    if args.command == "version":
        print(f"testground-tpu {__version__}")
        return 0
    try:
        return args.func(args) or 0
    except KeyboardInterrupt:
        return 130
    except BrokenPipeError:
        # downstream pager/head closed the pipe; exit quietly
        try:
            sys.stdout.close()
        except Exception:  # noqa: BLE001
            pass
        return 0
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"error: {e}", file=sys.stderr)
        if args.verbose:
            raise
        return 1


if __name__ == "__main__":
    sys.exit(main())
