"""``tg`` CLI entry point. Command surface mirrors the reference's
``pkg/cmd/root.go:10-24`` verbs; commands land with the engine layer."""

from __future__ import annotations

import sys

from testground_tpu import __version__


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] in ("version", "--version"):
        print(f"testground-tpu {__version__}")
        return 0
    print(
        "testground-tpu: TPU-native distributed-systems test platform\n"
        "commands: run build plan describe daemon collect terminate "
        "healthcheck tasks status logs version",
        file=sys.stderr,
    )
    return 0 if not argv else 2


if __name__ == "__main__":
    sys.exit(main())
