"""CLI command implementations (twin of ``pkg/cmd/{run,build,plan,describe,
collect,terminate,healthcheck,tasks,status,logs}.go``).

Output phrasing for run queueing/completion matches the reference so the
shell-level assertions keep working (``integration_tests/header.sh`` greps
"run is queued with ID" / "finished run with ID").
"""

from __future__ import annotations

import os
import shutil
import sys
import time

from testground_tpu.api import (
    Composition,
    Global,
    Group,
    Instances,
    TestPlanManifest,
    load_composition,
    validate_for_run,
)
from testground_tpu.config import EnvConfig
from testground_tpu.engine import Engine, Outcome, State
from testground_tpu.rpc import OutputWriter
from testground_tpu.utils.conv import parse_key_values

# --------------------------------------------------------------- plumbing


def _engine(args):
    """The engine behind every verb: in-process by default, or an
    Engine-shaped HTTP client when ``--endpoint`` (or the .env.toml
    ``[client] endpoint``) points at a daemon — the client↔daemon hop is
    transport, not semantics (``pkg/client/client.go:43-513``).

    In-process task state must survive across CLI invocations
    (status/logs/tasks run in fresh processes), so the memory default
    upgrades to disk unless .env.toml explicitly chose memory."""
    env = EnvConfig.load()
    endpoint = _endpoint(args, env)
    if endpoint:
        from testground_tpu.client import Client, RemoteEngine

        return RemoteEngine(Client(endpoint, token=env.client.token), env)
    if not env.task_repo_explicit:
        env.daemon.scheduler.task_repo_type = "disk"
    engine = Engine.new_default(env)
    engine.start_workers()
    return engine


def _print_chunk_line(line: str, raw_fallback: bool = True) -> None:
    """Decode one task-log chunk line to the console (shared by run-follow
    and ``tg logs``)."""
    from testground_tpu.rpc import Chunk

    try:
        c = Chunk.from_json(line)
    except Exception:  # noqa: BLE001 — non-chunk lines pass through
        if raw_fallback:
            sys.stdout.write(line)
        return
    if c.type == "p" and isinstance(c.payload, str):
        sys.stdout.write(c.payload)
    elif c.type == "e" and c.error:
        print(f"error: {c.error}", file=sys.stderr)


def _resolve_plan(env: EnvConfig, plan: str) -> tuple[str, TestPlanManifest]:
    """Resolve a plan name/path to (source dir, manifest) — the reference
    resolves against $TESTGROUND_HOME/plans (``pkg/cmd/run.go:181``)."""
    candidates = [
        plan,
        os.path.join(env.dirs.plans(), plan),
    ]
    for c in candidates:
        manifest_path = os.path.join(c, "manifest.toml")
        if os.path.isfile(manifest_path):
            return os.path.abspath(c), TestPlanManifest.load_file(manifest_path)
    raise FileNotFoundError(
        f"plan {plan!r} not found (searched: {candidates}); "
        f"import it with `tg plan import --from <dir>`"
    )


def _created_by(args, env: EnvConfig):
    """CreatedBy from the --metadata-* flags (+ [client] user) — the CI
    identity that drives per-branch queue dedup (``pkg/cmd/run.go:62-70``,
    ``queue.go:80-97``). None when no metadata was given."""
    from testground_tpu.engine.task import CreatedBy

    repo = getattr(args, "metadata_repo", "")
    branch = getattr(args, "metadata_branch", "")
    commit = getattr(args, "metadata_commit", "")
    if not (repo or branch or commit or env.client.user):
        return None
    return CreatedBy(
        user=env.client.user, repo=repo, branch=branch, commit=commit
    )


def _endpoint(args, env: EnvConfig) -> str:
    """Daemon endpoint precedence: --endpoint flag > .env.toml [client]."""
    return getattr(args, "endpoint", "") or env.client.endpoint


def _resolve_manifest(env: EnvConfig, args, plan: str) -> TestPlanManifest:
    """Resolve a plan's manifest: locally, or from the daemon when
    ``--endpoint`` points at one (GET /describe) — plans live daemon-side
    in this framework, so a remote CLI need not hold a local copy."""
    try:
        return _resolve_plan(env, plan)[1]
    except FileNotFoundError:
        endpoint = _endpoint(args, env)
        if not endpoint:
            raise
        from testground_tpu.client import Client

        return Client(endpoint, token=env.client.token).describe_plan(plan)


def _wait_task(engine: Engine, task_id: str, follow_logs: bool = True):
    if follow_logs:
        for line in engine.logs(task_id, follow=True):
            _print_chunk_line(line, raw_fallback=False)
    while True:
        t = engine.get_task(task_id)
        if t is not None and t.state().state in (State.COMPLETE, State.CANCELED):
            return t
        time.sleep(0.1)


def _collect_to_file(engine: Engine, runner_id: str, run_id: str, dest: str):
    from testground_tpu.rpc import discard_writer

    with open(dest, "wb") as f:
        engine.do_collect_outputs(runner_id, run_id, f, discard_writer())
    print(f"downloaded outputs to {dest}")


# ------------------------------------------------------------------- run


def _help_func(parser):
    """Default func for command groups invoked bare: print usage, exit 2."""

    def fn(args):
        parser.print_help()
        return 2

    return fn


def _add_metadata_flags(p) -> None:
    """CI metadata flags (``pkg/cmd/run.go:62-70``; also on build)."""
    p.add_argument("--metadata-repo", default="", help="source repo (CI)")
    p.add_argument("--metadata-branch", default="", help="source branch (CI)")
    p.add_argument("--metadata-commit", default="", help="source commit (CI)")


def _add_priority_flag(p) -> None:
    p.add_argument(
        "--priority",
        type=int,
        default=0,
        help="queue priority (higher runs first; against a full fleet a "
        "positive priority may EVICT the lowest-priority running task — "
        "docs/FLEET.md)",
    )


def register_run(sub) -> None:
    p = sub.add_parser("run", help="(builds and) runs a composition or single test case")
    p.set_defaults(func=_help_func(p))
    psub = p.add_subparsers(dest="run_mode")

    pc = psub.add_parser("composition", help="run a composition file")
    pc.add_argument("-f", "--file", required=True, help="composition TOML file")
    pc.add_argument("--collect", action="store_true", help="collect outputs after run")
    pc.add_argument("--collect-file", default="", help="write outputs tgz here")
    pc.add_argument(
        "--write-artifacts",
        action="store_true",
        help="write built artifacts back into the composition file",
    )
    pc.add_argument(
        "--ignore-artifacts",
        action="store_true",
        help="ignore artifacts in the composition; rebuild",
    )
    pc.add_argument("--run-ids", default="", help="only run these [[runs]] ids (csv)")
    pc.add_argument(
        "--result-file", default="", help="append run results as CSV rows"
    )
    pc.add_argument(
        "--detach",
        action="store_true",
        help="queue the task and exit without waiting (the reference's "
        "non---wait mode; follow later with `tg logs -f`)",
    )
    _add_priority_flag(pc)
    _add_metadata_flags(pc)
    pc.set_defaults(func=run_composition_cmd)

    ps = psub.add_parser("single", help="run a single plan/case")
    ps.add_argument("plan_case", help="<plan>:<case>")
    ps.add_argument("--builder", default="")
    ps.add_argument("--runner", default="")
    ps.add_argument("-i", "--instances", type=int, default=0)
    ps.add_argument(
        "-tp",
        "--test-param",
        action="append",
        default=[],
        help="test param k=v (repeatable)",
    )
    ps.add_argument("--collect", action="store_true")
    ps.add_argument(
        "-ub",
        "--use-build",
        default="",
        help="build artifact from a previous build (skips the build step)",
    )
    ps.add_argument(
        "--run-cfg",
        action="append",
        default=[],
        help="override runner configuration k=v (repeatable)",
    )
    ps.add_argument(
        "--disable-metrics",
        action="store_true",
        help="disable metrics batching",
    )
    ps.add_argument(
        "--detach",
        action="store_true",
        help="queue the task and exit without waiting",
    )
    _add_priority_flag(ps)
    _add_metadata_flags(ps)
    ps.set_defaults(func=run_single_cmd)

    pr = psub.add_parser(
        "resume",
        help="resume an interrupted checkpointed run from its newest "
        "snapshot (docs/CHECKPOINT.md): re-queues the task's own "
        "composition with runner config resume_from=<task>, so the new "
        "run seeds its carry from the snapshot and continues "
        "bit-identically",
    )
    pr.add_argument("task", help="task id of the checkpointed run")
    pr.add_argument(
        "--run-cfg",
        action="append",
        default=[],
        help="override runner configuration k=v on the resumed run "
        "(repeatable) — e.g. max_ticks=10000000 to extend a "
        "budget-interrupted soak; program-shaping options still "
        "validate against the snapshot manifest",
    )
    pr.add_argument(
        "--detach",
        action="store_true",
        help="queue the resumed task and exit without waiting",
    )
    _add_priority_flag(pr)
    _add_metadata_flags(pr)
    pr.set_defaults(func=run_resume_cmd)


def run_resume_cmd(args) -> int:
    """``tg run resume <task>``: rebuild the interrupted task's own
    composition (artifacts already resolved, so no rebuild — the
    snapshot's build_key validates the sources anyway) and queue it with
    ``resume_from`` pointing at the old run's outputs dir."""
    engine = _engine(args)
    try:
        t = engine.get_task(args.task)
        if t is None:
            raise KeyError(f"unknown task {args.task}")
        if not t.composition:
            raise ValueError(
                f"task {args.task} carries no composition to resume"
            )
        comp = Composition.from_dict(t.composition)
        if len(comp.runs) > 1:
            # multi-[[runs]] tasks write one outputs dir PER run
            # (<task>-<run id>) and every run would share this single
            # resume_from — refuse readably instead of failing each run
            # with "no snapshots" inside the executor
            raise ValueError(
                f"task {t.id} is a multi-[[runs]] composition "
                f"({len(comp.runs)} runs) — resume one run at a time by "
                "re-running the composition framed to that run "
                "(--run-ids <id>) with run config "
                f"resume_from = \"{t.id}-<run id>\""
            )
        comp.global_.run_config = dict(comp.global_.run_config or {})
        comp.global_.run_config.update(
            parse_key_values(getattr(args, "run_cfg", []))
        )
        comp.global_.run_config["resume_from"] = t.id
        print(
            f"resuming task {t.id} ({t.name()}) from its newest snapshot"
        )
    finally:
        engine.stop()
    return _run(args, comp)


def run_composition_cmd(args) -> int:
    comp = load_composition(args.file)
    if args.ignore_artifacts:
        for g in comp.groups:
            g.run.artifact = ""
    # validate before frame_for_runs so a bad composition is rejected even
    # when --run-ids selects a subset (queue_run re-validates the framed
    # composition; reference order is the same, run.go:157 → FrameForRuns)
    validate_for_run(comp)
    if args.run_ids:
        comp = comp.frame_for_runs(*args.run_ids.split(","))
    return _run(args, comp, write_artifacts_to=args.file if args.write_artifacts else "")


def run_single_cmd(args) -> int:
    """(``pkg/cmd/run.go`` runSingleCmd + createSingletonComposition)."""
    plan, _, case = args.plan_case.partition(":")
    if not case:
        raise ValueError("expected <plan>:<case>")
    env = EnvConfig.load()
    manifest = _resolve_manifest(env, args, plan)
    builder = args.builder or manifest.defaults.get("builder", "")
    runner = args.runner or manifest.defaults.get("runner", "")
    tc = manifest.testcase_by_name(case)
    instances = args.instances or (tc.instances.default if tc else 1) or 1
    comp = Composition(
        global_=Global(
            plan=plan,
            case=case,
            builder=builder,
            runner=runner,
            # --run-cfg k=v overrides (run.go:104-107)
            run_config=parse_key_values(getattr(args, "run_cfg", [])),
            disable_metrics=getattr(args, "disable_metrics", False),
        ),
        groups=[
            Group(
                id="single",
                instances=Instances(count=instances),
            )
        ],
    )
    comp.groups[0].run.test_params = {
        k: str(v) for k, v in parse_key_values(args.test_param).items()
    }
    if getattr(args, "use_build", ""):
        # --use-build: reuse a prior build's artifact, skipping the build
        # step entirely (run.go:119-123; reuse check supervisor do_build)
        comp.groups[0].run.artifact = args.use_build
    from testground_tpu.api import generate_default_run

    comp = generate_default_run(comp)
    print(
        'created a synthetic composition file for this job; all instances '
        'will run under singleton group "single"'
    )
    return _run(args, comp)


def _run(args, comp: Composition, write_artifacts_to: str = "") -> int:
    from testground_tpu.client import RemoteEngine
    from testground_tpu.tracectx import TraceContext

    engine = _engine(args)
    try:
        created_by = _created_by(args, engine.env)
        # the submit span roots the task's lifecycle trace: the CLI mints
        # the trace id here so the causal chain starts at the submitter,
        # and the daemon/engine parents every later span under it
        # (engine/tracetree.py; docs/OBSERVABILITY.md)
        submit_ctx = TraceContext.mint()
        priority = int(getattr(args, "priority", 0) or 0)
        if isinstance(engine, RemoteEngine):
            # the daemon resolves the plan from ITS $TESTGROUND_HOME/plans
            task_id = engine.queue_run(
                comp,
                priority=priority,
                created_by=created_by,
                trace_parent=submit_ctx.to_traceparent(),
            )
        else:
            src_dir, manifest = _resolve_plan(engine.env, comp.global_.plan)
            task_id = engine.queue_run(
                comp,
                manifest,
                sources_dir=src_dir,
                priority=priority,
                created_by=created_by,
                trace_parent=submit_ctx.to_traceparent(),
            )
        print(f"run is queued with ID: {task_id}")
        if getattr(args, "detach", False):
            # queue-only mode (the reference without --wait, run.go:348):
            # in-process engines must keep running the task, so detach is
            # only meaningful against a daemon
            if not isinstance(engine, RemoteEngine):
                print(
                    "warning: --detach without --endpoint queues into an "
                    "in-process engine that exits with the CLI; waiting "
                    "instead",
                    file=sys.stderr,
                )
            else:
                dropped = [
                    flag
                    for flag, attr in (
                        ("--collect", "collect"),
                        ("--collect-file", "collect_file"),
                        ("--result-file", "result_file"),
                        ("--write-artifacts", "write_artifacts"),
                    )
                    if getattr(args, attr, None)
                ]
                if dropped:
                    print(
                        "warning: --detach does not wait for the task, so "
                        f"{', '.join(dropped)} will be ignored",
                        file=sys.stderr,
                    )
                return 0
        t = _wait_task(engine, task_id)
        outcome = t.outcome()
        print(f"finished run with ID: {task_id} (outcome: {outcome.value})")

        # per-run breakdown for multi-[[runs]] compositions (the reference
        # CLI reports each run's result as it completes, run.go:281-336)
        run_results = (
            t.result.get("runs", {}) if isinstance(t.result, dict) else {}
        )
        for rid, rres in run_results.items():
            print(
                f"  run {rid}: outcome: "
                f"{rres.get('outcome', Outcome.UNKNOWN.value)}"
            )

        if write_artifacts_to and isinstance(t.result, dict):
            comp_out = t.result.get("composition")
            if comp_out:
                Composition.from_dict(comp_out).write_file(write_artifacts_to)
                print(f"wrote artifacts into composition {write_artifacts_to}")

        collect_file = getattr(args, "collect_file", "")
        if getattr(args, "collect", False) or collect_file:
            dest = collect_file or f"{task_id}.tgz"
            _collect_to_file(engine, comp.global_.runner, task_id, dest)

        result_file = getattr(args, "result_file", "")
        if result_file:
            import csv

            new = not os.path.exists(result_file)
            with open(result_file, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["task_id", "plan_case", "outcome", "error"])
                if run_results:
                    # one row per [[runs]] entry, like the reference's
                    # --result-file CSV (asserted per-run by
                    # integration_tests/1493_continue_on_failure.sh)
                    for rid, rres in run_results.items():
                        w.writerow(
                            [
                                f"{t.id}-{rid}",
                                t.name(),
                                rres.get("outcome", Outcome.UNKNOWN.value),
                                # per-run error, not the task-level one — a
                                # failure in run A must not show up on run
                                # B's row (when do_run raises, the task
                                # result has no 'runs' key and the single
                                # task-level row below carries t.error)
                                rres.get("error", ""),
                            ]
                        )
                else:
                    w.writerow([t.id, t.name(), outcome.value, t.error])

        return 0 if outcome == Outcome.SUCCESS else 1
    finally:
        engine.stop()


# ------------------------------------------------------------------ build


def register_build(sub) -> None:
    p = sub.add_parser("build", help="builds a composition or single plan")
    p.set_defaults(func=_help_func(p))
    psub = p.add_subparsers(dest="build_mode")
    pc = psub.add_parser("composition")
    pc.add_argument("-f", "--file", required=True)
    pc.add_argument("--write-artifacts", action="store_true")
    pc.add_argument(
        "--buckets",
        action="store_true",
        help="also precompile the canonical shape-bucket ladder for the "
        "composition's case (PERF.md 'Serving: buckets + packing') — "
        "one command makes the compile cache warm for ANY instance "
        "count a bucketed run may ask for",
    )
    pc.add_argument(
        "--run-cfg",
        action="append",
        default=[],
        help="override runner configuration k=v for the precompile "
        "(repeatable) — e.g. bucket_ladder=4096,32768",
    )
    _add_metadata_flags(pc)
    pc.set_defaults(func=build_composition_cmd)
    ps = psub.add_parser("single")
    ps.add_argument(
        "plan",
        help="<plan> or <plan>:<case> — naming a case lets program "
        "builders (sim:plan) precompile that case into the compile cache",
    )
    ps.add_argument("--builder", default="")
    ps.add_argument(
        "--buckets",
        action="store_true",
        help="also precompile the canonical shape-bucket ladder for "
        "this case (requires <plan>:<case>); per-bucket compile_secs "
        "land in the build markers",
    )
    ps.add_argument(
        "--run-cfg",
        action="append",
        default=[],
        help="override runner configuration k=v for the precompile "
        "(repeatable) — e.g. bucket_ladder=4096,32768",
    )
    _add_metadata_flags(ps)
    ps.set_defaults(func=build_single_cmd)

    pp = psub.add_parser(
        "purge", help="purge the cache for a builder and testplan"
    )
    pp.add_argument("-b", "--builder", required=True)
    pp.add_argument("-p", "--plan", required=True)
    pp.set_defaults(func=build_purge_cmd)


def _apply_bucket_build_flags(comp, args) -> None:
    """``tg build --buckets`` / ``--run-cfg``: thread the ladder-warming
    request through the composition's run config (the channel the
    sim:plan precompile coalesces); bucketed runs default to
    bucket=auto so they read the programs the build just warmed."""
    overrides = parse_key_values(getattr(args, "run_cfg", []) or [])
    if overrides:
        comp.global_.run_config = dict(comp.global_.run_config or {})
        comp.global_.run_config.update(overrides)
    if not getattr(args, "buckets", False):
        return
    comp.global_.run_config = dict(comp.global_.run_config or {})
    comp.global_.run_config["build_buckets"] = True
    comp.global_.run_config.setdefault("bucket", "auto")


def build_composition_cmd(args) -> int:
    from testground_tpu.client import RemoteEngine
    from testground_tpu.tracectx import TraceContext

    comp = load_composition(args.file)
    _apply_bucket_build_flags(comp, args)
    engine = _engine(args)
    try:
        created_by = _created_by(args, engine.env)
        submit_ctx = TraceContext.mint()
        if isinstance(engine, RemoteEngine):
            task_id = engine.queue_build(
                comp,
                created_by=created_by,
                trace_parent=submit_ctx.to_traceparent(),
            )
        else:
            src_dir, manifest = _resolve_plan(engine.env, comp.global_.plan)
            task_id = engine.queue_build(
                comp,
                manifest,
                sources_dir=src_dir,
                created_by=created_by,
                trace_parent=submit_ctx.to_traceparent(),
            )
        print(f"build is queued with ID: {task_id}")
        t = _wait_task(engine, task_id)
        print(f"finished build with ID: {task_id} (outcome: {t.outcome().value})")
        if args.write_artifacts and isinstance(t.result, dict):
            comp_out = t.result.get("composition")
            if comp_out:
                Composition.from_dict(comp_out).write_file(args.file)
                print(f"wrote artifacts into composition {args.file}")
        return 0 if t.outcome() == Outcome.SUCCESS else 1
    finally:
        engine.stop()


def build_purge_cmd(args) -> int:
    """(``build.go:91-110`` purge — drop a builder's cached artifacts for
    one plan)."""
    engine = _engine(args)
    try:
        ow = OutputWriter(sink=None, echo=sys.stdout)
        engine.do_build_purge(args.builder, args.plan, ow)
        print(f"purged {args.builder} cache for plan {args.plan}")
        return 0
    finally:
        engine.stop()


def build_single_cmd(args) -> int:
    from testground_tpu.client import RemoteEngine

    plan, _, case = args.plan.partition(":")
    engine = _engine(args)
    try:
        try:
            src_dir, manifest = _resolve_plan(engine.env, plan)
        except FileNotFoundError:
            # daemon-hosted plan: the daemon resolves its own sources
            src_dir = ""
            manifest = _resolve_manifest(engine.env, args, plan)
        builder = args.builder or manifest.defaults.get("builder", "")
        # with a case the build can precompile (build = compile for
        # sim:plan); the instance count and runner default from the
        # manifest, matching what a default `tg run single` would execute
        instances = 1
        runner = ""
        if case:
            tc = manifest.testcase_by_name(case)
            if tc is None:
                raise ValueError(
                    f"test case {case} not found in plan {plan}"
                )
            instances = tc.instances.default or tc.instances.minimum or 1
            runner = manifest.defaults.get("runner", "")
        comp = Composition(
            global_=Global(
                plan=plan, case=case, builder=builder, runner=runner
            ),
            groups=[
                Group(id="single", instances=Instances(count=instances))
            ],
        )
        if getattr(args, "buckets", False) and not case:
            raise ValueError(
                "--buckets needs a test case to resolve a program from: "
                "use `tg build single <plan>:<case> --buckets`"
            )
        _apply_bucket_build_flags(comp, args)
        created_by = _created_by(args, engine.env)
        from testground_tpu.tracectx import TraceContext

        submit_ctx = TraceContext.mint()
        if isinstance(engine, RemoteEngine):
            task_id = engine.queue_build(
                comp,
                created_by=created_by,
                trace_parent=submit_ctx.to_traceparent(),
            )
        else:
            task_id = engine.queue_build(
                comp,
                manifest,
                sources_dir=src_dir,
                created_by=created_by,
                trace_parent=submit_ctx.to_traceparent(),
            )
        print(f"build is queued with ID: {task_id}")
        t = _wait_task(engine, task_id)
        print(f"finished build with ID: {task_id} (outcome: {t.outcome().value})")
        if isinstance(t.result, dict):
            for gid, artifact in t.result.get("artifacts", {}).items():
                # printed so a later `tg run single --use-build <artifact>`
                # can reuse it (run.go:119-123)
                print(f"group {gid} artifact: {artifact}")
        return 0 if t.outcome() == Outcome.SUCCESS else 1
    finally:
        engine.stop()


# ------------------------------------------------------------------- plan


def register_plan(sub) -> None:
    p = sub.add_parser("plan", help="manage test plans in $TESTGROUND_HOME/plans")
    p.set_defaults(func=_help_func(p))
    psub = p.add_subparsers(dest="plan_mode")

    pl = psub.add_parser("list", help="list known plans")
    pl.add_argument("--testcases", action="store_true", help="also list testcases")
    pl.set_defaults(func=plan_list_cmd)

    pi = psub.add_parser("import", help="import a plan directory or git repo")
    pi.add_argument(
        "--from",
        dest="source",
        required=True,
        help="source dir, or a git URL with --git",
    )
    pi.add_argument("--name", default="", help="rename the plan on import")
    pi.add_argument(
        "--git",
        action="store_true",
        help="git-clone the source (any scheme git supports)",
    )
    pi.add_argument(
        "--force", action="store_true", help="overwrite an existing plan"
    )
    pi.set_defaults(func=plan_import_cmd)

    pr = psub.add_parser("rm", help="remove an imported plan")
    pr.add_argument("plan")
    pr.set_defaults(func=plan_rm_cmd)

    pc = psub.add_parser("create", help="scaffold a new plan")
    pc.add_argument("plan")
    pc.set_defaults(func=plan_create_cmd)


def plan_list_cmd(args) -> int:
    env = EnvConfig.load()
    root = env.dirs.plans()
    for name in sorted(os.listdir(root)) if os.path.isdir(root) else []:
        manifest_path = os.path.join(root, name, "manifest.toml")
        if not os.path.isfile(manifest_path):
            continue
        print(name)
        if args.testcases:
            m = TestPlanManifest.load_file(manifest_path)
            for tc in m.testcases:
                print(f"  {name}:{tc.name}")
    return 0


def plan_import_cmd(args) -> int:
    env = EnvConfig.load()
    tmp_ctx = None
    try:
        if args.git:
            # clone through the git binary — any scheme git supports, like
            # the reference's go-git clone path (``plan.go:210-214``) —
            # into a tempdir, then fall through to the shared import tail
            # so validation happens BEFORE any existing plan is replaced
            import subprocess
            import tempfile

            name = args.name or os.path.basename(
                args.source.rstrip("/").removesuffix(".git")
            )
            if name in ("", ".", ".."):
                raise ValueError(
                    f"cannot derive a plan name from {args.source!r}; "
                    "pass --name"
                )
            tmp_ctx = tempfile.TemporaryDirectory(dir=env.dirs.work())
            src = os.path.join(tmp_ctx.name, "clone")
            res = subprocess.run(
                ["git", "clone", "--depth", "1", args.source, src],
                capture_output=True,
                text=True,
            )
            if res.returncode != 0:
                raise RuntimeError(f"git clone failed: {res.stderr.strip()}")
        else:
            name = args.name or os.path.basename(
                os.path.abspath(args.source).rstrip("/")
            )
            src = os.path.abspath(args.source)
        if not os.path.isfile(os.path.join(src, "manifest.toml")):
            raise FileNotFoundError(
                f"{args.source} has no manifest.toml at its root"
            )
        endpoint = _endpoint(args, env)
        if endpoint:
            from testground_tpu.client import Client

            name = Client(endpoint, token=env.client.token).import_plan(
                src, name=name
            )
            print(f"imported plan {name} into daemon at {endpoint}")
            return 0
        dest = os.path.join(env.dirs.plans(), name)
        if os.path.exists(dest):
            if not args.force:
                raise FileExistsError(
                    f"plan {name} already exists at {dest}; "
                    "pass --force to replace"
                )
            shutil.rmtree(dest)
        shutil.copytree(
            src, dest, ignore=shutil.ignore_patterns("__pycache__", ".git")
        )
        print(f"imported plan {name} -> {dest}")
        return 0
    finally:
        if tmp_ctx is not None:
            tmp_ctx.cleanup()


def plan_rm_cmd(args) -> int:
    env = EnvConfig.load()
    dest = os.path.join(env.dirs.plans(), args.plan)
    if not os.path.isdir(dest):
        raise FileNotFoundError(f"no such plan: {args.plan}")
    shutil.rmtree(dest)
    print(f"removed plan {args.plan}")
    return 0


_PLAN_TEMPLATE = '''"""{name}: a testground-tpu plan."""

from testground_tpu.sdk import invoke_map


def ok(runenv):
    runenv.record_message("hello from {name}")


if __name__ == "__main__":
    invoke_map({{"ok": ok}})
'''

_MANIFEST_TEMPLATE = """name = "{name}"

[defaults]
builder = "exec:py"
runner = "local:exec"

[builders."exec:py"]
enabled = true

[runners."local:exec"]
enabled = true

[[testcases]]
name = "ok"
instances = {{ min = 1, max = 100, default = 1 }}
"""


def plan_create_cmd(args) -> int:
    env = EnvConfig.load()
    dest = os.path.join(env.dirs.plans(), args.plan)
    if os.path.exists(dest):
        raise FileExistsError(f"plan {args.plan} already exists")
    os.makedirs(dest)
    with open(os.path.join(dest, "main.py"), "w") as f:
        f.write(_PLAN_TEMPLATE.format(name=args.plan))
    with open(os.path.join(dest, "manifest.toml"), "w") as f:
        f.write(_MANIFEST_TEMPLATE.format(name=args.plan))
    print(f"created plan {args.plan} at {dest}")
    return 0


# ------------------------------------------------------------------ check


def register_check(sub) -> None:
    p = sub.add_parser(
        "check",
        help="statically analyze composition file(s) against the sim:jax "
        "admission rules — every incompatible-knob refusal the executor "
        "would raise, reported in ONE pass before anything queues "
        "(docs/CHECKING.md); --trace-plans additionally runs each "
        "referenced plan under jax.eval_shape at the composition's "
        "shapes and lints the lowered tick",
    )
    p.add_argument(
        "compositions",
        nargs="+",
        help="composition TOML file(s); the plan resolves from "
        "$TESTGROUND_HOME/plans, a plans/ dir beside the composition "
        "(plans/<plan>/_compositions/x.toml layout), or ./plans/<plan>",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="emit the machine-readable findings document (schema "
        "version 1; exit codes unchanged)",
    )
    p.add_argument(
        "--trace-plans",
        action="store_true",
        help="abstract plan tracing: run each referenced testcase under "
        "jax.eval_shape at the composition's real (and padded-ladder, "
        "when bucketed) shapes — no device allocation — and scan the "
        "lowered tick jaxpr for invariant lints (host callbacks, while "
        "loops, weak-typed state, traced-count contract violations)",
    )
    p.add_argument(
        "--run-cfg",
        action="append",
        default=[],
        help="override runner configuration k=v for the analysis "
        "(repeatable) — check what a different knob combination would "
        "do without editing the file",
    )
    p.add_argument(
        "--devices",
        type=int,
        default=0,
        help="device-context override: evaluate the mesh-bound rules as "
        "if the run had N devices (0 = detect from this host's jax "
        "backend; lets a laptop check what an 8-chip host refuses)",
    )
    p.set_defaults(func=check_cmd)


def _resolve_plan_for_check(
    env: EnvConfig, comp_path: str, plan: str
) -> tuple[str, TestPlanManifest]:
    """Plan resolution for `tg check`: the run-verb search paths plus
    the repo layouts a checked-in composition lives in —
    ``plans/<plan>/_compositions/x.toml`` resolves its own plan dir, and
    ``./plans/<plan>`` covers compositions checked from a repo root."""
    try:
        return _resolve_plan(env, plan)
    except FileNotFoundError:
        pass
    comp_dir = os.path.dirname(os.path.abspath(comp_path))
    candidates = [
        os.path.dirname(comp_dir),  # plans/<plan>/_compositions/x.toml
        os.path.join(os.getcwd(), "plans", plan),
        os.path.join(comp_dir, plan),
    ]
    for c in candidates:
        manifest_path = os.path.join(c, "manifest.toml")
        if os.path.isfile(manifest_path):
            m = TestPlanManifest.load_file(manifest_path)
            if m.name == plan:
                return os.path.abspath(c), m
    raise FileNotFoundError(
        f"plan {plan!r} for {comp_path} not found (searched "
        f"$TESTGROUND_HOME/plans and {candidates}); import it with "
        "`tg plan import --from <dir>` or run check from the repo root"
    )


def check_cmd(args) -> int:
    import json

    from testground_tpu.sim.check import (
        Finding,
        check_composition,
        findings_payload,
        render_findings,
        rule_by_id,
    )

    env = EnvConfig.load()
    overrides = parse_key_values(getattr(args, "run_cfg", []) or [])
    results = []
    load_failures = 0
    for path in args.compositions:
        try:
            comp = load_composition(path)
            if overrides:
                comp.global_.run_config = dict(
                    comp.global_.run_config or {}
                )
                comp.global_.run_config.update(overrides)
            plan_dir, manifest = _resolve_plan_for_check(
                env, path, comp.global_.plan
            )
            findings = check_composition(
                comp,
                manifest,
                env_layer=env.runners.get(comp.global_.runner or "sim:jax"),
                devices=getattr(args, "devices", 0) or 0,
                trace_plans=getattr(args, "trace_plans", False),
                plan_sources=plan_dir,
            )
        except Exception as e:  # noqa: BLE001 — per-file isolation: one
            # unloadable file must not hide the other files' findings,
            # and the failure lands IN the findings document (not
            # stderr-only) so --json consumers see it too
            load_failures += 1
            r = rule_by_id("composition.invalid")
            findings = [
                Finding(
                    rule=r.id,
                    severity=r.severity,
                    layer=r.layer,
                    message=f"cannot check: {e}",
                )
            ]
        results.append((path, findings))
    if getattr(args, "json", False):
        print(json.dumps(findings_payload(results), indent=2, sort_keys=True))
    else:
        for path, findings in results:
            print(render_findings(path, findings))
    errors = sum(
        1 for _, fs in results for f in fs if f.severity == "error"
    )
    if load_failures:
        return 2
    return 1 if errors else 0


# --------------------------------------------------------------- describe


def register_describe(sub) -> None:
    p = sub.add_parser("describe", help="describe a plan or test case")
    p.add_argument("plan", help="<plan> or <plan>:<case>")
    p.set_defaults(func=describe_cmd)


def describe_cmd(args) -> int:
    env = EnvConfig.load()
    plan, _, case = args.plan.partition(":")
    manifest = _resolve_manifest(env, args, plan)
    if case:
        tc = manifest.testcase_by_name(case)
        if tc is None:
            raise KeyError(f"test case {case} not found in plan {plan}")
        print(tc.describe())
    else:
        print(manifest.describe())
        for tc in manifest.testcases:
            print(tc.describe())
    return 0


# ---------------------------------------------------------- tasks / status


def register_tasks(sub) -> None:
    p = sub.add_parser("tasks", help="list tasks")
    p.add_argument("--state", action="append", default=[], help="filter by state")
    p.add_argument("--type", action="append", default=[], help="filter by type")
    p.add_argument(
        "--before", default="", help="created before (YYYY-MM-DD[ HH:MM:SS])"
    )
    p.add_argument(
        "--after", default="", help="created after (YYYY-MM-DD[ HH:MM:SS])"
    )
    p.add_argument("-n", "--limit", type=int, default=0)
    p.set_defaults(func=tasks_cmd)


def _parse_when(text: str) -> float | None:
    """YYYY-MM-DD[ HH:MM:SS] → epoch seconds (local time)."""
    if not text:
        return None
    for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            return time.mktime(time.strptime(text, fmt))
        except ValueError:
            continue
    raise ValueError(
        f"cannot parse time {text!r}; use YYYY-MM-DD or 'YYYY-MM-DD HH:MM:SS'"
    )


def tasks_cmd(args) -> int:
    # validate the date flags before spinning up an engine
    before, after = _parse_when(args.before), _parse_when(args.after)
    engine = _engine(args)
    try:
        tasks = engine.tasks(
            states=args.state or None,
            types=args.type or None,
            before=before,
            after=after,
            limit=args.limit,
        )
        # ID / DATE / PLAN:CASE / QUEUED / DURATION / STATE / TYPE +
        # outcome — the reference's tabwriter column order
        # (tasks.go:50-54) plus the queue-wait column (scheduled →
        # processing; live for still-queued tasks)
        for t in tasks:
            created = time.strftime(
                "%Y-%m-%d %H:%M:%S", time.localtime(t.created())
            )
            # PRE: times this task was preempted/migrated (docs/FLEET.md)
            preemptions = int(t.trace.get("preemptions", 0) or 0)
            print(
                f"{t.id}  {created}  {t.name():24}  "
                f"{t.queued_secs():6.1f}s  {t.took():7.1f}s  "
                f"{t.state().state.value:10}  {t.type.value:5}  "
                f"{preemptions:3}  "
                f"{t.outcome().value}"
            )
        return 0
    finally:
        engine.stop()


def register_stats(sub) -> None:
    p = sub.add_parser(
        "stats",
        help="show a completed task's sim telemetry summary "
        "(message flow, latency, timings, memory — docs/OBSERVABILITY.md)",
    )
    p.add_argument("task", help="task id")
    p.add_argument(
        "--json",
        action="store_true",
        help="dump the raw stats payload as JSON (machine-readable; the "
        "same shape as GET /stats)",
    )
    p.add_argument(
        "-f",
        "--follow",
        action="store_true",
        help="follow the task live first (per-chunk telemetry + SLO "
        "breaches via GET /stream, like `tg logs -f`), then print the "
        "final summary table",
    )
    p.set_defaults(func=stats_cmd)


def stats_cmd(args) -> int:
    import json

    from testground_tpu.client import RemoteEngine
    from testground_tpu.runners.pretty import render_telemetry_summary

    engine = _engine(args)
    try:
        if getattr(args, "follow", False):
            # under --json the live view goes to stderr — stdout stays
            # the machine-readable payload (the --json contract)
            _follow_stream(
                engine,
                args.task,
                families=("telemetry", "slo", "spans"),
                out=sys.stderr if getattr(args, "json", False) else None,
            )
        if isinstance(engine, RemoteEngine):
            data = engine.task_stats(args.task)
        else:
            t = engine.get_task(args.task)
            if t is None:
                raise KeyError(f"unknown task {args.task}")
            data = t.stats_payload()
        if getattr(args, "json", False):
            print(json.dumps(data, indent=2, sort_keys=True))
        else:
            print(render_telemetry_summary(data))
        return 0
    finally:
        engine.stop()


def register_perf(sub) -> None:
    p = sub.add_parser(
        "perf",
        help="show a task's performance ledger (compile/execute split, "
        "peer·ticks/s, HBM high-water mark, XLA cost estimates — "
        "docs/OBSERVABILITY.md)",
    )
    p.add_argument("task", help="task id")
    p.add_argument(
        "--json",
        action="store_true",
        help="dump the raw perf payload as JSON (machine-readable; the "
        "same shape as GET /perf)",
    )
    p.add_argument(
        "--compare",
        default="",
        metavar="FILE",
        help="print throughput deltas against a baseline JSON file — a "
        "BENCH_rNN.json line, a prior `tg perf --json` dump, or a "
        "journal sim block (written to stderr under --json so stdout "
        "stays parseable)",
    )
    p.add_argument(
        "--phases",
        action="store_true",
        help="print the per-phase tick attribution table (flops/bytes "
        "per phase + residual + whole-program rows; requires the run "
        "to have recorded it — --run-cfg phases=true)",
    )
    p.add_argument(
        "--measure",
        action="store_true",
        help="with --phases: insist on the measured ms/tick calibration "
        "column (recorded with --run-cfg phases_measure=K) — prints a "
        "hint when the run only holds the static cost rows",
    )
    p.add_argument(
        "-f",
        "--follow",
        action="store_true",
        help="follow the task live first (per-chunk throughput rows + "
        "SLO breaches via GET /stream, like `tg logs -f`), then print "
        "the final ledger table",
    )
    p.set_defaults(func=perf_cmd)


def perf_cmd(args) -> int:
    import json

    from testground_tpu.client import RemoteEngine
    from testground_tpu.runners.pretty import render_perf_summary
    from testground_tpu.sim.perf import perf_compare

    engine = _engine(args)
    try:
        if getattr(args, "follow", False):
            _follow_stream(
                engine,
                args.task,
                families=("perf", "slo", "spans"),
                out=sys.stderr if getattr(args, "json", False) else None,
            )
        if isinstance(engine, RemoteEngine):
            data = engine.task_perf(args.task)
        else:
            t = engine.get_task(args.task)
            if t is None:
                raise KeyError(f"unknown task {args.task}")
            data = t.perf_payload()
        if getattr(args, "json", False):
            print(json.dumps(data, indent=2, sort_keys=True))
        else:
            print(render_perf_summary(data))
        if getattr(args, "phases", False):
            from testground_tpu.runners.pretty import render_phase_table

            # with --json, stdout stays the parseable payload (the
            # phases block is inside it) — the table goes to stderr
            out = sys.stderr if getattr(args, "json", False) else sys.stdout
            print("-- phases --", file=out)
            print(render_phase_table(data), file=out)
            if getattr(args, "measure", False):
                # same block resolution as render_phase_table (top-level
                # payload or journal sim shape) — the hint and the table
                # must never disagree about the same payload
                block = (
                    data.get("phases")
                    or (data.get("sim") or {}).get("phases")
                    or {}
                )
                rows = block.get("phases") or []
                if not any(
                    isinstance(r, dict) and r.get("measured_ms") is not None
                    for r in rows
                ):
                    print(
                        "no measured calibration recorded — re-run with "
                        "--run-cfg phases=true phases_measure=30 for "
                        "measured ms/tick per phase",
                        file=out,
                    )
        if getattr(args, "compare", ""):
            with open(args.compare) as f:
                # BENCH_rNN.json files are one JSON object per line
                # (possibly with comment noise) — take the LAST line
                # that parses (the newest round, matching the bench
                # tail unwrapping in sim/perf.py); a whole-file JSON
                # document also parses
                text = f.read()
            try:
                baseline = json.loads(text)
            except ValueError:
                baseline = None
                for line in reversed(text.splitlines()):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        baseline = json.loads(line)
                        break
                    except ValueError:
                        continue
                if baseline is None:
                    raise ValueError(
                        f"{args.compare} holds no parseable JSON"
                    ) from None
            # with --json, stdout is the machine-readable payload — the
            # human-facing delta lines go to stderr so `| jq` keeps working
            out = sys.stderr if getattr(args, "json", False) else sys.stdout
            label = os.path.basename(args.compare)
            print(f"-- vs {label} --", file=out)
            for line in perf_compare(data, baseline, label=label):
                print(line, file=out)
        return 0
    finally:
        engine.stop()


def register_trace(sub) -> None:
    p = sub.add_parser(
        "trace",
        help="show a task's flight-recorder events (per-instance "
        "message-lifecycle timeline — docs/OBSERVABILITY.md); enable "
        "recording with [global.run.trace] / [groups.run.trace]",
    )
    p.add_argument("task", help="task id")
    p.add_argument(
        "-n",
        "--limit",
        type=int,
        default=0,
        help="print at most N events (default: all)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="dump the raw events as JSON lines (the sim_trace.jsonl "
        "rows) instead of the aligned timeline",
    )
    p.add_argument(
        "--lifecycle",
        action="store_true",
        help="render the task's causal lifecycle span tree "
        "(task_spans.jsonl: submit → queued → claim → execute → run "
        "spans) instead of the flight-recorder timeline; the sibling "
        "task_trace.json opens in Perfetto",
    )
    p.set_defaults(func=trace_cmd)


def _render_trace_event(ev: dict) -> str:
    kind = ev.get("event", "?")
    who = f"{ev.get('group', '?')}/i{ev.get('instance', '?')}"
    if kind == "status":
        what = f"status {ev.get('prev', '?')} → {ev.get('status', '?')}"
    elif kind == "signal":
        what = f"signal state {ev.get('state', '?')}"
    elif kind == "send":
        what = f"send → i{ev.get('dst', '?')} ({ev.get('fate', '?')})"
    elif kind == "deliver":
        what = f"deliver ← i{ev.get('src', '?')}"
    else:
        what = kind
    return f"t={ev.get('tick', '?'):>6}  {who:<16}  {what}"


def trace_cmd(args) -> int:
    import json

    from testground_tpu.client import RemoteEngine

    engine = _engine(args)
    try:
        if getattr(args, "lifecycle", False):
            return _trace_lifecycle(engine, args)
        if isinstance(engine, RemoteEngine):
            data = engine.task_trace(args.task, limit=args.limit)
            summary, events = data.get("trace", {}), data.get("events", [])
        else:
            t = engine.get_task(args.task)
            if t is None:
                raise KeyError(f"unknown task {args.task}")
            from testground_tpu.sim.trace import read_trace_events

            journal = (
                t.result.get("journal", {})
                if isinstance(t.result, dict)
                else {}
            )
            summary = journal.get("trace", {})
            events = read_trace_events(
                engine.env.dirs.outputs(), t.plan, t.id, limit=args.limit
            )
        if not summary and not events:
            # same message AND exit code with or without --json — a CI
            # pipe must not read an empty stream as a recorded trace
            print(
                f"no flight-recorder trace for task {args.task} — enable "
                "it with [global.run.trace] in the composition "
                "(docs/OBSERVABILITY.md)",
                file=sys.stderr,
            )
            return 1
        if isinstance(engine, RemoteEngine) and data.get("truncated"):
            print(
                f"warning: daemon capped the response at "
                f"{data.get('limit')} events — fetch the full stream "
                "via GET /artifact?name=sim_trace.jsonl",
                file=sys.stderr,
            )
        if getattr(args, "json", False):
            for ev in events:
                print(json.dumps(ev))
            return 0
        print(
            "trace: {e} event(s) from {i} instance(s)".format(
                e=summary.get("events", len(events)),
                i=summary.get("instances", "?"),
            )
            + (
                f" — {summary['events_file']} loads in Perfetto"
                if summary.get("events_file")
                else ""
            )
        )
        for ev in events:
            print(_render_trace_event(ev))
        return 0
    finally:
        engine.stop()


def _trace_lifecycle(engine, args) -> int:
    """``tg trace <task> --lifecycle``: load the archived lifecycle span
    tree (task_spans.jsonl — engine/tracetree.py) and render it as an
    indented tree; --json dumps the raw span rows. Works identically
    in-process (outputs dir) and remote (GET /artifact)."""
    import json

    from testground_tpu.client import RemoteEngine
    from testground_tpu.engine.tracetree import (
        TASK_SPANS_FILE,
        load_task_spans,
    )
    from testground_tpu.runners.pretty import render_lifecycle_tree

    if isinstance(engine, RemoteEngine):
        try:
            raw = engine.task_artifact(args.task, TASK_SPANS_FILE)
        except Exception as e:  # noqa: BLE001 — 404 → readable hint below
            raw = b""
            reason = f" ({e})"
        else:
            reason = ""
        spans = []
        for line in raw.decode(errors="replace").splitlines():
            try:
                spans.append(json.loads(line))
            except ValueError:
                continue
    else:
        t = engine.get_task(args.task)
        if t is None:
            raise KeyError(f"unknown task {args.task}")
        reason = ""
        spans = load_task_spans(
            os.path.join(
                engine.env.dirs.outputs(), t.plan, t.id, TASK_SPANS_FILE
            )
        )
    if not spans:
        # same message AND exit code with or without --json, like the
        # flight-recorder branch above
        print(
            f"no lifecycle trace for task {args.task}{reason} — the span "
            "tree is assembled when the task archives "
            "(docs/OBSERVABILITY.md 'Control plane')",
            file=sys.stderr,
        )
        return 1
    if getattr(args, "json", False):
        for s in spans:
            print(json.dumps(s))
        return 0
    print(render_lifecycle_tree(spans))
    return 0


# ------------------------------------------------------------------ watch


def _breach_line(row: dict, color: bool) -> str:
    """One highlighted SLO-breach line (the run health plane's live
    surface — docs/OBSERVABILITY.md "Run health plane")."""
    sev = row.get("severity", "warn")
    text = (
        f"!! SLO breach ({sev}) {row.get('rule', '?')}: "
        f"{row.get('metric', '?')} = {row.get('observed', '?')} "
        f"violates {row.get('op', '?')} {row.get('threshold', '?')} "
        f"at tick {row.get('tick', '?')}"
    )
    if color:
        code = "\033[31;1m" if sev == "fail" else "\033[33m"
        return f"{code}{text}\033[0m"
    return text


def _follow_stream(engine, task_id: str, families, out=None, follow=True) -> None:
    """Follow a task's observability stream and render one line per
    chunk (plus immediate SLO-breach lines) until the task finishes —
    the shared live view behind ``tg watch``, ``tg stats -f`` and
    ``tg perf -f``. ``families`` must include ``spans`` for the chunk
    clock unless ``perf`` rows (one per chunk) are streamed; with
    ``follow=False`` (``tg watch --no-follow``) one replay sweep of
    what exists is rendered instead of waiting for the task."""
    from testground_tpu.sim.netmatrix import NM_MSG_BYTES
    from testground_tpu.sim.perf import fmt_rate, num

    out = out or sys.stdout
    color = hasattr(out, "isatty") and out.isatty()
    use_spans_clock = "spans" in families
    header = (
        f"{'tick':>8}  {'wall':>8}  {'ticks/s':>9}  {'peer·t/s':>9}"
        f"  {'delivered':>9}  {'dropped':>8}  {'in-flight':>9}"
        f"  {'infl-KiB':>8}  breaches"
    )
    printed_header = False
    # telemetry deltas accumulated since the last chunk line
    acc = {"delivered": 0, "dropped": 0, "fault_dropped": 0}
    last_tele: dict = {}
    last_perf: dict = {}
    breaches = 0

    def chunk_line(tick, wall) -> str:
        d = acc["delivered"]
        x = acc["dropped"] + acc["fault_dropped"]
        acc.update(delivered=0, dropped=0, fault_dropped=0)
        # in-flight wire bytes: calendar occupancy × the fixed message
        # size (the traffic matrix's bytes accounting) — "?" when the
        # telemetry row has no finite depth yet
        depth = num(last_tele.get("cal_depth"))
        infl = f"{depth * NM_MSG_BYTES / 1024:.1f}" if depth is not None else "?"
        return (
            f"{tick:>8}  {wall:>8.2f}  "
            f"{fmt_rate(last_perf.get('ticks_per_sec')):>9}  "
            f"{fmt_rate(last_perf.get('peer_ticks_per_sec')):>9}  "
            f"{d:>9}  {x:>8}  "
            f"{last_tele.get('cal_depth', '?'):>9}  {infl:>8}  {breaches}"
        )

    for row in engine.stream_rows(
        task_id, follow=follow, families=families
    ):
        if not row:
            continue  # heartbeat / blank keepalive
        fam = row.get("stream")
        if fam == "telemetry":
            for k in acc:
                acc[k] += int(row.get(k, 0) or 0)
            last_tele = row
        elif fam == "perf":
            last_perf = row
            if not use_spans_clock:  # perf rows ARE the chunk clock
                if not printed_header:
                    printed_header = True
                    print(header, file=out)
                print(
                    chunk_line(
                        row.get("tick", "?"), row.get("wall_secs", 0.0)
                    ),
                    file=out,
                )
        elif fam == "slo":
            breaches += 1
            print(_breach_line(row, color), file=out)
        elif fam == "spans":
            ev = row.get("event") or {}
            span, typ = ev.get("span"), ev.get("type")
            if typ == "point" and span == "chunk" and use_spans_clock:
                if not printed_header:
                    printed_header = True
                    print(header, file=out)
                print(
                    chunk_line(
                        ev.get("ticks", "?"), ev.get("wall_secs", 0.0)
                    ),
                    file=out,
                )
            elif typ == "span_start" and span == "run":
                run = row.get("run", "")
                tag = f" [{run}]" if run and run != task_id else ""
                print(f"-- run started{tag} --", file=out)
            elif typ == "span_end" and span == "run":
                print(
                    "-- run finished: outcome "
                    f"{ev.get('outcome', ev.get('error', '?'))} --",
                    file=out,
                )
        try:
            out.flush()
        except OSError:
            pass


def register_watch(sub) -> None:
    p = sub.add_parser(
        "watch",
        help="live one-row-per-chunk view of a task (telemetry deltas, "
        "throughput, SLO-breach highlighting), across the queued→"
        "running→done lifecycle — docs/OBSERVABILITY.md 'Run health "
        "plane'",
    )
    p.add_argument("task", help="task id")
    p.add_argument(
        "--json",
        action="store_true",
        help="dump the raw ndjson rows (the GET /stream payload) "
        "instead of the rendered view",
    )
    p.add_argument(
        "--no-follow",
        action="store_true",
        help="replay what exists and exit instead of waiting for the "
        "task to finish",
    )
    p.set_defaults(func=watch_cmd)


def watch_cmd(args) -> int:
    import json

    engine = _engine(args)
    try:
        follow = not getattr(args, "no_follow", False)
        if getattr(args, "json", False):
            for row in engine.stream_rows(args.task, follow=follow):
                print(json.dumps(row))
                sys.stdout.flush()
        else:
            if follow:
                print(f"watching task {args.task} (ctrl-c to stop)")
            _follow_stream(
                engine,
                args.task,
                families=("telemetry", "perf", "slo", "spans"),
                follow=follow,
            )
            if follow:
                t = engine.get_task(args.task)
                if t is not None:
                    print(
                        f"task {args.task}: outcome {t.outcome().value}"
                    )
        return 0
    finally:
        engine.stop()


def register_netmap(sub) -> None:
    p = sub.add_parser(
        "netmap",
        help="show a task's group-to-group traffic matrix (sent heatmap, "
        "lossy pairs, link-shaping observables) and recommend a "
        "cross-traffic-minimizing group partition with --cut — "
        "docs/OBSERVABILITY.md 'Traffic matrix'; record with "
        "--run-cfg telemetry=true netmatrix=true",
    )
    p.add_argument("task", help="task id")
    p.add_argument(
        "--json",
        action="store_true",
        help="dump the raw sim.net_matrix journal block as JSON "
        "(machine-readable; the same shape as in GET /stats)",
    )
    p.add_argument(
        "-f",
        "--follow",
        action="store_true",
        help="follow the per-chunk matrix deltas live first (the "
        "netmatrix family of GET /stream), then print the final "
        "heatmap",
    )
    p.add_argument(
        "--cut",
        type=int,
        default=0,
        metavar="N",
        help="recommend a balanced N-shard group partition minimizing "
        "cross-cut traffic bytes (measured, not guessed — the "
        "instance-axis → mesh-axis placement advisor)",
    )
    p.set_defaults(func=netmap_cmd)


def netmap_cmd(args) -> int:
    import json

    from testground_tpu.client import RemoteEngine
    from testground_tpu.runners.pretty import (
        render_netmap,
        render_netmap_cut,
    )

    engine = _engine(args)
    try:
        as_json = bool(getattr(args, "json", False))
        # under --json every human-facing line goes to stderr — stdout
        # stays the machine-readable payload (the --json contract)
        hout = sys.stderr if as_json else sys.stdout
        if getattr(args, "follow", False):
            print(
                f"following task {args.task} traffic deltas "
                "(ctrl-c to stop)",
                file=hout,
            )
            for row in engine.stream_rows(
                args.task, follow=True, families=("netmatrix",)
            ):
                if not row or row.get("stream") != "netmatrix":
                    continue
                cells = row.get("cells") or []
                sent = sum(
                    int(c[2]) for c in cells if len(c) > 2
                )
                lost = sum(
                    int(c[5]) + int(c[6]) + int(c[7])
                    for c in cells
                    if len(c) > 7
                )
                line = (
                    f"tick {row.get('tick', '?'):>8}  "
                    f"{len(cells)} active pair(s)  sent {sent}"
                )
                if lost:
                    line += f"  LOST {lost}"
                print(line, file=hout)
                try:
                    hout.flush()
                except OSError:
                    pass
        if isinstance(engine, RemoteEngine):
            data = engine.task_stats(args.task)
        else:
            t = engine.get_task(args.task)
            if t is None:
                raise KeyError(f"unknown task {args.task}")
            data = t.stats_payload()
        block = (data.get("sim") or {}).get("net_matrix") or {}
        if as_json:
            print(json.dumps(block, indent=2, sort_keys=True))
        if not block:
            print(
                "no traffic matrix recorded for this task — run with "
                "--run-cfg telemetry=true netmatrix=true (cohorts and "
                "disable_metrics run matrix-free)",
                file=hout,
            )
            return 1
        if not as_json:
            ident = (
                f"{data.get('plan', '?')}:{data.get('case', '?')}"
                f"  ({args.task})"
            )
            print(render_netmap(block, ident))
        if getattr(args, "cut", 0):
            import numpy as np

            from testground_tpu.sim.netmatrix import (
                cut_advisor,
                matrix_bytes,
            )

            mat = np.asarray(block.get("matrix") or [], np.int64)
            rec = cut_advisor(
                matrix_bytes(mat),
                int(args.cut),
                labels=block.get("labels") or None,
            )
            print("", file=hout)
            print(render_netmap_cut(rec, int(args.cut)), file=hout)
        return 0
    finally:
        engine.stop()


def register_diff(sub) -> None:
    p = sub.add_parser(
        "diff",
        help="differential run analysis of two tasks: deterministic "
        "counters compared exactly (a mismatch between identically-"
        "seeded runs is a correctness finding), throughput judged "
        "from per-chunk samples with noise-robust statistics "
        "(median ratio + Mann-Whitney U) — docs/OBSERVABILITY.md "
        "'Run diff'. Exit 1 on correctness findings.",
    )
    p.add_argument("task_a", help="baseline task id (A)")
    p.add_argument("task_b", help="candidate task id (B)")
    p.add_argument(
        "--planes",
        default="",
        metavar="P1,P2",
        help="comma-separated plane subset "
        "(counters,perf,latency,phases,slo,netmatrix; default all)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="dump the full RunDiff document as JSON (machine-readable; "
        "the same shape as GET /diff)",
    )
    p.set_defaults(func=diff_cmd)


def diff_cmd(args) -> int:
    import json

    from testground_tpu.analysis.diff import validate_planes
    from testground_tpu.runners.pretty import render_run_diff

    # validate the plane selection client-side so an unknown plane is
    # the same usage error (exit 2) in-process and remote — a daemon
    # 400 would otherwise surface as a generic DaemonError (exit 1)
    try:
        validate_planes(args.planes or None)
    except ValueError as e:
        print(f"tg diff: {e}", file=sys.stderr)
        return 2
    engine = _engine(args)
    try:
        # in-process and remote engines expose the same diff_tasks verb
        # (the document is always built by Engine.diff_tasks — ONE
        # comparison codepath, daemon-side when remote)
        try:
            doc = engine.diff_tasks(
                args.task_a, args.task_b, planes=args.planes or None
            )
        except ValueError as e:  # unknown plane — usage error
            print(f"tg diff: {e}", file=sys.stderr)
            return 2
        if getattr(args, "json", False):
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(render_run_diff(doc))
        # correctness findings gate (exit 1); perf verdicts inform but
        # never fail `tg diff` itself — the bench sentinel gates perf
        return 1 if doc.get("findings") else 0
    finally:
        engine.stop()


def register_top(sub) -> None:
    p = sub.add_parser(
        "top",
        help="live fleet view: worker occupancy, queue depth, per-state "
        "task counts over the FULL store, and one row per queued/"
        "running task (GET /fleet — docs/OBSERVABILITY.md 'Control "
        "plane')",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="dump the raw fleet payload as ndjson (one object per "
        "refresh) instead of the rendered view",
    )
    p.add_argument(
        "--no-follow",
        action="store_true",
        help="print one snapshot and exit instead of refreshing",
    )
    p.add_argument(
        "-i",
        "--interval",
        type=float,
        default=2.0,
        help="refresh interval in seconds (default: 2)",
    )
    p.set_defaults(func=top_cmd)


def top_cmd(args) -> int:
    import json

    from testground_tpu.runners.pretty import render_fleet

    engine = _engine(args)
    try:
        follow = not getattr(args, "no_follow", False)
        interval = max(0.1, getattr(args, "interval", 2.0))
        clear = follow and sys.stdout.isatty() and not args.json
        while True:
            payload = engine.fleet_payload()
            if getattr(args, "json", False):
                print(json.dumps(payload, sort_keys=True))
            else:
                if clear:
                    # home + clear-to-end, not full clear: no flicker
                    sys.stdout.write("\033[H\033[J")
                print(render_fleet(payload))
            sys.stdout.flush()
            if not follow:
                return 0
            time.sleep(interval)
    finally:
        engine.stop()


def register_status(sub) -> None:
    p = sub.add_parser("status", help="get task status")
    p.add_argument("-t", "--task", required=True, help="task id")
    p.add_argument("--extended", action="store_true")
    p.add_argument(
        "--telemetry",
        action="store_true",
        help="also render the sim telemetry summary table",
    )
    p.set_defaults(func=status_cmd)


def status_cmd(args) -> int:
    engine = _engine(args)
    try:
        t = engine.get_task(args.task)
        if t is None:
            raise KeyError(f"unknown task {args.task}")
        print(f"ID:      {t.id}")
        print(f"Name:    {t.name()}")
        print(f"Type:    {t.type.value}")
        print(f"State:   {t.state().state.value}")
        print(f"Outcome: {t.outcome().value}")
        print(f"Queued:  {t.queued_secs():.1f}s")
        cb = t.created_by
        if cb.user or cb.repo or cb.branch or cb.commit:
            parts = [cb.user or "-"]
            if cb.repo or cb.branch:
                parts.append(f"{cb.repo}@{cb.branch}" if cb.branch else cb.repo)
            if cb.commit:
                parts.append(cb.commit[:12])
            print(f"By:      {' '.join(parts)}")
        if t.error:
            print(f"Error:   {t.error}")
        mj = (
            t.result.get("journal", {}).get("metrics")
            if isinstance(t.result, dict)
            else None
        )
        if mj:
            print("Metrics:")
            for gid, names in mj.items():
                for name, agg in names.items():
                    if agg.get("count"):
                        print(
                            f"  {gid}/{name}: mean={agg['mean']:.3f} "
                            f"min={agg['min']:.3f} max={agg['max']:.3f} "
                            f"n={agg['count']}"
                        )
        if getattr(args, "telemetry", False):
            from testground_tpu.runners.pretty import (
                render_telemetry_summary,
            )

            print("Telemetry:")
            summary = render_telemetry_summary(t.stats_payload())
            print("\n".join(f"  {line}" for line in summary.splitlines()))
        if args.extended:
            import json

            print(json.dumps(t.to_dict(), indent=2))
        return 0
    finally:
        engine.stop()


def register_logs(sub) -> None:
    p = sub.add_parser("logs", help="print task logs")
    p.add_argument("-t", "--task", required=True)
    p.add_argument("-f", "--follow", action="store_true")
    p.set_defaults(func=logs_cmd)


def logs_cmd(args) -> int:
    engine = _engine(args)
    try:
        for line in engine.logs(args.task, follow=args.follow):
            _print_chunk_line(line)
        return 0
    finally:
        engine.stop()


# ---------------------------------------------------------------- collect


def register_collect(sub) -> None:
    p = sub.add_parser("collect", help="collect run outputs into a tgz")
    p.add_argument("run_id")
    p.add_argument("--runner", default="local:exec")
    p.add_argument("-o", "--output", default="")
    p.set_defaults(func=collect_cmd)


def collect_cmd(args) -> int:
    engine = _engine(args)
    try:
        dest = args.output or f"{args.run_id}.tgz"
        _collect_to_file(engine, args.runner, args.run_id, dest)
        return 0
    finally:
        engine.stop()


# ------------------------------------------- healthcheck / terminate / misc


def register_healthcheck(sub) -> None:
    p = sub.add_parser("healthcheck", help="check a runner's environment")
    p.add_argument("--runner", required=True)
    p.add_argument("--fix", action="store_true")
    p.set_defaults(func=healthcheck_cmd)


def healthcheck_cmd(args) -> int:
    engine = _engine(args)
    try:
        ow = OutputWriter(sink=None, echo=sys.stdout)
        report = engine.do_healthcheck(args.runner, args.fix, ow)
        print(report)
        return 0 if report.ok() else 1
    finally:
        engine.stop()


def register_preempt(sub) -> None:
    p = sub.add_parser(
        "preempt",
        help="checkpoint-and-requeue a running task at its next chunk "
        "boundary (the fleet controller's live-migration verb — "
        "docs/FLEET.md); a checkpointed run resumes bit-identically "
        "when re-claimed",
    )
    p.add_argument("task", help="task id")
    p.set_defaults(func=preempt_cmd)


def preempt_cmd(args) -> int:
    engine = _engine(args)
    try:
        res = engine.preempt(args.task)
        if not res.get("ok"):
            print(
                f"preempt refused: {res.get('error', 'unknown')}",
                file=sys.stderr,
            )
            return 1
        if res.get("queued"):
            print(f"task {args.task} is still queued — nothing to preempt")
        else:
            print(
                f"task {args.task} will checkpoint and requeue at its "
                "next chunk boundary"
            )
        return 0
    finally:
        engine.stop()


def register_terminate(sub) -> None:
    p = sub.add_parser(
        "terminate",
        help="terminate all jobs and supporting processes of a runner or builder",
    )
    p.add_argument("--runner", default="")
    p.add_argument("--builder", default="")
    p.add_argument(
        "--drain",
        action="store_true",
        help="gracefully drain the daemon instead: stop claiming, "
        "checkpoint + requeue running runs (they resume on restart), "
        "cancel builds, then shut the daemon down (docs/FLEET.md)",
    )
    p.set_defaults(func=terminate_cmd)


def terminate_cmd(args) -> int:
    if getattr(args, "drain", False):
        if args.runner or args.builder:
            print(
                "--drain drains the whole daemon; it takes no "
                "--runner/--builder",
                file=sys.stderr,
            )
            return 1
        engine = _engine(args)
        try:
            res = engine.drain()
            print(
                "daemon drained: {drained} worker(s) idle, "
                "{preempted} task(s) preempted, "
                "{canceled} build(s) canceled".format(
                    drained=res.get("drained"),
                    preempted=res.get("preempted", 0),
                    canceled=res.get("canceled", 0),
                )
            )
            return 0 if res.get("drained") else 1
        finally:
            engine.stop()
    # one component at a time, like the reference (terminate.go:38-45)
    if bool(args.runner) == bool(args.builder):
        print(
            "specify exactly one of --runner or --builder", file=sys.stderr
        )
        return 1
    engine = _engine(args)
    try:
        ow = OutputWriter(sink=None, echo=sys.stdout)
        if args.runner:
            engine.do_terminate(args.runner, ow, ctype="runner")
        else:
            engine.do_terminate(args.builder, ow, ctype="builder")
        return 0
    finally:
        engine.stop()


def register_daemon(sub) -> None:
    p = sub.add_parser("daemon", help="run the testground daemon")
    p.add_argument(
        "--listen",
        default="",
        help="listen address host:port (default: .env.toml daemon.listen "
        "or localhost:8042)",
    )
    p.set_defaults(func=daemon_cmd)


def daemon_cmd(args) -> int:
    from testground_tpu.daemon.server import serve

    return serve(listen=args.listen)


def register_sync_service(sub) -> None:
    p = sub.add_parser(
        "sync-service",
        help="run a standalone network-reachable sync service (the "
        "shared coordination plane of a cross-host local:exec run — "
        "docs/CROSSHOST.md); prints 'LISTENING <host> <port>' once "
        "bound and serves until SIGTERM",
    )
    p.add_argument(
        "--host",
        default="127.0.0.1",
        help="bind address (0.0.0.0 serves other hosts; default loopback)",
    )
    p.add_argument(
        "--port", type=int, default=0, help="bind port (0 = ephemeral)"
    )
    p.add_argument(
        "--backend",
        choices=("auto", "python", "native"),
        default="auto",
        help="native C++ event-loop server when a toolchain exists "
        "(auto), or force one implementation",
    )
    p.add_argument(
        "--idle-timeout",
        type=float,
        default=30.0,
        help="evict connections silent for this many seconds "
        "(heartbeating clients are never idle; 0 disables)",
    )
    p.add_argument(
        "--evict-grace",
        type=float,
        default=2.0,
        help="window an abnormally-disconnected instance has to "
        "reconnect before its eviction event is published",
    )
    p.add_argument(
        "--shards",
        type=int,
        default=0,
        help="event-loop shards (0 = backend auto: native picks "
        "min(4, cores), python runs one loop — docs/CROSSHOST.md "
        "'Server architecture')",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=-1,
        help="also serve a Prometheus text exposition of the tg_sync_* "
        "family at http://127.0.0.1:<port>/metrics (0 = ephemeral, "
        "printed; default off) — docs/OBSERVABILITY.md 'Sync plane'",
    )
    p.add_argument(
        "--stats-interval",
        type=float,
        default=60.0,
        help="log a one-line stats heartbeat (conns/waiters/subs/ops-"
        "per-sec) to stderr every N seconds so a detached service is "
        "debuggable from its log alone (0 disables; default 60)",
    )
    p.set_defaults(func=sync_service_cmd)


def sync_service_cmd(args) -> int:
    import threading

    from testground_tpu.sync.boot import boot_sync_service
    from testground_tpu.sync.server import serve_until_signal
    from testground_tpu.sync.stats import (
        SyncMetricsExporter,
        run_stats_heartbeat,
    )

    try:
        svc = boot_sync_service(
            mode=args.backend,
            host=args.host,
            port=args.port,
            idle_timeout=args.idle_timeout,
            evict_grace=args.evict_grace,
            bin_dir=os.path.join(EnvConfig.load().dirs.work(), "bin"),
            log=lambda msg: print(msg, file=sys.stderr),
            shards=args.shards,
        )
    except Exception as e:  # noqa: BLE001 — boot failures exit readably
        print(f"sync-service: {e}", file=sys.stderr)
        return 1
    # the service binds args.host, but the sidecars dial it locally —
    # a wildcard bind is reachable on loopback
    local = ("127.0.0.1" if args.host in ("0.0.0.0", "") else args.host,
             svc.address[1])
    exporter = None
    if args.metrics_port >= 0:
        try:
            exporter = SyncMetricsExporter(
                local, port=args.metrics_port
            ).start()
            print(
                f"METRICS http://127.0.0.1:{exporter.port}/metrics",
                flush=True,
            )
        except OSError as e:
            print(f"sync-service: metrics port: {e}", file=sys.stderr)
            svc.stop()
            return 1
    hb_stop = threading.Event()
    if args.stats_interval > 0:
        threading.Thread(
            target=run_stats_heartbeat,
            args=(local, args.stats_interval, hb_stop),
            daemon=True,
            name="tg-sync-heartbeat",
        ).start()
    try:
        return serve_until_signal(svc)
    finally:
        hb_stop.set()
        if exporter is not None:
            exporter.stop()


def register_sync_stats(sub) -> None:
    p = sub.add_parser(
        "sync-stats",
        help="query a live sync service's stats plane: op counters + "
        "service-time percentiles, barrier fan-in timelines, pubsub "
        "depth, connection churn (docs/OBSERVABILITY.md 'Sync plane'); "
        "works against either backend, v1 or v2",
    )
    p.add_argument(
        "address",
        help="host:port of a running sync service (`tg sync-service` "
        "prints it as LISTENING; a local:exec run's service address is "
        "in the instances' SYNC_SERVICE_HOST/PORT env)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="dump the raw sync_stats reply as JSON (machine-readable; "
        "the wire payload minus the correlation id)",
    )
    p.add_argument(
        "--timeout",
        type=float,
        default=5.0,
        help="connect + reply timeout in seconds",
    )
    p.add_argument(
        "--watch",
        type=float,
        default=0.0,
        metavar="N",
        help="refresh every N seconds (an operator's live view of a "
        "ramp without Prometheus; each refresh is the exporter's same "
        "one-shot fetch; Ctrl-C exits; under --json one payload line "
        "per refresh)",
    )
    p.add_argument(
        "--watch-count",
        type=int,
        default=0,
        help="stop after this many --watch refreshes (0 = until "
        "Ctrl-C; for scripting)",
    )
    p.set_defaults(func=sync_stats_cmd)


def sync_stats_cmd(args) -> int:
    import json
    import time

    from testground_tpu.runners.pretty import render_sync_stats
    from testground_tpu.sync.stats import fetch_sync_stats

    host, _, port = args.address.rpartition(":")
    if not host or not port.isdigit():
        print(
            f"sync-stats: expected <host>:<port>, got {args.address!r}",
            file=sys.stderr,
        )
        return 2
    watch = max(0.0, getattr(args, "watch", 0.0) or 0.0)
    as_json = getattr(args, "json", False)
    shown = 0
    while True:
        try:
            stats = fetch_sync_stats(host, int(port), timeout=args.timeout)
        except (OSError, ValueError) as e:
            print(
                f"sync-stats: sync service at {args.address} "
                f"unreachable: {e}",
                file=sys.stderr,
            )
            # one-shot: unreachable is an error; watching: a live ramp's
            # service may restart — keep watching unless it never answered
            if not watch or shown == 0:
                return 1
        else:
            if as_json:
                print(
                    json.dumps(
                        stats,
                        indent=None if watch else 2,
                        sort_keys=True,
                    ),
                    flush=True,
                )
            else:
                if watch and shown and sys.stdout.isatty():
                    print("\x1b[2J\x1b[H", end="")  # clear between frames
                header = (
                    f"--- {args.address} @ {time.strftime('%H:%M:%S')} "
                    f"(refresh {watch:g}s, Ctrl-C to exit) ---"
                )
                if watch:
                    print(header)
                print(render_sync_stats(stats), flush=True)
            shown += 1
        if not watch:
            return 0
        if args.watch_count and shown >= args.watch_count:
            return 0
        try:
            time.sleep(watch)
        except KeyboardInterrupt:
            return 0


def register_sim_worker(sub) -> None:
    p = sub.add_parser(
        "sim-worker",
        help="join a multi-host sim:jax cohort as a follower process "
        "(the cluster-node analog; the leader is the engine whose "
        "runner config sets coordinator_address)",
    )
    p.add_argument(
        "--coordinator",
        required=True,
        help="jax.distributed coordinator host:port (process 0)",
    )
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument(
        "--plans",
        default="",
        help="plans dir holding the same plan sources as the leader "
        "(default: $TESTGROUND_HOME/plans)",
    )
    p.add_argument(
        "--once", action="store_true", help="exit after one job (tests)"
    )
    p.add_argument(
        "--connect-attempts",
        type=int,
        default=3,
        help="bounded retries joining the coordinator (a worker "
        "commonly races the leader's startup across hosts)",
    )
    p.add_argument(
        "--connect-timeout",
        type=float,
        default=60.0,
        help="per-attempt coordinator join timeout in seconds",
    )
    p.set_defaults(func=sim_worker_cmd)


def sim_worker_cmd(args) -> int:
    from testground_tpu.config import EnvConfig
    from testground_tpu.sim.executor import run_sim_worker

    plans_dir = args.plans or EnvConfig.load().dirs.plans()
    # the wrapper turns a dead leader into a one-line clean exit
    # instead of a distributed-runtime LOG(FATAL) (sim/executor.py)
    return run_sim_worker(
        args.coordinator,
        args.num_processes,
        args.process_id,
        plans_dir,
        once=args.once,
        connect_attempts=args.connect_attempts,
        connect_timeout_secs=args.connect_timeout,
    )


def register_version(sub) -> None:
    sub.add_parser("version", help="print version")
