"""Builder plugin registry. Twin of the reference's ``pkg/build``.

Builders registered here (mirroring ``pkg/engine/engine.go:25-30``):
- ``exec:py`` — resolves a Python plan source dir into a runnable module
  (the analog of ``exec:go``'s host executable).
- ``exec:bin`` — any-language plans: runs the plan's ``build.sh`` and
  ships its ``run`` executable (the ``docker:generic`` analog behind the
  Rust/JS plans).
- ``sim:plan`` — resolves a plan's sim program for the ``sim:jax`` runner.
"""

from .base import Builder

__all__ = ["Builder"]
