"""``sim:plan`` builder: resolve a plan's simulation program for the
``sim:jax`` runner.

The sim runner executes plans as traceable JAX state machines, not
processes, so the "artifact" is the plan source dir itself (validated to
expose ``sim_plans`` — see ``testground_tpu.sim.api``). Snapshotting is
shared with ``exec:py`` so queued runs are immune to source edits.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter

from .base import Builder, Precompiler, purge_snapshots

__all__ = ["SimPlanBuilder"]


def _source_digest(artifact_dir: str) -> str:
    """Digest of the snapshot's Python sources (path + contents) — the
    part of the precompile BuildKey that invalidates on plan edits."""
    h = hashlib.sha256()
    for root, dirs, files in os.walk(artifact_dir):
        dirs.sort()
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            h.update(os.path.relpath(path, artifact_dir).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


class SimPlanBuilder(Builder, Precompiler):
    def id(self) -> str:
        return "sim:plan"

    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput:
        src = inp.unpacked_plan_dir
        if not src or not os.path.isdir(src):
            raise ValueError(f"plan sources not found: {src!r}")
        if not (
            os.path.isfile(os.path.join(src, "sim.py"))
            or os.path.isfile(os.path.join(src, "main.py"))
        ):
            raise ValueError(
                f"plan has neither sim.py nor main.py entry point: {src}"
            )
        work = inp.env.dirs.work()
        dest = os.path.join(work, f"sim-plan--{inp.test_plan}-{inp.build_id}")
        if os.path.exists(dest):
            shutil.rmtree(dest)
        shutil.copytree(
            src,
            dest,
            ignore=shutil.ignore_patterns(
                "__pycache__", "*.pyc", ".git", "_compositions"
            ),
        )
        ow.infof("sim:plan built %s -> %s", inp.test_plan, dest)
        return BuildOutput(builder_id=self.id(), artifact_path=dest)

    def purge(self, testplan: str, ow: OutputWriter, env=None) -> None:
        removed = purge_snapshots("sim-plan", testplan, ow, env)
        ow.infof("sim:plan purge: removed %d snapshot(s)", removed)

    # ------------------------------------------------------- build = compile

    def precompile(self, comp, manifest, env, ow, cancel) -> None:
        """Trace + compile the composition's sim programs into the
        persistent XLA cache — the build-time analog of the reference's
        image build (``pkg/build/docker_go.go:266-283``): expensive
        artifact production happens in the *build* task, deduped by a
        BuildKey, and runs of the same composition become cache reads.

        Uses the EXACT code path the sim:jax executor uses (same testcase
        specialization, same mesh construction, same program options) so
        the traced HLO — and therefore the XLA cache key — is identical.
        The chunk program is compiled AOT (``lower().compile()``) without
        executing a tick; only ``init_carry`` executes, to produce a carry
        whose shardings match what the run will feed the chunk."""
        from testground_tpu.api import prepare_for_run
        from testground_tpu.config import CoalescedConfig
        from testground_tpu.utils.compile_cache import enable_compile_cache

        cache_dir = enable_compile_cache(env.dirs.home if env else None)
        if cache_dir is None:
            ow.infof("sim:plan precompile skipped: compile cache disabled")
            return
        if not comp.global_.case:
            # case-less `tg build single <plan>`: there is no composition
            # to resolve a program from — snapshot-only build, like the
            # reference building a plan image without a run
            ow.infof(
                "sim:plan precompile skipped: no test case on this build"
            )
            return
        from testground_tpu.sim.executor import (
            SimJaxConfig,
            _make_mesh,
            _parse_hosts,
            _precheck_device_memory,
            fault_specs_of,
            load_and_specialize,
            make_sim_program,
            resolve_transport,
            slo_specs_of,
            trace_specs_of,
        )
        from testground_tpu.sim.faults import build_fault_schedule
        from testground_tpu.sim.trace import build_trace_plan

        artifacts = {g.id: g.run.artifact for g in comp.groups}
        # prepare BEFORE coalescing the runner config: prepare_for_run is
        # what fills manifest runner-config defaults into run_config, and
        # do_run coalesces after it — a different order here would compile
        # a different program than the run executes (wasting the cache and
        # poisoning the BuildKey marker)
        comp = prepare_for_run(comp, manifest)
        cfg = (
            CoalescedConfig()
            .append(env.runners.get("sim:jax") if env else None)
            .append(comp.global_.run_config)
            .coalesce_into(SimJaxConfig)
        )
        hosts = _parse_hosts(getattr(cfg, "additional_hosts", None))
        # mirror the executor's telemetry gate EXACTLY (executor
        # telemetry_on): the composition's disable_metrics opt-out and
        # multi-host cohorts both force telemetry off at run time, so a
        # build under either must precompile the telemetry-OFF variant
        # or it warms a program the run never traces (and the run pays
        # the full XLA compile)
        telemetry = (
            bool(getattr(cfg, "telemetry", False))
            and not comp.global_.disable_metrics
            and not getattr(cfg, "coordinator_address", "")
        )
        # transport gate mirrors the executor (resolve_transport is the
        # shared gate): a mesh forces xla, so the build must precompile
        # the variant the run will actually trace. A cohort resolves
        # against the GLOBAL mesh at run time (always multi-device), so
        # coordinator_address forces xla here too — like the telemetry
        # gate above, or the build warms a program the run never traces
        transport = resolve_transport(cfg, _make_mesh(cfg.shard))
        if getattr(cfg, "coordinator_address", ""):
            transport = "xla"
        digests = {
            path: _source_digest(path) for path in set(artifacts.values())
        }

        import jax

        # one compile per distinct program shape across [[runs]] — the
        # BuildKey analog: the key is (plan source digest, case, group
        # layout/params, every program-shaping option, backend + topology +
        # jax version); an edited plan re-keys via the source digest
        seen: set[str] = set()
        for run in comp.runs:
            # fault schedules are program-shaping (the event tensors bake
            # into the traced tick), so they join the BuildKey and the
            # precompiled program — mirroring the executor exactly
            run_fault_specs = fault_specs_of(
                run.groups,
                comp.global_.run.faults
                if comp.global_.run is not None
                else None,
            )
            # the flight-recorder plan is program-shaping too, and its
            # gate mirrors the executor's: disable_metrics and cohort
            # configs run trace-free, so a build under either must
            # precompile the no-trace variant
            run_trace_specs = (
                trace_specs_of(
                    run.groups,
                    comp.global_.run.trace
                    if comp.global_.run is not None
                    else None,
                )
                if not comp.global_.disable_metrics
                and not getattr(cfg, "coordinator_address", "")
                else {}
            )
            # SLO rules never shape the program (host-side evaluation),
            # but they are part of the run declaration the marker
            # records — same gating as the telemetry plane they ride
            run_slo_specs = (
                slo_specs_of(
                    run.groups,
                    comp.global_.run.slo
                    if comp.global_.run is not None
                    else None,
                )
                if telemetry
                else {}
            )
            spec = {
                "sources": digests[
                    artifacts[
                        comp.get_group(
                            run.groups[0].effective_group_id()
                        ).id
                    ]
                ],
                "plan": comp.global_.plan,
                "case": comp.global_.case,
                "groups": [
                    {
                        "id": rg.id,
                        "instances": rg.calculated_instance_count,
                        "parameters": dict(rg.test_params),
                    }
                    for rg in run.groups
                ],
                "tick_ms": cfg.tick_ms,
                "chunk": cfg.chunk,
                "seed": cfg.seed,
                "shard": cfg.shard,
                "validate": bool(getattr(cfg, "validate", False)),
                "telemetry": telemetry,
                "transport": transport,
                "faults": run_fault_specs,
                "trace": run_trace_specs,
                "slo": run_slo_specs,
                "hosts": list(hosts),
                "backend": jax.default_backend(),
                "devices": jax.device_count(),
                "jax": jax.__version__,
            }
            key = hashlib.sha256(
                json.dumps(spec, sort_keys=True).encode()
            ).hexdigest()[:32]
            if key in seen:
                continue
            seen.add(key)
            marker = os.path.join(cache_dir, "precompiled", f"{key}.json")
            if os.path.exists(marker):
                ow.infof(
                    "sim:plan precompile: cache hit for run %s (key %s)",
                    run.id,
                    key,
                )
                continue
            if cancel.is_set():
                return
            t0 = time.perf_counter()
            first = comp.get_group(run.groups[0].effective_group_id())
            from testground_tpu.api import RunGroup

            # same load/specialize/construct helpers as the executor and
            # the sim-worker — the single-code-path guarantee behind the
            # "identical HLO" claim above
            testcase, groups = load_and_specialize(
                artifacts[first.id],
                comp.global_.case,
                [
                    RunGroup(
                        id=rg.id,
                        instances=rg.calculated_instance_count,
                        parameters=dict(rg.test_params),
                    )
                    for rg in run.groups
                ],
                cfg.tick_ms,
            )
            mesh = _make_mesh(cfg.shard)
            prog = make_sim_program(
                testcase,
                groups,
                test_plan=comp.global_.plan,
                test_case=comp.global_.case,
                test_run="build",
                tick_ms=cfg.tick_ms,
                mesh=mesh,
                chunk=cfg.chunk,
                hosts=hosts,
                validate=bool(getattr(cfg, "validate", False)),
                telemetry=telemetry,
                faults=build_fault_schedule(
                    groups, run_fault_specs, cfg.tick_ms
                ),
                trace=build_trace_plan(groups, run_trace_specs),
                transport=transport,
            )
            # same capacity precheck as the run: an oversized composition
            # must refuse readably at BUILD time too, not die as an XLA
            # OOM inside the precompile's chunk execution
            _precheck_device_memory(prog, cfg, mesh, ow)
            # Walk the exact compile sequence the executor walks. Under a
            # mesh the chunk compiles TWICE at runtime: the first call
            # sees init's output shardings, but XLA assigns the per-group
            # state leaves its own (GSPMD) shardings, so the second call
            # retraces at that fixed point (one iteration — verified; see
            # SimProgram.run). Execute one chunk here so both variants
            # land in the cache; the run then compiles nothing.
            carry = jax.jit(lambda: prog.init_carry(cfg.seed))()  # noqa: B023
            fn = prog.compiled_chunk()
            # compiles variant 1 + runs one chunk (telemetry programs
            # return (carry, done, block) — take the carry positionally)
            carry = fn(carry)[0]
            # fixed-point variant, no execution — timed in its
            # lower-vs-compile halves and harvested for cost/memory
            # analysis, so the BuildKey marker records the performance
            # ledger's compile block (docs/OBSERVABILITY.md)
            from testground_tpu.sim.perf import (
                compile_analysis,
                timed_lower_compile,
            )

            lower_secs, xla_secs, compiled = timed_lower_compile(fn, carry)
            perf = {
                "lower_secs": round(lower_secs, 6),
                "compile_secs": round(xla_secs, 6),
                **compile_analysis(compiled),
            }
            del carry, compiled
            secs = time.perf_counter() - t0
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w") as f:
                json.dump(
                    {**spec, "compile_secs": round(secs, 3), "perf": perf},
                    f,
                )
            ow.infof(
                "sim:plan precompiled run %s into %s in %.1fs (key %s)",
                run.id,
                cache_dir,
                secs,
                key,
            )
