"""``sim:plan`` builder: resolve a plan's simulation program for the
``sim:jax`` runner.

The sim runner executes plans as traceable JAX state machines, not
processes, so the "artifact" is the plan source dir itself (validated to
expose ``sim_plans`` — see ``testground_tpu.sim.api``). Snapshotting is
shared with ``exec:py`` so queued runs are immune to source edits.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter

from .base import Builder, Precompiler, purge_snapshots

__all__ = ["SimPlanBuilder"]


def _source_digest(artifact_dir: str) -> str:
    """Digest of the snapshot's Python sources (path + contents) — the
    part of the precompile BuildKey that invalidates on plan edits."""
    h = hashlib.sha256()
    for root, dirs, files in os.walk(artifact_dir):
        dirs.sort()
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            h.update(os.path.relpath(path, artifact_dir).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


class SimPlanBuilder(Builder, Precompiler):
    def id(self) -> str:
        return "sim:plan"

    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput:
        src = inp.unpacked_plan_dir
        if not src or not os.path.isdir(src):
            raise ValueError(f"plan sources not found: {src!r}")
        if not (
            os.path.isfile(os.path.join(src, "sim.py"))
            or os.path.isfile(os.path.join(src, "main.py"))
        ):
            raise ValueError(
                f"plan has neither sim.py nor main.py entry point: {src}"
            )
        work = inp.env.dirs.work()
        dest = os.path.join(work, f"sim-plan--{inp.test_plan}-{inp.build_id}")
        if os.path.exists(dest):
            shutil.rmtree(dest)
        shutil.copytree(
            src,
            dest,
            ignore=shutil.ignore_patterns(
                "__pycache__", "*.pyc", ".git", "_compositions"
            ),
        )
        ow.infof("sim:plan built %s -> %s", inp.test_plan, dest)
        return BuildOutput(builder_id=self.id(), artifact_path=dest)

    def purge(self, testplan: str, ow: OutputWriter, env=None) -> None:
        removed = purge_snapshots("sim-plan", testplan, ow, env)
        ow.infof("sim:plan purge: removed %d snapshot(s)", removed)

    # ------------------------------------------------------- build = compile

    def precompile(self, comp, manifest, env, ow, cancel) -> None:
        """Trace + compile the composition's sim programs into the
        persistent XLA cache — the build-time analog of the reference's
        image build (``pkg/build/docker_go.go:266-283``): expensive
        artifact production happens in the *build* task, deduped by a
        BuildKey, and runs of the same composition become cache reads.

        Uses the EXACT code path the sim:jax executor uses (same testcase
        specialization, same mesh construction, same program options) so
        the traced HLO — and therefore the XLA cache key — is identical.
        The chunk program is compiled AOT (``lower().compile()``) without
        executing a tick; only ``init_carry`` executes, to produce a carry
        whose shardings match what the run will feed the chunk."""
        from testground_tpu.api import prepare_for_run
        from testground_tpu.config import CoalescedConfig
        from testground_tpu.utils.compile_cache import enable_compile_cache

        cache_dir = enable_compile_cache(env.dirs.home if env else None)
        if cache_dir is None:
            ow.infof("sim:plan precompile skipped: compile cache disabled")
            return
        if not comp.global_.case:
            # case-less `tg build single <plan>`: there is no composition
            # to resolve a program from — snapshot-only build, like the
            # reference building a plan image without a run
            ow.infof(
                "sim:plan precompile skipped: no test case on this build"
            )
            return
        # static-analysis pass (sim/check.py): the precompile evaluates
        # the SAME rules `tg check` and the executor enforce, so every
        # admission refusal the run would hit surfaces in the build log
        # up front. Warn-only by design: the executor's refusal stays
        # the authoritative failure, and the snapshot artifact above is
        # already valid whatever the knobs say.
        try:
            from testground_tpu.sim.check import check_composition

            for f in check_composition(
                comp,
                manifest,
                env_layer=env.runners.get("sim:jax") if env else None,
            ):
                ow.warn(
                    "check: [%s] %s: %s", f.severity, f.rule, f.message
                )
        except Exception as e:  # noqa: BLE001 — advisory pass only
            ow.warn("sim:plan static check pass failed: %s", e)
        from testground_tpu.sim.executor import (
            SimJaxConfig,
            _make_mesh,
            _parse_hosts,
            _precheck_device_memory,
            fault_specs_of,
            load_and_specialize,
            make_sim_program,
            resolve_buckets,
            resolve_transport,
            slo_specs_of,
            trace_specs_of,
        )
        from testground_tpu.sim.faults import build_fault_schedule
        from testground_tpu.sim.meshplan import layout_str as _layout_str
        from testground_tpu.sim.trace import build_trace_plan

        artifacts = {g.id: g.run.artifact for g in comp.groups}
        # prepare BEFORE coalescing the runner config: prepare_for_run is
        # what fills manifest runner-config defaults into run_config, and
        # do_run coalesces after it — a different order here would compile
        # a different program than the run executes (wasting the cache and
        # poisoning the BuildKey marker)
        comp = prepare_for_run(comp, manifest)
        cfg = (
            CoalescedConfig()
            .append(env.runners.get("sim:jax") if env else None)
            .append(comp.global_.run_config)
            .coalesce_into(SimJaxConfig)
        )
        hosts = _parse_hosts(getattr(cfg, "additional_hosts", None))
        # mirror the executor's telemetry gate EXACTLY (executor
        # telemetry_on): the composition's disable_metrics opt-out and
        # multi-host cohorts both force telemetry off at run time, so a
        # build under either must precompile the telemetry-OFF variant
        # or it warms a program the run never traces (and the run pays
        # the full XLA compile)
        telemetry = (
            bool(getattr(cfg, "telemetry", False))
            and not comp.global_.disable_metrics
            and not getattr(cfg, "coordinator_address", "")
        )
        # the traffic-matrix plane is program-shaping too, and its gate
        # mirrors the executor's exactly: it requires the telemetry
        # plane (the run refuses otherwise) and cohorts shed it — both
        # collapse to the matrix-OFF variant here
        netmatrix = telemetry and bool(getattr(cfg, "netmatrix", False))
        # transport gate mirrors the executor (resolve_transport is the
        # shared gate): the mesh layout shapes the decision (divisible
        # layouts score the sharded arms, indivisible ones resolve to
        # xla), so the build must precompile the variant the run will
        # actually trace. A cohort resolves against the GLOBAL mesh at
        # run time (always multi-device), so coordinator_address forces
        # xla here — like the telemetry gate above, or the build warms
        # a program the run never traces. transport=auto needs each
        # run's SPECIALIZED shapes to score, so auto resolves per run
        # inside the loop below against the build mesh (same cost
        # model, same decision cache — the executor then reuses the
        # cached decision verbatim).
        build_mesh = (
            None
            if getattr(cfg, "coordinator_address", "")
            else _make_mesh(cfg.shard, getattr(cfg, "mesh", ""))
        )
        transport_auto = (
            str(getattr(cfg, "transport", "xla") or "xla").lower() == "auto"
            and not getattr(cfg, "coordinator_address", "")
        )
        transport = None
        if not transport_auto:
            transport = resolve_transport(cfg, build_mesh)
            if getattr(cfg, "coordinator_address", ""):
                transport = "xla"
        digests = {
            path: _source_digest(path) for path in set(artifacts.values())
        }

        import jax

        # one compile per distinct program shape across [[runs]] — the
        # BuildKey analog: the key is (plan source digest, case, group
        # layout/params, every program-shaping option, backend + topology +
        # jax version); an edited plan re-keys via the source digest
        seen: set[str] = set()
        # transport=auto load memo: (artifact, layout) → specialized
        # (testcase, groups), shared across [[runs]] so the pre-key
        # resolution never re-imports a plan it already specialized
        load_memo: dict = {}
        for run in comp.runs:
            # fault schedules are program-shaping (the event tensors bake
            # into the traced tick), so they join the BuildKey and the
            # precompiled program — mirroring the executor exactly
            run_fault_specs = fault_specs_of(
                run.groups,
                comp.global_.run.faults
                if comp.global_.run is not None
                else None,
            )
            # the flight-recorder plan is program-shaping too, and its
            # gate mirrors the executor's: disable_metrics and cohort
            # configs run trace-free, so a build under either must
            # precompile the no-trace variant
            run_trace_specs = (
                trace_specs_of(
                    run.groups,
                    comp.global_.run.trace
                    if comp.global_.run is not None
                    else None,
                )
                if not comp.global_.disable_metrics
                and not getattr(cfg, "coordinator_address", "")
                else {}
            )
            # SLO rules never shape the program (host-side evaluation),
            # but they are part of the run declaration the marker
            # records — same gating as the telemetry plane they ride
            run_slo_specs = (
                slo_specs_of(
                    run.groups,
                    comp.global_.run.slo
                    if comp.global_.run is not None
                    else None,
                )
                if telemetry
                else {}
            )
            # shape bucketing mirrors the executor's resolve_buckets
            # gate exactly: the padded layout is part of the BuildKey
            # (a bucketed and an exact build are different programs),
            # and the program below compiles the runtime-N variant the
            # run will read from the cache
            bucket_plan = resolve_buckets(
                cfg,
                [
                    rg.calculated_instance_count for rg in run.groups
                ],
                mesh=build_mesh,
                warn=ow.warn,
            )
            from testground_tpu.api import RunGroup

            first = comp.get_group(run.groups[0].effective_group_id())
            run_groups_in = [
                RunGroup(
                    id=rg.id,
                    instances=rg.calculated_instance_count,
                    parameters=dict(rg.test_params),
                )
                for rg in run.groups
            ]
            if bucket_plan is not None:
                padded_in = [
                    RunGroup(
                        id=rg.id,
                        instances=p,
                        parameters=dict(rg.parameters),
                    )
                    for rg, p in zip(
                        run_groups_in, bucket_plan.padded_counts
                    )
                ]
            else:
                padded_in = run_groups_in
            loaded = None
            run_transport = transport
            if transport_auto:
                # auto scores the SPECIALIZED shapes, so the load moves
                # ahead of the BuildKey — the resolved backend is part
                # of the key (a different backend is a different
                # program), and the loaded testcase is reused below.
                # This runs before the marker cache-hit check (the key
                # needs the resolved backend), so: honor cancellation
                # first, and memoize the load per layout — a warm
                # many-[[runs]] build must not pay a plan import per
                # cache hit (the decision cache already dedups scoring).
                if cancel.is_set():
                    return
                from testground_tpu.sim.transport_model import (
                    TransportContext,
                )

                load_key = (
                    artifacts[first.id],
                    tuple(
                        (g.id, g.instances, json.dumps(
                            dict(g.parameters), sort_keys=True
                        ))
                        for g in padded_in
                    ),
                )
                if load_key in load_memo:
                    testcase, groups = load_memo[load_key]
                else:
                    testcase, groups = load_and_specialize(
                        artifacts[first.id],
                        comp.global_.case,
                        padded_in,
                        cfg.tick_ms,
                    )
                    load_memo[load_key] = (testcase, groups)
                if (
                    bucket_plan is not None
                    and "filter_rules" in type(testcase).SHAPING
                    and len(groups) > 1
                ):
                    # executor fallback mirrored: this combination runs
                    # exact shapes, so warm (and score) the exact program
                    bucket_plan = None
                    exact_key = (
                        artifacts[first.id],
                        tuple(
                            (g.id, g.instances, json.dumps(
                                dict(g.parameters), sort_keys=True
                            ))
                            for g in run_groups_in
                        ),
                    )
                    if exact_key in load_memo:
                        testcase, groups = load_memo[exact_key]
                    else:
                        testcase, groups = load_and_specialize(
                            artifacts[first.id],
                            comp.global_.case,
                            run_groups_in,
                            cfg.tick_ms,
                        )
                        load_memo[exact_key] = (testcase, groups)
                loaded = (testcase, groups)
                run_transport = resolve_transport(
                    cfg,
                    build_mesh,
                    warn=ow.warn,
                    context=TransportContext(
                        testcase=testcase,
                        groups=tuple(groups),
                        test_plan=comp.global_.plan,
                        test_case=comp.global_.case,
                        tick_ms=cfg.tick_ms,
                        chunk=cfg.chunk,
                        telemetry=telemetry,
                        validate=bool(getattr(cfg, "validate", False)),
                        hosts=tuple(hosts),
                        probe_reps=int(
                            getattr(cfg, "transport_probe", 0) or 0
                        ),
                    ),
                )
            spec = {
                "sources": digests[artifacts[first.id]],
                "plan": comp.global_.plan,
                "case": comp.global_.case,
                "groups": [
                    {
                        "id": rg.id,
                        "instances": rg.calculated_instance_count,
                        "parameters": dict(rg.test_params),
                    }
                    for rg in run.groups
                ],
                "tick_ms": cfg.tick_ms,
                "chunk": cfg.chunk,
                "seed": cfg.seed,
                "shard": cfg.shard,
                "validate": bool(getattr(cfg, "validate", False)),
                "telemetry": telemetry,
                "transport": run_transport,
                "faults": run_fault_specs,
                "trace": run_trace_specs,
                "slo": run_slo_specs,
                "hosts": list(hosts),
                "backend": jax.default_backend(),
                "devices": jax.device_count(),
                "jax": jax.__version__,
                # keyed only when bucketed — exact builds keep their
                # pre-bucket BuildKeys (and their existing markers)
                **(
                    {"bucket": list(bucket_plan.padded_counts)}
                    if bucket_plan is not None
                    else {}
                ),
                # keyed only when the matrix plane is on — same
                # backward-compatible idiom as the bucket key
                **({"netmatrix": True} if netmatrix else {}),
                # the mesh layout shapes the program (sharding
                # constraints + the shard_map transport variant) —
                # keyed only when meshed, same idiom as the bucket key
                **(
                    {"mesh": _layout_str(build_mesh)}
                    if build_mesh is not None
                    else {}
                ),
            }
            key = hashlib.sha256(
                json.dumps(spec, sort_keys=True).encode()
            ).hexdigest()[:32]
            if key in seen:
                continue
            seen.add(key)
            marker = os.path.join(cache_dir, "precompiled", f"{key}.json")
            if os.path.exists(marker):
                ow.infof(
                    "sim:plan precompile: cache hit for run %s (key %s)",
                    run.id,
                    key,
                )
                continue
            if cancel.is_set():
                return
            t0 = time.perf_counter()
            # same load/specialize/construct helpers as the executor and
            # the sim-worker — the single-code-path guarantee behind the
            # "identical HLO" claim above. Under bucketing the testcase
            # specializes against the PADDED layout (executor rule),
            # fault selectors lower over the exact layout and remap,
            # and the flight recorder is off (the executor's gate).
            # transport=auto already loaded (and fallback-checked) the
            # testcase above to score it — reuse it here.
            if loaded is not None:
                testcase, groups = loaded
            else:
                testcase, groups = load_and_specialize(
                    artifacts[first.id],
                    comp.global_.case,
                    padded_in,
                    cfg.tick_ms,
                )
                if (
                    bucket_plan is not None
                    and "filter_rules" in type(testcase).SHAPING
                    and len(groups) > 1
                ):
                    # executor fallback mirrored: this combination runs
                    # exact shapes, so warm the exact program
                    bucket_plan = None
                    spec.pop("bucket", None)
                    testcase, groups = load_and_specialize(
                        artifacts[first.id],
                        comp.global_.case,
                        run_groups_in,
                        cfg.tick_ms,
                    )
            from testground_tpu.sim.engine import build_groups as _bg

            vgroups = (
                _bg(run_groups_in) if bucket_plan is not None else groups
            )
            fault_schedule = build_fault_schedule(
                vgroups, run_fault_specs, cfg.tick_ms
            )
            if fault_schedule is not None and bucket_plan is not None:
                from testground_tpu.sim.faults import remap_schedule

                fault_schedule = remap_schedule(
                    fault_schedule,
                    bucket_plan.index_map(),
                    bucket_plan.padded_n,
                )
            mesh = build_mesh
            prog = make_sim_program(
                testcase,
                groups,
                test_plan=comp.global_.plan,
                test_case=comp.global_.case,
                test_run="build",
                tick_ms=cfg.tick_ms,
                mesh=mesh,
                chunk=cfg.chunk,
                hosts=hosts,
                validate=bool(getattr(cfg, "validate", False)),
                telemetry=telemetry,
                faults=fault_schedule,
                trace=(
                    build_trace_plan(vgroups, run_trace_specs)
                    if bucket_plan is None
                    else None
                ),
                transport=run_transport,
                live_counts=(
                    bucket_plan.live_counts
                    if bucket_plan is not None
                    else None
                ),
                netmatrix=netmatrix,
            )
            # same capacity precheck as the run: an oversized composition
            # must refuse readably at BUILD time too, not die as an XLA
            # OOM inside the precompile's chunk execution
            _precheck_device_memory(prog, cfg, mesh, ow)
            # Walk the exact compile sequence the executor walks. Under a
            # mesh the chunk compiles TWICE at runtime: the first call
            # sees init's output shardings, but XLA assigns the per-group
            # state leaves its own (GSPMD) shardings, so the second call
            # retraces at that fixed point (one iteration — verified; see
            # SimProgram.run). Execute one chunk here so both variants
            # land in the cache; the run then compiles nothing. Bucketed
            # programs init with runtime (seed, live_counts) inputs —
            # the same traced signature the run uses.
            if bucket_plan is not None:
                import numpy as _np

                carry = jax.jit(
                    lambda s, lc: prog.init_carry(s, lc)  # noqa: B023
                )(
                    _np.int32(cfg.seed),
                    _np.asarray(bucket_plan.live_counts, _np.int32),
                )
            else:
                carry = jax.jit(lambda: prog.init_carry(cfg.seed))()  # noqa: B023
            fn = prog.compiled_chunk()
            # compiles variant 1 + runs one chunk (telemetry programs
            # return (carry, done, block) — take the carry positionally)
            carry = fn(carry)[0]
            # fixed-point variant, no execution — timed in its
            # lower-vs-compile halves and harvested for cost/memory
            # analysis, so the BuildKey marker records the performance
            # ledger's compile block (docs/OBSERVABILITY.md)
            from testground_tpu.sim.perf import (
                compile_analysis,
                timed_lower_compile,
            )

            lower_secs, xla_secs, compiled = timed_lower_compile(fn, carry)
            perf = {
                "lower_secs": round(lower_secs, 6),
                "compile_secs": round(xla_secs, 6),
                **compile_analysis(compiled),
            }
            del carry, compiled
            secs = time.perf_counter() - t0
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w") as f:
                json.dump(
                    {**spec, "compile_secs": round(secs, 3), "perf": perf},
                    f,
                )
            ow.infof(
                "sim:plan precompiled run %s into %s in %.1fs (key %s)",
                run.id,
                cache_dir,
                secs,
                key,
            )

        # ---------------------------------------- bucket-ladder warming
        # `tg build --buckets` (build_buckets=true): beyond the
        # composition's own rung, precompile EVERY canonical bucket of
        # the ladder for this (plan, case, params) — one command makes
        # the persistent cache warm for any instance count a tenant may
        # ask for, with per-bucket compile_secs journaled in the
        # markers so the warmup cost is a recorded number, not a guess.
        if getattr(cfg, "build_buckets", False) and not cancel.is_set():
            self._warm_bucket_ladder(
                comp, cfg, artifacts, hosts, telemetry, cache_dir, ow, cancel
            )

    def _warm_bucket_ladder(
        self, comp, cfg, artifacts, hosts, telemetry, cache_dir, ow, cancel
    ) -> None:
        """Compile the canonical bucket ladder for the composition's
        first [[runs]] entry (same group structure/params, each group
        padded to each rung). Best-effort per rung: an over-budget rung
        (memory precheck) is skipped loudly, not fatal."""
        import time as _time

        import numpy as _np

        from testground_tpu.api import RunGroup
        from testground_tpu.sim.buckets import parse_ladder
        from testground_tpu.sim.executor import (
            _make_mesh,
            _precheck_device_memory,
            load_and_specialize,
            make_sim_program,
            resolve_transport,
        )

        import jax

        if getattr(cfg, "coordinator_address", ""):
            ow.warn("bucket-ladder warming skipped under a cohort config")
            return
        # a mesh narrows the ladder instead of refusing it: only rungs
        # whose padded count divides across the peer shards compile the
        # sharded program (sim/meshplan.py) — indivisible rungs are
        # skipped loudly per rung inside the loop below
        mesh = _make_mesh(cfg.shard, getattr(cfg, "mesh", ""))
        from testground_tpu.sim.meshplan import peer_shards

        shards = peer_shards(mesh)
        # transport=auto scores PER RUNG (the decision is shape-
        # dependent: a 4k bucket and a 1M bucket may pick different
        # backends) — resolved inside the loop with each rung's
        # specialized context; explicit knobs resolve once here
        transport_auto = (
            str(getattr(cfg, "transport", "xla") or "xla").lower()
            == "auto"
        )
        transport = (
            None if transport_auto else resolve_transport(cfg, mesh)
        )
        ladder = parse_ladder(getattr(cfg, "bucket_ladder", "") or None)
        run = comp.runs[0]
        first = comp.get_group(run.groups[0].effective_group_id())
        counts = [rg.calculated_instance_count for rg in run.groups]
        warmed = []
        for rung in ladder:
            if cancel.is_set():
                return
            if any(c > rung for c in counts):
                continue  # this rung cannot hold the composition
            if shards > 1 and rung % shards != 0:
                ow.warn(
                    "bucket %d skipped: it does not divide across %d "
                    "peer shard(s) — pick ladder rungs that are "
                    "multiples of the shard count to warm them meshed",
                    rung,
                    shards,
                )
                continue
            t0 = _time.perf_counter()
            try:
                testcase, groups = load_and_specialize(
                    artifacts[first.id],
                    comp.global_.case,
                    [
                        RunGroup(
                            id=rg.id,
                            instances=rung,
                            parameters=dict(rg.test_params),
                        )
                        for rg in run.groups
                    ],
                    cfg.tick_ms,
                )
                if transport_auto:
                    from testground_tpu.sim.transport_model import (
                        TransportContext,
                    )

                    rung_transport = resolve_transport(
                        cfg,
                        mesh,
                        warn=ow.warn,
                        context=TransportContext(
                            testcase=testcase,
                            groups=tuple(groups),
                            test_plan=comp.global_.plan,
                            test_case=comp.global_.case,
                            tick_ms=cfg.tick_ms,
                            chunk=cfg.chunk,
                            telemetry=telemetry,
                            validate=bool(
                                getattr(cfg, "validate", False)
                            ),
                            hosts=tuple(hosts),
                            # same decision-cache key as the run's gate
                            # — a probe-vs-static split between warming
                            # and running would warm the wrong backend
                            probe_reps=int(
                                getattr(cfg, "transport_probe", 0) or 0
                            ),
                        ),
                    )
                else:
                    rung_transport = transport
                prog = make_sim_program(
                    testcase,
                    groups,
                    test_plan=comp.global_.plan,
                    test_case=comp.global_.case,
                    test_run="build",
                    tick_ms=cfg.tick_ms,
                    mesh=mesh,
                    chunk=cfg.chunk,
                    hosts=hosts,
                    validate=bool(getattr(cfg, "validate", False)),
                    telemetry=telemetry,
                    faults=None,
                    trace=None,
                    transport=rung_transport,
                    live_counts=tuple(counts),
                    # same gate as the per-run precompile above: the
                    # matrix plane rides telemetry
                    netmatrix=telemetry
                    and bool(getattr(cfg, "netmatrix", False)),
                )
                _precheck_device_memory(prog, cfg, mesh, ow)
                carry = jax.jit(
                    lambda s, lc: prog.init_carry(s, lc)  # noqa: B023
                )(
                    _np.int32(cfg.seed),
                    _np.asarray(counts, _np.int32),
                )
                prog.compiled_chunk()(carry)
                del carry
            except Exception as e:  # noqa: BLE001 — per-rung best-effort
                ow.warn(
                    "bucket %d warmup failed (skipped): %s", rung, e
                )
                continue
            secs = round(_time.perf_counter() - t0, 3)
            warmed.append({"bucket": rung, "compile_secs": secs})
            ow.infof(
                "sim:plan bucket %d warmed in %.1fs (%s:%s)",
                rung,
                secs,
                comp.global_.plan,
                comp.global_.case,
            )
            # run packing compiles its own HLO per (bucket, vmapped
            # width): when the composition opts into packing, warm the
            # power-of-two width ladder too — bounded to packs whose
            # total lane count stays inside the bucket ladder's top
            # rung, the envelope packs are for (small tenants)
            pack_on = str(
                getattr(cfg, "pack", False)
            ).strip().lower() in ("1", "true", "yes", "on")
            if pack_on:
                from testground_tpu.sim.pack import (
                    PackRunner,
                    pack_width,
                )

                pack_max = int(getattr(cfg, "pack_max", 8) or 8)
                # packed-lane budget: a full pack of smallest-rung runs
                # — larger rungs warm proportionally fewer widths (a
                # width-8 pack of 1M-lane buckets is not a serving
                # shape, and its compile would dwarf the build)
                lane_budget = pack_max * ladder[0]
                w = 2
                while w <= pack_width(pack_max, pack_max):
                    if w * rung > lane_budget:
                        break  # packed lanes past the serving envelope
                    t1 = _time.perf_counter()
                    try:
                        runner = PackRunner(prog, w)
                        seeds = _np.zeros((w,), _np.int32)
                        lcs = _np.asarray(
                            [counts] * w, _np.int32
                        )
                        live = _np.ones((w,), bool)
                        pc = runner.packed_init()(seeds, lcs, live)
                        runner.packed_chunk()(pc)
                        del pc
                    except Exception as e:  # noqa: BLE001
                        ow.warn(
                            "bucket %d pack width %d warmup failed "
                            "(skipped): %s",
                            rung,
                            w,
                            e,
                        )
                        w *= 2
                        continue
                    psecs = round(_time.perf_counter() - t1, 3)
                    warmed.append(
                        {
                            "bucket": rung,
                            "pack_width": w,
                            "compile_secs": psecs,
                        }
                    )
                    ow.infof(
                        "sim:plan bucket %d pack-width %d warmed in "
                        "%.1fs",
                        rung,
                        w,
                        psecs,
                    )
                    w *= 2
        if warmed:
            marker = os.path.join(
                cache_dir,
                "precompiled",
                f"buckets-{comp.global_.plan}-{comp.global_.case}.json",
            )
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w") as f:
                json.dump(
                    {
                        "plan": comp.global_.plan,
                        "case": comp.global_.case,
                        "ladder": list(ladder),
                        "buckets": warmed,
                    },
                    f,
                )
