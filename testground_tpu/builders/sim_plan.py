"""``sim:plan`` builder: resolve a plan's simulation program for the
``sim:jax`` runner.

The sim runner executes plans as traceable JAX state machines, not
processes, so the "artifact" is the plan source dir itself (validated to
expose ``sim_plans`` — see ``testground_tpu.sim.api``). Snapshotting is
shared with ``exec:py`` so queued runs are immune to source edits.
"""

from __future__ import annotations

import os
import shutil
import threading

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter

from .base import Builder, purge_snapshots

__all__ = ["SimPlanBuilder"]


class SimPlanBuilder(Builder):
    def id(self) -> str:
        return "sim:plan"

    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput:
        src = inp.unpacked_plan_dir
        if not src or not os.path.isdir(src):
            raise ValueError(f"plan sources not found: {src!r}")
        if not (
            os.path.isfile(os.path.join(src, "sim.py"))
            or os.path.isfile(os.path.join(src, "main.py"))
        ):
            raise ValueError(
                f"plan has neither sim.py nor main.py entry point: {src}"
            )
        work = inp.env.dirs.work()
        dest = os.path.join(work, f"sim-plan--{inp.test_plan}-{inp.build_id}")
        if os.path.exists(dest):
            shutil.rmtree(dest)
        shutil.copytree(
            src,
            dest,
            ignore=shutil.ignore_patterns(
                "__pycache__", "*.pyc", ".git", "_compositions"
            ),
        )
        ow.infof("sim:plan built %s -> %s", inp.test_plan, dest)
        return BuildOutput(builder_id=self.id(), artifact_path=dest)

    def purge(self, testplan: str, ow: OutputWriter, env=None) -> None:
        removed = purge_snapshots("sim-plan", testplan, ow, env)
        ow.infof("sim:plan purge: removed %d snapshot(s)", removed)
