"""``exec:py`` builder: resolve a Python plan into a runnable artifact.

The analog of the reference's ``exec:go`` (``pkg/build/exec_go.go``: compile
to a host executable at ``<work>/exec-go--<plan>-<id>``). Python needs no
compilation; the build snapshots the plan sources into
``<work>/exec-py--<plan>-<build-id>/`` (immutable artifact, so later source
edits don't mutate queued runs), validates the entry point, and returns the
snapshot's ``main.py`` as the artifact path. Dependency overrides map to
extra ``PYTHONPATH`` entries recorded in ``deps.json`` (the analog of go.mod
replace directives, ``exec_go.go:94-118``).
"""

from __future__ import annotations

import json
import os
import threading

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter

from .base import Builder, purge_snapshots, snapshot_plan_sources

__all__ = ["ExecPyBuilder"]


class ExecPyBuilder(Builder):
    def id(self) -> str:
        return "exec:py"

    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput:
        src = inp.unpacked_plan_dir
        # entry-point check BEFORE snapshotting so a bad plan doesn't
        # leave an orphaned snapshot dir per failed build attempt
        if src and os.path.isdir(src) and not os.path.isfile(
            os.path.join(src, "main.py")
        ):
            raise ValueError(f"plan has no main.py entry point: {src}")
        work = inp.env.dirs.work()
        dest = os.path.join(work, f"exec-py--{inp.test_plan}-{inp.build_id}")
        snapshot_plan_sources(src, dest)

        deps = {mod: {"target": t, "version": v} for mod, (t, v) in
                inp.dependencies.items()}
        with open(os.path.join(dest, "deps.json"), "w") as f:
            json.dump({"selectors": inp.selectors, "dependencies": deps}, f)

        artifact = os.path.join(dest, "main.py")
        ow.infof("exec:py built %s -> %s", inp.test_plan, artifact)
        return BuildOutput(
            builder_id=self.id(),
            artifact_path=artifact,
            dependencies={m: d["version"] for m, d in deps.items()},
        )

    def purge(self, testplan: str, ow: OutputWriter, env=None) -> None:
        """Remove this builder's snapshot artifacts for a plan."""
        removed = purge_snapshots("exec-py", testplan, ow, env)
        ow.infof("exec:py purge: removed %d snapshot(s)", removed)
