"""Builder interface (``pkg/api/builder.go:14-26``)."""

from __future__ import annotations

import abc
import os
import re
import shutil
import threading

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter
from testground_tpu.runners.base import Terminatable

__all__ = ["Builder", "snapshot_plan_sources"]

# Paths never copied into a build snapshot (caches, VCS, fixtures).
_SNAPSHOT_IGNORE = ("__pycache__", "*.pyc", ".git", "_compositions")


def purge_snapshots(prefix: str, testplan: str, ow: OutputWriter, env) -> int:
    """Delete every ``<work>/<prefix>--<testplan>-<build-id>`` snapshot —
    the shared artifact naming of the snapshot builders. Returns the count
    removed; a missing env (interface parity callers) removes nothing."""
    if env is None:
        return 0
    work = env.dirs.work()
    if not os.path.isdir(work):
        return 0
    # exact plan match: build ids are 20-char xids (engine/task.py), with
    # an optional per-group suffix — a bare prefix match would also claim
    # plans whose names extend this one (net vs net-v2)
    pat = re.compile(
        rf"^{re.escape(prefix)}--{re.escape(testplan)}"
        rf"-[a-z0-9]{{20}}(-\d+)?$"
    )
    removed = 0
    for name in os.listdir(work):
        if not pat.match(name):
            continue
        path = os.path.join(work, name)
        try:
            shutil.rmtree(path)
        except OSError as e:
            ow.warn("could not purge %s: %s", name, e)
            continue
        ow.infof("purged %s", name)
        removed += 1
    return removed


def snapshot_plan_sources(src: str | None, dest: str) -> None:
    """Copy plan sources into an immutable build snapshot at ``dest``
    (replacing any previous snapshot), so later source edits don't mutate
    queued runs. Shared by the exec:* builders."""
    if not src or not os.path.isdir(src):
        raise ValueError(f"plan sources not found: {src!r}")
    if os.path.exists(dest):
        shutil.rmtree(dest)
    shutil.copytree(
        src, dest, ignore=shutil.ignore_patterns(*_SNAPSHOT_IGNORE)
    )


class Builder(Terminatable, abc.ABC):
    """A builder takes a test plan and builds it into executable form so it
    can be scheduled by a runner.

    Builders are Terminatable so ``tg terminate --builder`` succeeds (the
    reference's DoTerminate accepts builders, ``engine.go:285-311``); the
    snapshot builders run synchronously inside the worker with no external
    jobs, so the default terminate is a no-op report — mirroring the
    runners' no-op implementations."""

    def terminate_all(self, ow: OutputWriter) -> None:
        ow.infof("builder %s has no external jobs to terminate", self.id())

    @abc.abstractmethod
    def id(self) -> str: ...

    @abc.abstractmethod
    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput: ...

    def purge(self, testplan: str, ow: OutputWriter, env=None) -> None:
        """Drop cached artifacts for one plan (``api.Builder.Purge``,
        ``pkg/api/builder.go:14-26``). ``env`` is the engine's EnvConfig —
        builders locate their snapshots under its work dir."""

    def config_type(self) -> type | None:
        return None


class Precompiler(abc.ABC):
    """A builder whose artifact includes a compiled program.

    The reference's expensive artifact production happens at *build* time,
    BuildKey-deduped (``pkg/engine/supervisor.go:359-364``; go-build cache
    ``pkg/build/docker_go.go:266-283``). For JAX-program builders the
    expensive step is XLA compilation, so an explicit build task
    additionally traces + compiles the composition's programs into the
    persistent compile cache (``utils/compile_cache.py``) — a later run of
    the same composition skips XLA compile entirely."""

    @abc.abstractmethod
    def precompile(
        self,
        comp,
        manifest,
        env,
        ow: OutputWriter,
        cancel: threading.Event,
    ) -> None: ...
