"""Builder interface (``pkg/api/builder.go:14-26``)."""

from __future__ import annotations

import abc
import os
import shutil
import threading

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter

__all__ = ["Builder", "snapshot_plan_sources"]

# Paths never copied into a build snapshot (caches, VCS, fixtures).
_SNAPSHOT_IGNORE = ("__pycache__", "*.pyc", ".git", "_compositions")


def snapshot_plan_sources(src: str | None, dest: str) -> None:
    """Copy plan sources into an immutable build snapshot at ``dest``
    (replacing any previous snapshot), so later source edits don't mutate
    queued runs. Shared by the exec:* builders."""
    if not src or not os.path.isdir(src):
        raise ValueError(f"plan sources not found: {src!r}")
    if os.path.exists(dest):
        shutil.rmtree(dest)
    shutil.copytree(
        src, dest, ignore=shutil.ignore_patterns(*_SNAPSHOT_IGNORE)
    )


class Builder(abc.ABC):
    """A builder takes a test plan and builds it into executable form so it
    can be scheduled by a runner."""

    @abc.abstractmethod
    def id(self) -> str: ...

    @abc.abstractmethod
    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput: ...

    def purge(self, testplan: str, ow: OutputWriter) -> None:
        """Free resources such as caches."""

    def config_type(self) -> type | None:
        return None
