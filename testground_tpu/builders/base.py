"""Builder interface (``pkg/api/builder.go:14-26``)."""

from __future__ import annotations

import abc
import threading

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter

__all__ = ["Builder"]


class Builder(abc.ABC):
    """A builder takes a test plan and builds it into executable form so it
    can be scheduled by a runner."""

    @abc.abstractmethod
    def id(self) -> str: ...

    @abc.abstractmethod
    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput: ...

    def purge(self, testplan: str, ow: OutputWriter) -> None:
        """Free resources such as caches."""

    def config_type(self) -> type | None:
        return None
