"""``exec:bin`` builder: plans in ANY compiled/interpreted language.

The analog of the reference's ``docker:generic`` (``pkg/build/
docker_generic.go:34-100``: build the plan's own Dockerfile — used by the
Rust/JS plans): the multi-language property of the platform is delivered
by the instance PROTOCOL (TEST_* env vars + JSON event lines on stdout +
the sync service's TCP wire protocol), not by language SDK bindings. This
builder snapshots the plan sources, runs the plan's own ``build.sh`` when
present (the Dockerfile analog), and requires an executable ``run`` entry
point as the artifact.
"""

from __future__ import annotations

import os
import signal
import stat
import subprocess
import threading
import time

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter

from .base import Builder, purge_snapshots, snapshot_plan_sources

__all__ = ["ExecBinBuilder"]

BUILD_TIMEOUT_SECS = 600


class ExecBinBuilder(Builder):
    def id(self) -> str:
        return "exec:bin"

    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput:
        src = inp.unpacked_plan_dir
        work = inp.env.dirs.work()
        dest = os.path.join(work, f"exec-bin--{inp.test_plan}-{inp.build_id}")
        snapshot_plan_sources(src, dest)

        build_script = os.path.join(dest, "build.sh")
        if os.path.isfile(build_script):
            ow.infof("exec:bin: running %s", build_script)
            # Popen + poll so a task kill interrupts a long compile instead
            # of holding the engine worker until the timeout. The script
            # runs in its own session so the kill reaches the compilers it
            # forked, not just the /bin/sh wrapper (whose orphans would
            # otherwise hold the pipes open and block communicate()).
            with subprocess.Popen(
                ["/bin/sh", build_script],
                cwd=dest,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                start_new_session=True,
            ) as proc:
                deadline = time.monotonic() + BUILD_TIMEOUT_SECS
                while True:
                    try:
                        out, err = proc.communicate(timeout=0.5)
                        break
                    except subprocess.TimeoutExpired:
                        if cancel.is_set() or time.monotonic() > deadline:
                            try:
                                os.killpg(proc.pid, signal.SIGKILL)
                            except ProcessLookupError:
                                pass
                            out, err = proc.communicate()
                            if cancel.is_set():
                                raise RuntimeError("build canceled")
                            raise subprocess.TimeoutExpired(
                                build_script, BUILD_TIMEOUT_SECS, out, err
                            )
            if out.strip():
                ow.infof("build.sh stdout:\n%s", out.strip())
            if proc.returncode != 0:
                raise RuntimeError(
                    f"build.sh failed (exit {proc.returncode}):\n"
                    f"{err.strip()}"
                )
            if err.strip():  # surface compiler warnings on success too
                ow.infof("build.sh stderr:\n%s", err.strip())

        artifact = os.path.join(dest, "run")
        if not os.path.isfile(artifact):
            raise ValueError(
                f"plan has no `run` entry point after build: {dest} "
                "(exec:bin plans must ship or build an executable named "
                "`run`)"
            )
        os.chmod(
            artifact,
            os.stat(artifact).st_mode | stat.S_IXUSR | stat.S_IXGRP,
        )
        ow.infof("exec:bin built %s -> %s", inp.test_plan, artifact)
        return BuildOutput(builder_id=self.id(), artifact_path=artifact)

    def purge(self, testplan: str, ow: OutputWriter, env=None) -> None:
        removed = purge_snapshots("exec-bin", testplan, ow, env)
        ow.infof("exec:bin purge: removed %d snapshot(s)", removed)
