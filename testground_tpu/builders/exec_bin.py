"""``exec:bin`` builder: plans in ANY compiled/interpreted language.

The analog of the reference's ``docker:generic`` (``pkg/build/
docker_generic.go:34-100``: build the plan's own Dockerfile — used by the
Rust/JS plans): the multi-language property of the platform is delivered
by the instance PROTOCOL (TEST_* env vars + JSON event lines on stdout +
the sync service's TCP wire protocol), not by language SDK bindings. This
builder snapshots the plan sources, runs the plan's own ``build.sh`` when
present (the Dockerfile analog), and requires an executable ``run`` entry
point as the artifact.
"""

from __future__ import annotations

import os
import shutil
import stat
import subprocess
import threading

from testground_tpu.api import BuildInput, BuildOutput
from testground_tpu.rpc import OutputWriter

from .base import Builder

__all__ = ["ExecBinBuilder"]

BUILD_TIMEOUT_SECS = 600


class ExecBinBuilder(Builder):
    def id(self) -> str:
        return "exec:bin"

    def build(
        self, inp: BuildInput, ow: OutputWriter, cancel: threading.Event
    ) -> BuildOutput:
        src = inp.unpacked_plan_dir
        if not src or not os.path.isdir(src):
            raise ValueError(f"plan sources not found: {src!r}")

        work = inp.env.dirs.work()
        dest = os.path.join(work, f"exec-bin--{inp.test_plan}-{inp.build_id}")
        if os.path.exists(dest):
            shutil.rmtree(dest)
        shutil.copytree(
            src,
            dest,
            ignore=shutil.ignore_patterns(
                "__pycache__", "*.pyc", ".git", "_compositions"
            ),
        )

        build_script = os.path.join(dest, "build.sh")
        if os.path.isfile(build_script):
            ow.infof("exec:bin: running %s", build_script)
            proc = subprocess.run(
                ["/bin/sh", build_script],
                cwd=dest,
                capture_output=True,
                text=True,
                timeout=BUILD_TIMEOUT_SECS,
            )
            if proc.stdout.strip():
                ow.infof("build.sh stdout:\n%s", proc.stdout.strip())
            if proc.returncode != 0:
                raise RuntimeError(
                    f"build.sh failed (exit {proc.returncode}):\n"
                    f"{proc.stderr.strip()}"
                )

        artifact = os.path.join(dest, "run")
        if not os.path.isfile(artifact):
            raise ValueError(
                f"plan has no `run` entry point after build: {dest} "
                "(exec:bin plans must ship or build an executable named "
                "`run`)"
            )
        os.chmod(
            artifact,
            os.stat(artifact).st_mode | stat.S_IXUSR | stat.S_IXGRP,
        )
        ow.infof("exec:bin built %s -> %s", inp.test_plan, artifact)
        return BuildOutput(builder_id=self.id(), artifact_path=artifact)

    def purge(self, testplan: str, ow: OutputWriter) -> None:
        ow.infof("exec:bin purge: artifacts are removed with the work dir")
