"""Healthcheck report types (``pkg/api/healthcheck.go:17-56``)."""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["CheckResult", "Report"]

# check statuses
OK = "ok"
FAILED = "failed"
ABORTED = "aborted"
OMITTED = "omitted"


@dataclass
class CheckResult:
    name: str
    status: str
    message: str = ""

    def to_dict(self) -> dict:
        return {"name": self.name, "status": self.status, "message": self.message}


@dataclass
class Report:
    checks: list[CheckResult] = field(default_factory=list)
    fixes: list[CheckResult] = field(default_factory=list)

    def ok(self) -> bool:
        return all(c.status == OK for c in self.checks) and all(
            f.status in (OK, OMITTED) for f in self.fixes
        )

    def to_dict(self) -> dict:
        return {
            "checks": [c.to_dict() for c in self.checks],
            "fixes": [f.to_dict() for f in self.fixes],
        }

    def __str__(self) -> str:
        lines = []
        for c in self.checks:
            lines.append(f"check {c.name}: {c.status} {c.message}".rstrip())
        for f in self.fixes:
            lines.append(f"fix   {f.name}: {f.status} {f.message}".rstrip())
        return "\n".join(lines)
