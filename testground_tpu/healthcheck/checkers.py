"""Reusable checkers (``pkg/healthcheck/checkers.go:20-190``).

Checkers return ``(ok, message)``. Combinators ``all_of``/``any_of``/
``not_`` mirror the reference's All/Any/Not.
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
import uuid
from typing import Callable

Checker = Callable[[], tuple[bool, str]]

__all__ = [
    "all_of",
    "any_of",
    "check_command_status",
    "check_dialable",
    "check_dir_exists",
    "check_dir_writable",
    "check_file_exists",
    "check_executable_on_path",
    "check_port_bindable",
    "check_sync_service",
    "not_",
]


def check_dir_exists(path: str) -> Checker:
    """(``checkers.go`` DirExistsChecker)."""

    def check() -> tuple[bool, str]:
        if os.path.isdir(path):
            return True, f"directory exists: {path}"
        return False, f"directory missing: {path}"

    return check


def check_dir_writable(path: str) -> Checker:
    """Directory exists AND a file can actually be created in it (catches
    read-only mounts and permission problems, not just absence)."""

    def check() -> tuple[bool, str]:
        if not os.path.isdir(path):
            return False, f"directory missing: {path}"
        # unique probe name: concurrent healthchecks (one per scheduler
        # worker) must not race on the same file
        probe = os.path.join(
            path, f".tg-healthcheck-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        )
        try:
            with open(probe, "w") as f:
                f.write("ok")
            os.unlink(probe)
        except OSError as e:
            return False, f"directory not writable: {path}: {e}"
        return True, f"directory writable: {path}"

    return check


def check_port_bindable(host: str = "127.0.0.1", port: int = 0) -> Checker:
    """An ephemeral (or specific) TCP port can be bound — the runner's
    in-process sync service needs one per run."""

    def check() -> tuple[bool, str]:
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
                s.bind((host, port))
                bound = s.getsockname()[1]
            return True, f"bound {host}:{bound}"
        except OSError as e:
            return False, f"cannot bind {host}:{port}: {e}"

    return check


def check_file_exists(path: str) -> Checker:
    def check() -> tuple[bool, str]:
        if os.path.isfile(path):
            return True, f"file exists: {path}"
        return False, f"file missing: {path}"

    return check


def check_dialable(host: str, port: int, timeout: float = 2.0) -> Checker:
    """(``checkers.go`` DialableChecker)."""

    def check() -> tuple[bool, str]:
        try:
            with socket.create_connection((host, port), timeout=timeout):
                return True, f"{host}:{port} is dialable"
        except OSError as e:
            return False, f"{host}:{port} not dialable: {e}"

    return check


def check_sync_service(host: str, port: int, timeout: float = 2.0) -> Checker:
    """A (possibly remote) sync service answers a real ``ping`` RPC at
    ``host:port`` — connect-level reachability alone can lie (a stopped
    or wedged server still completes TCP handshakes via the listen
    backlog). Used by ``tg healthcheck`` when the local:exec runner is
    configured with an external ``sync_service_address``
    (docs/CROSSHOST.md)."""
    import json

    def check() -> tuple[bool, str]:
        try:
            with socket.create_connection((host, port), timeout=timeout) as s:
                s.settimeout(timeout)
                s.sendall(b'{"id": 1, "op": "ping"}\n')
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    buf += chunk
            msg = json.loads(buf or b"{}")
            if msg.get("pong"):
                boot = msg.get("boot", "")
                return True, (
                    f"sync service at {host}:{port} answered ping"
                    + (f" (boot {boot[:12]})" if boot else "")
                )
            return False, (
                f"{host}:{port} spoke, but not the sync protocol: "
                f"{buf[:80]!r}"
            )
        except (OSError, ValueError) as e:
            return False, f"sync service at {host}:{port} unreachable: {e}"

    return check


def check_command_status(*argv: str) -> Checker:
    """(``checkers.go`` CommandStartedChecker/exit-status)."""

    def check() -> tuple[bool, str]:
        try:
            rc = subprocess.run(
                argv, capture_output=True, timeout=30
            ).returncode
        except (OSError, subprocess.TimeoutExpired) as e:
            return False, f"command failed: {e}"
        return rc == 0, f"exit status {rc}"

    return check


def check_executable_on_path(name: str) -> Checker:
    def check() -> tuple[bool, str]:
        path = shutil.which(name)
        if path:
            return True, f"{name} found at {path}"
        return False, f"{name} not on PATH"

    return check


def all_of(*checkers: Checker) -> Checker:
    def check() -> tuple[bool, str]:
        msgs = []
        for c in checkers:
            ok, msg = c()
            msgs.append(msg)
            if not ok:
                return False, "; ".join(msgs)
        return True, "; ".join(msgs)

    return check


def any_of(*checkers: Checker) -> Checker:
    def check() -> tuple[bool, str]:
        msgs = []
        for c in checkers:
            ok, msg = c()
            msgs.append(msg)
            if ok:
                return True, msg
        return False, "; ".join(msgs)

    return check


def not_(checker: Checker) -> Checker:
    def check() -> tuple[bool, str]:
        ok, msg = checker()
        return not ok, msg

    return check
