"""Declarative healthcheck framework: check/fix pairs.

Twin of the reference's ``pkg/healthcheck``: a Helper enlists named
(checker, fixer) pairs; ``run_checks(fix=...)`` evaluates them and produces a
report (``helper.go:55-65``, report types ``pkg/api/healthcheck.go:17-56``).
"""

from .helper import Helper
from .report import CheckResult, Report
from . import checkers, fixers

__all__ = ["CheckResult", "Helper", "Report", "checkers", "fixers"]
