"""Healthcheck helper (``pkg/healthcheck/helper.go``)."""

from __future__ import annotations

from typing import Callable

from testground_tpu.rpc import OutputWriter, discard_writer

from .report import ABORTED, FAILED, OK, OMITTED, CheckResult, Report

__all__ = ["Helper"]

# A Checker returns (ok: bool, message: str). A Fixer returns a message and
# raises on failure.
Checker = Callable[[], tuple[bool, str]]
Fixer = Callable[[], str]


class Helper:
    def __init__(self):
        self._items: list[tuple[str, Checker, Fixer | None]] = []

    def enlist(self, name: str, checker: Checker, fixer: Fixer | None = None) -> None:
        """(``helper.go:55-60`` Enlist)."""
        self._items.append((name, checker, fixer))

    def run_checks(self, fix: bool, ow: OutputWriter | None = None) -> Report:
        """Evaluate all checks; when ``fix`` is set, run the fixer for failed
        checks and re-check (``helper.go:61-110`` RunChecks)."""
        ow = ow or discard_writer()
        report = Report()
        for name, checker, fixer in self._items:
            try:
                ok, msg = checker()
            except Exception as e:  # noqa: BLE001
                ok, msg = False, str(e)
            if ok:
                report.checks.append(CheckResult(name, OK, msg))
                report.fixes.append(CheckResult(name, OMITTED, "check passed"))
                continue
            report.checks.append(CheckResult(name, FAILED, msg))
            if not fix:
                report.fixes.append(CheckResult(name, OMITTED, "fix not requested"))
                continue
            if fixer is None:
                report.fixes.append(CheckResult(name, ABORTED, "no fixer"))
                continue
            try:
                fix_msg = fixer()
            except Exception as e:  # noqa: BLE001
                report.fixes.append(CheckResult(name, FAILED, str(e)))
                continue
            # re-check after fixing
            try:
                ok2, msg2 = checker()
            except Exception as e:  # noqa: BLE001
                ok2, msg2 = False, str(e)
            status = OK if ok2 else FAILED
            report.fixes.append(CheckResult(name, status, fix_msg or msg2))
            if ok2:
                report.checks[-1] = CheckResult(name, OK, "fixed")
            ow.infof("healthcheck %s: fixed=%s", name, ok2)
        return report
