"""Reusable fixers (``pkg/healthcheck/fixers.go:19-114``).

Fixers return a message on success and raise on failure. Combinators
``and_then``/``or_else`` mirror And/Or; ``not_implemented`` and
``requires_manual_fixing`` mirror the sentinel fixers.
"""

from __future__ import annotations

import os
import subprocess
from typing import Callable

Fixer = Callable[[], str]

__all__ = [
    "and_then",
    "create_directory",
    "not_implemented",
    "or_else",
    "requires_manual_fixing",
    "start_command",
]


def create_directory(path: str) -> Fixer:
    def fix() -> str:
        os.makedirs(path, exist_ok=True)
        return f"created directory {path}"

    return fix


def start_command(*argv: str, cwd: str | None = None) -> Fixer:
    """Start a background process (``fixers.go`` StartCommand)."""

    def fix() -> str:
        subprocess.Popen(
            argv,
            cwd=cwd,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
        return f"started: {' '.join(argv)}"

    return fix


def not_implemented() -> Fixer:
    def fix() -> str:
        raise NotImplementedError("no automatic fix for this check")

    return fix


def requires_manual_fixing(hint: str = "") -> Fixer:
    def fix() -> str:
        raise RuntimeError(f"requires manual fixing: {hint}" if hint else
                           "requires manual fixing")

    return fix


def and_then(*fixers: Fixer) -> Fixer:
    def fix() -> str:
        msgs = [f() for f in fixers]
        return "; ".join(msgs)

    return fix


def or_else(*fixers: Fixer) -> Fixer:
    def fix() -> str:
        last: Exception | None = None
        for f in fixers:
            try:
                return f()
            except Exception as e:  # noqa: BLE001
                last = e
        raise last if last else RuntimeError("no fixers provided")

    return fix
