"""Version compatibility shims — ONE home for stdlib fallbacks.

``tomllib`` entered the stdlib in Python 3.11; on 3.10 the identical
API ships as the third-party ``tomli`` (declared as a conditional
dependency in pyproject). Import it from here so the fallback logic
lives in exactly one place:

    from testground_tpu.utils.compat import tomllib
"""

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11
    import tomli as tomllib  # type: ignore[no-redef]

__all__ = ["tomllib"]
