"""Persistent XLA compilation cache wiring — the framework's "build
artifact" in the reference's sense.

The reference's expensive artifact production is a cached, BuildKey-deduped
*build* step (``pkg/engine/supervisor.go:359-364``; the go-build cache
image ``pkg/build/docker_go.go:266-283``). Here the true artifact is the
compiled XLA program: at 100k instances a cold trace+compile costs ~44 s —
roughly the whole 10k-tick execution — so every entry point that compiles
a :class:`~testground_tpu.sim.engine.SimProgram` (the sim:jax executor,
``tg sim-worker`` followers, the ``sim:plan`` builder's precompile pass,
and ``bench.py``) routes compilation through one on-disk cache under
``$TESTGROUND_HOME/data/compile-cache``.

XLA keys entries by a hash of the optimized HLO + compile options + backend
version, so identical (plan, groups, shapes, mesh) programs deduplicate
across processes and rounds automatically; tracing/lowering (pure Python)
is still paid per process, but the dominant XLA compile step becomes a
cache read. ``TESTGROUND_COMPILE_CACHE`` overrides the location; the values
``off``/``0``/``none`` disable caching entirely.
"""

from __future__ import annotations

import os

__all__ = [
    "cache_event_counts",
    "compile_cache_dir",
    "enable_compile_cache",
]

_DISABLE = ("off", "0", "none", "false")

# set once per (process, directory); jax.config.update is cheap but the
# log line should not repeat per run
_enabled_dir: str | None = None


def compile_cache_dir(home: str | None = None) -> str | None:
    """Resolve the cache directory: env override > ``$TESTGROUND_HOME``
    layout > default home (``~/testground``). None means disabled."""
    env = os.environ.get("TESTGROUND_COMPILE_CACHE", "")
    if env:
        return None if env.lower() in _DISABLE else env
    if not home:
        home = os.environ.get("TESTGROUND_HOME") or os.path.join(
            os.path.expanduser("~"), "testground"
        )
    return os.path.join(home, "data", "compile-cache")


def enable_compile_cache(home: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at the testground home.

    Safe to call repeatedly and before/after backend init (it only sets
    config flags). The thresholds are zeroed so every program is cached —
    the sim tick program is the artifact we care about, but small helper
    jits cost nothing to keep and make warm processes fully warm. Returns
    the active directory, or None when disabled."""
    global _enabled_dir
    d = compile_cache_dir(home)
    if d is None or d == _enabled_dir:
        return d
    import jax

    try:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        # cache everything: the default 1 s / 0-byte floors would skip the
        # small programs the test suite compiles
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax binds its cache object at the FIRST compile after backend
        # init; a config change alone is ignored past that point. Any
        # jit may have run before this call — the runner healthcheck's
        # mesh probe compiles before the executor enables the cache, so
        # without an explicit rebind a daemon-served run never touched
        # the persistent cache at all (observed: zero cache events
        # through the CLI path while the direct path hit). Rebind
        # unconditionally — reset_cache() is cheap and next use binds
        # to the directory just configured.
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — caching is an optimization, never fatal
        return None
    _enabled_dir = d
    _register_cache_listener()
    return d


# ------------------------------------------------- cache observability
# jax emits monitoring events for persistent-cache traffic; counting
# them is the reliable hit/miss signal (wall-clock ratios are flaky —
# the compile-cache tests learned this in PR 3). The executor reads the
# deltas around a run's first dispatch to journal whether a bucketed
# program was served warm (``sim.bucket.compile_cache``) — the signal
# behind the tg_compile_bucket_hit/_miss Prometheus counters.

_cache_events = {"hits": 0, "misses": 0}
_listener_on = False


def _on_cache_event(event: str, **kw) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        _cache_events["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        _cache_events["misses"] += 1


def _register_cache_listener() -> None:
    global _listener_on
    if _listener_on:
        return
    try:
        import jax

        jax.monitoring.register_event_listener(_on_cache_event)
        _listener_on = True
    except Exception:  # noqa: BLE001 — observability only
        pass


def cache_event_counts() -> dict:
    """Cumulative persistent-cache hit/miss event counts for this
    process (zeros until :func:`enable_compile_cache` registered the
    listener). Read a delta around a compile to classify it."""
    return dict(_cache_events)
