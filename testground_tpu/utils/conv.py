"""Value conversions (``pkg/conv/conversions.go``)."""

from __future__ import annotations

import json
from typing import Any

__all__ = [
    "env_list_to_map",
    "infer_typed",
    "map_to_env_list",
    "parse_key_values",
]


def env_list_to_map(env: list[str]) -> dict[str, str]:
    """``["K=V", ...] -> {K: V}`` (``conversions.go:12-22``)."""
    out: dict[str, str] = {}
    for kv in env:
        k, _, v = kv.partition("=")
        out[k] = v
    return out


def map_to_env_list(m: dict[str, str]) -> list[str]:
    return [f"{k}={v}" for k, v in m.items()]


def infer_typed(v: str) -> Any:
    """Infer a typed value from a string: JSON literal if it parses, else the
    raw string (the reference's typed-map inference, ``conversions.go:24-50``)."""
    try:
        return json.loads(v)
    except (json.JSONDecodeError, ValueError):
        return v


def parse_key_values(pairs: list[str]) -> dict[str, Any]:
    """``["k=v", ...]`` with typed-value inference; used by CLI
    ``--run-param``/``--build-param`` style flags."""
    out: dict[str, Any] = {}
    for kv in pairs:
        k, _, v = kv.partition("=")
        out[k] = infer_typed(v)
    return out
