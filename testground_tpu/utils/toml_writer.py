"""Minimal TOML emitter.

The stdlib ships ``tomllib`` (read-only); this module provides the write half
needed for persisting compositions (``pkg/api/composition.go:440-459``) and
``--write-artifacts`` round-trips. Supports the subset of TOML the framework
emits: tables, arrays of tables, inline scalars, lists, and nested dicts.
Round-trips with ``tomllib.loads``.
"""

from __future__ import annotations

from typing import Any

__all__ = ["dumps"]


def _format_scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        escaped = (
            v.replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
            .replace("\t", "\\t")
            .replace("\r", "\\r")
        )
        return f'"{escaped}"'
    if isinstance(v, list):
        return "[" + ", ".join(_format_scalar(x) for x in v) + "]"
    raise TypeError(f"cannot TOML-encode value of type {type(v)!r}: {v!r}")


def _needs_quoting(key: str) -> bool:
    return not key.replace("-", "").replace("_", "").isalnum() or key == ""


def _format_key(key: str) -> str:
    return _format_scalar(key) if _needs_quoting(key) else key


def _is_table_array(v: Any) -> bool:
    return (
        isinstance(v, list) and len(v) > 0 and all(isinstance(x, dict) for x in v)
    )


def _emit(d: dict, prefix: list[str], lines: list[str]) -> None:
    scalars = {
        k: v for k, v in d.items() if not isinstance(v, dict) and not _is_table_array(v)
    }
    tables = {k: v for k, v in d.items() if isinstance(v, dict)}
    table_arrays = {k: v for k, v in d.items() if _is_table_array(v)}

    for k, v in scalars.items():
        lines.append(f"{_format_key(k)} = {_format_scalar(v)}")

    for k, v in tables.items():
        path = prefix + [k]
        lines.append("")
        lines.append("[" + ".".join(_format_key(p) for p in path) + "]")
        _emit(v, path, lines)

    for k, arr in table_arrays.items():
        path = prefix + [k]
        for item in arr:
            lines.append("")
            lines.append("[[" + ".".join(_format_key(p) for p in path) + "]]")
            _emit(item, path, lines)


def dumps(d: dict) -> str:
    lines: list[str] = []
    _emit(d, [], lines)
    return "\n".join(lines).lstrip("\n") + "\n"
