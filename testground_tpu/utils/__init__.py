"""Small shared utilities: TOML emission, value conversions, tar streams.

Twin of the reference's ``pkg/conv`` plus the TOML-encode half of
BurntSushi/toml that the stdlib lacks.
"""
