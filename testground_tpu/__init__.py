"""testground-tpu: a TPU-native platform for testing, benchmarking, and
simulating distributed and p2p systems at scale.

This framework keeps the contracts of the reference Testground platform
(composition TOML, test-plan manifests, the run/build/collect CLI and task
engine, the Signal/Barrier/Publish coordination primitives, per-link
latency/bandwidth/jitter/loss shaping) and executes test plans either as:

- real host processes (the ``local:exec`` runner, like the reference's
  ``pkg/runner/local_exec.go``), or
- a vectorized discrete-event network simulation on TPU (the ``sim:jax``
  runner): each instance's main loop is lifted with ``jax.vmap``, sync
  primitives lower to ``jax.lax.psum``/``all_gather`` over a device mesh, and
  link policies become per-instance/per-rule state tensors stepped each tick,
  so one chip hosts thousands of simulated peers.

Layer map (mirrors reference SURVEY.md §1):
    cli -> client -> daemon -> engine -> {builders, runners} -> sdk/sync/sim
"""

__version__ = "0.1.0"
