# Developer entry points (the reference's Makefile analog: tidy/build/
# test-go/integration targets become pytest tiers + the bench).

PY ?= python

.PHONY: test test-tier1 test-kernel test-e2e bench dryrun \
	telemetry-smoke chaos-smoke trace-smoke fleet-smoke perf-smoke slo-smoke \
	phases-smoke checkpoint-smoke preempt-smoke crosshost-smoke \
	pack-smoke sync-fanin-smoke transport-smoke check-smoke \
	netmap-smoke diff-smoke mesh-smoke check-plans test-sync-tsan

# the full ladder (SURVEY.md §4): unit + sim kernel + daemon/CLI e2e.
# pyproject addopts applies --durations=15 to every invocation, keeping
# the wall-clock hogs visible: the tier-1 CI budget is a hard 870s
# cutoff, so any test creeping past ~20s must be caught and marked
# @pytest.mark.slow (excluded by the tier-1 invocation below) before
# it eats the budget.
test:
	$(PY) -m pytest tests/ -q

# exactly what the tier-1 gate runs (ROADMAP.md): slow-marked tests are
# excluded so the suite fits the 870s budget
test-tier1:
	$(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors

# fast unit tier only (no engine/e2e; ~seconds)
test-kernel:
	$(PY) -m pytest tests/test_composition.py tests/test_preparation.py \
		tests/test_manifest.py tests/test_config.py tests/test_template.py \
		tests/test_rpc.py tests/test_toml_writer.py tests/test_engine.py -q

# the integration tier: real processes + daemon + cross-runner
test-e2e:
	$(PY) -m pytest tests/test_local_exec.py tests/test_daemon.py \
		tests/test_cli_e2e.py tests/test_integration_scenarios.py \
		tests/test_cross_runner.py -q

# headline numbers on the local accelerator (one JSON line)
bench:
	$(PY) bench.py

# telemetry-plane contract check (docs/OBSERVABILITY.md): a tiny run with
# telemetry on must produce a non-empty, schema-valid sim_timeseries.jsonl
# whose per-tick sums equal the journal's cumulative totals
telemetry-smoke:
	$(PY) tools/telemetry_smoke.py

# fault-plane contract check (docs/FAULTS.md): the plans/chaos
# composition (crash-mid-barrier + link flap + partition-and-heal) must
# complete on CPU with the declared fault counters, the chaos
# flow-conservation identity exact (sent = delivered + in-flight +
# dropped + rejected + fault_dropped), and a deterministic per-tick
# counter stream across two runs
chaos-smoke:
	$(PY) tools/chaos_smoke.py

# flight-recorder + latency-histogram contract check
# (docs/OBSERVABILITY.md): the plans/chaos smoke composition with
# [global.run.trace] must record the scheduled chaos per instance
# (crash/restart transitions, fault_dropped send fates), export a valid
# Perfetto trace_events.json, journal conserving per-group latency
# percentiles, and stay deterministic across two runs
trace-smoke:
	$(PY) tools/trace_smoke.py

# control-plane observability contract (docs/OBSERVABILITY.md "Control
# plane"): a traced submit must export a single connected lifecycle
# span tree (+ Perfetto mirror), journal the lifecycle in causal order
# with trace ids, conserve Σ tg_fleet_tasks against the task store,
# and render the tg top fleet view
fleet-smoke:
	$(PY) tools/fleet_smoke.py

# performance-ledger contract check (docs/OBSERVABILITY.md): a tiny run
# must journal sim.perf (AOT lower/compile split + cost analysis +
# throughput gauges), write a schema-valid sim_perf.jsonl whose
# per-chunk walls sum to the ledger's execute wall, and conserve the
# chunk/tick accounting
perf-smoke:
	$(PY) tools/perf_smoke.py

# run-health-plane contract check (docs/OBSERVABILITY.md "Run health
# plane"): the chaos smoke composition's warn-severity SLO must breach
# deterministically and be recorded (journal + sim_slo.jsonl + stats
# table) without failing the run; the same rule at severity=fail must
# cancel the run with a typed SloBreachError whose archived journal
# keeps the telemetry record; SLOs without telemetry refuse loudly
slo-smoke:
	$(PY) tools/slo_smoke.py

# phase-attribution contract check (docs/OBSERVABILITY.md "Phase
# attribution"): a tiny run with phases=true must journal sim.phases
# (one cost row per compiled-in tick phase + the explicit residual and
# whole-program rows, Σ phases + residual == whole by construction),
# stamp every phase with a measured ms/tick (phases_measure), mirror
# the rows to sim_phases.jsonl, and export tg_phase_* gauges
phases-smoke:
	$(PY) tools/phases_smoke.py

# checkpoint/resume contract check (docs/CHECKPOINT.md): the chaos
# smoke composition checkpointed every chunk, interrupted at tick 32
# mid-fault-schedule, then resumed, must journal IDENTICAL ticks/flow/
# fault/SLO totals and byte-equal telemetry + SLO streams vs an
# uninterrupted run; retention bounded to checkpoint_keep; a truncated
# snapshot refuses loudly with the typed CheckpointError
checkpoint-smoke:
	$(PY) tools/checkpoint_smoke.py

# fleet-controller preemption contract (docs/FLEET.md) against a real
# daemon subprocess: POST /preempt live-migrates a running task
# (checkpoint at the next chunk boundary, requeue, auto-resume) to a
# bit-equal completion; a priority-5 arrival evicts the busy priority-0
# run; a composition tg check rejects is refused at submit with the
# rule ids; SIGTERM drains (checkpoint + requeue + daemon.drain + exit
# 0) and a restarted daemon resumes the interrupted task bit-equal;
# tg_fleet_preemptions/evictions/refused_total exported
preempt-smoke:
	$(PY) tools/preempt_smoke.py

# cross-host control-plane contract check (docs/CROSSHOST.md): a
# two-"host" ping-pong with instances split across engine-less process
# groups joining the sync service purely by address (both backends, with
# a mid-run partition/reconnect round), then the 3-"host" chaos cohort —
# member-death (occupancy evicted, survivors complete), sync-partition-
# and-heal (barrier re-armed, subscription resumed), leader-death (one-
# line clean member exit, no LOG(FATAL)) — journaled per event; < 60 s
crosshost-smoke:
	$(PY) tools/crosshost_smoke.py

# multi-tenant serving contract check (PERF.md "Serving: buckets +
# packing"): warm the bucket ladder once (`tg build --buckets`
# semantics, pack widths included), then 8 concurrent small runs at
# DIFFERENT instance counts against one engine must report zero cold
# compiles (sim.bucket.compile_cache == hit for every run), execute as
# ONE width-8 vmapped pack, keep exact-N all-success results, and beat
# N/2 × the isolated single-run throughput in aggregate
pack-smoke:
	$(PY) tools/pack_smoke.py

# sync-plane stats contract check (docs/OBSERVABILITY.md "Sync plane"):
# ~200 concurrent clients against BOTH sync backends must conserve
# stats exactly (Σ server op counters == client-side op count), answer
# the wire-versioned sync_stats v2 shape, pass a 1k-client fan-in rung
# through the real bench machinery (the event-loop rewrite's mid-scale
# tripwire), reconcile a live `tg sync-service --metrics-port` scrape
# with a `tg sync-stats` snapshot, log the heartbeat line, and keep the
# always-on instrumentation overhead sane; the full 1k-10k fan-in ramp
# stays manual (tools/bench_sync_fanin.py, PERF.md "Sync fan-in (r2)")
sync-fanin-smoke:
	$(PY) tools/sync_fanin_smoke.py

# the transport=auto cost model + segmented pallas commit kernel
# (PERF.md "Pallas transport kernels"): contrasting shapes must pick
# BOTH backends in interpret scoring, an auto run must journal
# sim.transport (stats line + tg_transport_resolved gauge), and the
# two backends must agree bit-for-bit on a tile-spanning stream —
# part of the observability-smoke CI set
transport-smoke:
	$(PY) tools/transport_smoke.py

# the sharded serving plane (PERF.md "Sharded serving plane"): two
# tenants bucketed + packed on a 4-virtual-device mesh through the
# real CLI path with transport=auto must journal sim.mesh + a scored
# decision (stats mesh line, tg_mesh_shards gauge, mesh label) and
# keep every flow total bit-equal to unmeshed, unpacked solo runs —
# part of the observability-smoke CI set
mesh-smoke:
	$(PY) tools/mesh_smoke.py

# static-analysis plane contract check (docs/CHECKING.md): a clean
# composition checks to zero findings / exit 0; a seeded-bad one
# combining four incompatible knobs reports EVERY violation in one
# pass with stable rule ids / exit 1; the deliberately-broken fixture
# plan fires the eval_shape/jaxpr lints (traced-count contract, host
# callback); a pack-opted solo run journals sim.pack.solo_reason and
# `tg stats` renders it
check-smoke:
	$(PY) tools/check_smoke.py

# network-topology plane end to end (docs/OBSERVABILITY.md "Traffic
# matrix"): a daemon-served clustered composition (two isolated
# ping-pong pairs) with netmatrix=true must journal an exactly-
# reconciling sim.net_matrix block, stream/serve sim_netmatrix.jsonl,
# render the `tg netmap` heatmap through the real CLI, have
# `tg netmap --cut 2` recover the cluster split at zero cut bytes,
# and keep the Prometheus tg_net_pair_* series top-K bounded —
# part of the observability-smoke CI set
netmap-smoke:
	$(PY) tools/netmap_smoke.py

# differential run analysis + bench sentinel end to end
# (docs/OBSERVABILITY.md "Run diff / bench sentinel"): two
# identically-seeded daemon runs must diff CLEAN through the real
# `tg diff` CLI (exact counter equality, zero findings, zero
# significant throughput verdicts), a debug_chunk_sleep_ms-slowed run
# must be flagged `regressed` with a significant Mann–Whitney p-value,
# and the bench sentinel must round-trip: a tiny `bench.py --bank` run
# passes tools/bench_regression.py against the committed
# BENCH_HISTORY.jsonl baseline while a fabricated 3x-slower row fails
# it — part of the observability-smoke CI set
diff-smoke:
	$(PY) tools/diff_smoke.py

# `tg check` over every checked-in composition: the gallery's
# pre-lint gate (docs/CHECKING.md) — any error-severity finding in a
# composition under plans/*/_compositions/ fails the build, plan
# lints included
check-plans:
	$(PY) -m testground_tpu.cli.main check --trace-plans \
		plans/*/_compositions/*.toml

# the sync test suites against a ThreadSanitizer-instrumented native
# server build (docs/CHECKING.md "Sanitizer builds"): any data race in
# syncsvc.cc aborts the server (halt_on_error) and fails the suite;
# suppressions live in testground_tpu/native/tsan.supp (checked in,
# kept empty). `-k native` gates to the native-backend parametrizations
# — the python server has no TSAN surface.
test-sync-tsan:
	TG_NATIVE_SANITIZE=thread $(PY) -m pytest tests/test_sync.py \
		tests/test_sync_hardening.py tests/test_sync_backpressure.py \
		-q -k native

# the multi-chip compile/correctness gate on a virtual 8-device mesh
dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
