"""Headline benchmark: simulated peers × ticks per second.

Runs the ``benchmarks/pingpong-flood`` sim plan — every instance sustaining
shaped round-trip traffic — at BASELINE.md's north-star scale (100k
simulated instances, 10k ticks) on the available accelerator and reports

    {"metric": "sim_peer_ticks_per_sec", "value": ..., "unit": ...,
     "vs_baseline": ...}

vs_baseline is measured throughput over the north-star requirement
(100_000 peers × 10_000 ticks / 60 s): ≥1.0 means the <60 s target is met.
The reference's own envelope for a single host is 2–300 real instances
(README.md:136-139); every instance here exchanges real (simulated-network)
messages with link shaping, sync counters live, at 100k instances.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_PEER_TICKS_PER_SEC = 100_000 * 10_000 / 60.0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--instances", type=int, default=100_000)
    p.add_argument("--ticks", type=int, default=10_000)
    p.add_argument("--chunk", type=int, default=500)
    p.add_argument("--latency-ms", type=int, default=4)
    args = p.parse_args()

    import jax

    from testground_tpu.api import RunGroup
    from testground_tpu.sim.engine import SimProgram, build_groups
    from testground_tpu.sim.executor import load_sim_testcases

    n, ticks = args.instances, args.ticks
    tc = load_sim_testcases(os.path.join(REPO, "plans", "benchmarks"))[
        "pingpong-flood"
    ]()
    groups = build_groups(
        [
            RunGroup(
                id="all",
                instances=n,
                parameters={
                    "duration_ticks": str(ticks + args.chunk + 1),
                    "latency_ms": str(args.latency_ms),
                },
            )
        ]
    )
    devs = jax.devices()
    mesh = None
    if len(devs) > 1:
        import numpy as np

        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
    prog = SimProgram(
        tc,
        groups,
        test_plan="benchmarks",
        test_case="pingpong-flood",
        tick_ms=1.0,
        mesh=mesh,
        chunk=args.chunk,
    )

    print(
        f"# bench: {n} instances × {ticks} ticks on "
        f"{jax.default_backend()} ({len(devs)} device(s))",
        file=sys.stderr,
    )
    import numpy as np_

    carry = jax.jit(lambda: prog.init_carry(0))()
    fn = prog.compiled_chunk()
    carry, done = fn(carry)  # compile + warm one chunk
    _ = np_.asarray(carry.t)  # hard sync: D2H forces completion
    print("# warmup chunk done; timing...", file=sys.stderr)

    t0 = time.perf_counter()
    run_ticks = 0
    while run_ticks < ticks:
        carry, done = fn(carry)
        run_ticks += args.chunk
    _ = np_.asarray(carry.t)  # hard sync (block_until_ready may not block
    # on remotely-tunneled backends)
    wall = time.perf_counter() - t0

    value = n * run_ticks / wall
    print(
        f"# {run_ticks} ticks in {wall:.2f}s wall "
        f"({run_ticks / wall:.1f} ticks/s)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "sim_peer_ticks_per_sec",
                "value": round(value, 1),
                "unit": "peer*ticks/s (pingpong-flood @ %dk peers)"
                % (n // 1000),
                "vs_baseline": round(value / BASELINE_PEER_TICKS_PER_SEC, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
