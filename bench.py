"""Headline benchmark: simulated peers × ticks per second.

Four workloads, one JSON line:

- **primary — full path**: ``network/pingpong-sustained`` at 100k
  instances × 10k ticks. The general transport with NO fast-path
  shortcuts: sorted slot assignment, sender-provenance plane, cross-tick
  occupancy stacking, 7 of 8 LinkShape features compiled in (all but
  duplicate-shaping), live sync counters signalled every round, and a
  dynamic latency reshape mid-run. ``vs_baseline`` compares against the
  north-star requirement (100_000 peers × 10_000 ticks / 60 s =
  16.7M peer·ticks/s, defined for a **v4-8 = 4 chips**);
  ``vs_baseline_per_chip`` normalizes both sides by chip count — the
  apples-to-apples reading when this host exposes a single chip.
- **fast path**: ``benchmarks/pingpong-flood`` — the stripped pairwise
  transport (direct slots, latency-only), same scale.
- **storm**: ``benchmarks/storm`` at 100k — gossip flood over a random
  5-out graph (BASELINE config 5; multi-message fan-in on the sorted
  path). The reference's own envelope is 2–300 real instances per host
  (README.md:136-139); no single-host reference baseline exists at 100k.

  Workload-shape note for cross-round comparison: as of round 3 flood
  and storm pack kind+counter into ONE payload word (MSG_WIDTH 2→1;
  receivers never read word 1) and storm narrows OUT_MSGS to the actual
  fan-out — BENCH_r01/r02 flood/storm numbers were measured on the
  wider shapes. The PRIMARY full-path metric is unchanged in shape
  across rounds.
- **correctness checkpoint**: ``network/ping-pong`` (the actual
  reference testcase, RTT windows + mid-run reshape) run at 100k to
  completion — reported as ok-instance count and wall seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_PEER_TICKS_PER_SEC = 100_000 * 10_000 / 60.0
BASELINE_CHIPS = 4  # the north-star metric is defined on a v4-8


# The ONE place each bench workload's program shape lives (VERDICT r5
# weak #1): the timed benches and the `--build` precompile pass must
# compile the IDENTICAL program, or the cache warm is a lie. BENCH_r05
# showed exactly that lie's cost: the full path rode the driver's
# `tg build` (a composition) to a +5.1 s cache hit while flood paid
# +54.6 s cold — flood/storm/ping-pong are bench-private shapes no
# build task ever compiled.
BENCH_WORKLOADS = ("sustained", "flood", "storm", "pingpong")


def _bench_shape(name, n, ticks):
    """(plan, case, params, chunk) for one bench workload at (n, ticks)."""
    if name == "sustained":
        return (
            "network",
            "pingpong-sustained",
            {
                "duration_ticks": str(10 * ticks),
                "latency_ms": "4",
                "latency2_ms": "2",
                "reshape_every": "1000",
            },
            250,
        )
    if name == "flood":
        return (
            "benchmarks",
            "pingpong-flood",
            {"duration_ticks": str(10 * ticks), "latency_ms": "4"},
            500,
        )
    if name == "storm":
        return (
            "benchmarks",
            "storm",
            {
                "conn_outgoing": "5",
                "conn_delay_ticks": "32",
                "data_size_kb": "512",
            },
            64,
        )
    if name == "pingpong":
        return (
            "network",
            "ping-pong",
            {
                "latency_ms": "100",
                "latency2_ms": "10",
                "tolerance_ms": "15",
            },
            64,
        )
    raise KeyError(f"unknown bench workload {name!r}")


# what a real TPU core's VMEM affords the commit kernel's row blocks —
# the REMAINING envelope bound after the segmented kernel removed the
# whole-stream term (PERF.md "Pallas transport kernels"); overridable
# for parts with more on-chip memory
try:
    _PALLAS_VMEM_BUDGET = int(
        os.environ.get("TG_PALLAS_VMEM_BUDGET", "") or 0
    ) or 16 * 2**20
except ValueError:  # malformed override must not kill xla-only benches
    _PALLAS_VMEM_BUDGET = 16 * 2**20


def _workloads_for(transport, n, only=None):
    """The bench workloads a (transport, n) pair can actually compile.
    The segmented commit kernel (ISSUE 14) removed the whole-stream
    VMEM cap that used to exclude storm under pallas outright; what
    remains is the per-bucket ROW footprint (N·SLOTS-scaled), checked
    here against the real-chip VMEM budget so an over-envelope rung is
    skipped loudly instead of Mosaic-failing mid-bench and losing the
    run's result JSON. Interpret mode (no real TPU) has no envelope —
    nothing is skipped there."""
    names = [w for w in BENCH_WORKLOADS if only is None or w in only]
    if transport == "pallas" and "storm" in names:
        import jax

        from testground_tpu.sim.pallas_transport import commit_vmem_bytes

        # storm statics: SLOTS = IN_MSGS = 16, W = 1, bool occupancy
        # (TRACK_SRC = False), no etick in the bench programs
        need = commit_vmem_bytes(n, 16, 1, occ_bool=True)
        if jax.default_backend() == "tpu" and need > _PALLAS_VMEM_BUDGET:
            names.remove("storm")
            print(
                f"# storm: skipped under transport=pallas @ {n} "
                f"instances (row blocks need ~{need / 2**20:.0f} MiB "
                f"of the {_PALLAS_VMEM_BUDGET / 2**20:.0f} MiB VMEM "
                "budget; see PERF.md 'Pallas transport kernels')",
                file=sys.stderr,
            )
    return names


def build_bench_programs(n, ticks, transport="xla", only=None, mesh_shape=""):
    """`tg build` for the bench surface: trace + compile EVERY bench
    workload's program into the persistent compile cache, so a
    driver-fresh timed bench is a pure cache read for every workload —
    not just the full path. Walks the same sequence the sim:plan
    precompile walks (init + chunk execution; a second dispatch under a
    mesh lands the GSPMD fixed-point variant too)."""
    import jax
    import numpy as np

    walls = {}
    for name in _workloads_for(transport, n, only):
        plan, case, params, chunk = _bench_shape(name, n, ticks)
        prog = _build(
            plan, case, n, params, chunk, transport, mesh_shape=mesh_shape
        )
        t0 = time.perf_counter()
        carry = jax.jit(lambda: prog.init_carry(0))()  # noqa: B023
        fn = prog.compiled_chunk()
        carry = fn(carry)[0]
        if prog.mesh is not None:
            carry = fn(carry)[0]  # the sharding fixed-point retrace
        np.asarray(carry.t)  # force completion
        walls[name] = round(time.perf_counter() - t0, 2)
        print(
            f"# build[{name}]: traced+compiled+1 chunk in "
            f"{walls[name]}s",
            file=sys.stderr,
        )
    return walls


def build_bucket_programs(n, ticks, ladder=None, only=None):
    """``--build --buckets``: the `tg build --buckets` parity pass for
    the bench surface — precompile the canonical shape-bucket ladder
    (sim/buckets.py) for each bench workload, emitting per-bucket
    compile walls, so a bucketed serving daemon on this machine answers
    ANY instance count warm. Rungs below the bench's own ``n`` are
    warmed too (that is the point: small tenant runs), rungs above it
    are skipped unless they hold it."""
    import jax
    import numpy as np

    from testground_tpu.sim.buckets import parse_ladder, plan_buckets

    ladder = parse_ladder(ladder)
    walls = {}
    for name in _workloads_for("xla", n, only):
        plan, case, params, chunk = _bench_shape(name, n, ticks)
        for rung in ladder:
            bp = plan_buckets([min(n, rung)], rung, (rung,))
            if bp is None:
                continue
            t0 = time.perf_counter()
            try:
                prog = _build(
                    plan,
                    case,
                    rung,
                    params,
                    chunk,
                    "xla",
                    live_counts=bp.live_counts,
                )
                carry = jax.jit(
                    lambda s, lc: prog.init_carry(s, lc)  # noqa: B023
                )(np.int32(0), np.asarray(bp.live_counts, np.int32))
                carry = prog.compiled_chunk()(carry)[0]
                np.asarray(carry.t)
            except Exception as e:  # noqa: BLE001 — per-rung best-effort
                print(
                    f"# build[{name}@bucket{rung}]: skipped ({e})",
                    file=sys.stderr,
                )
                continue
            secs = round(time.perf_counter() - t0, 2)
            walls[f"{name}@bucket{rung}"] = secs
            print(
                f"# build[{name}@bucket{rung}]: traced+compiled+1 chunk "
                f"in {secs}s",
                file=sys.stderr,
            )
    return walls


def _build(
    plan,
    case,
    n,
    params,
    chunk,
    transport="xla",
    live_counts=None,
    mesh_shape="",
):
    from testground_tpu.api import RunGroup
    from testground_tpu.sim.engine import SimProgram, build_groups
    from testground_tpu.sim.executor import (
        instantiate_testcase,
        load_sim_testcases,
    )

    factory = load_sim_testcases(os.path.join(REPO, "plans", plan))[case]
    groups = build_groups(
        [RunGroup(id="all", instances=n, parameters=params)]
    )
    tc = instantiate_testcase(factory, groups, tick_ms=1.0)
    import jax
    import numpy as np

    devs = jax.devices()
    if mesh_shape:
        # an explicit --mesh rung (sim/meshplan.py): the layout applies
        # to EVERY arm, pallas included — the shard_map commit variant
        # is what a meshed A/B round measures. Divisibility failures
        # surface as the engine's own loud refusal, not a silent skip.
        from testground_tpu.sim.meshplan import make_mesh

        mesh = make_mesh(mesh_shape)
    else:
        # default ladder, unchanged since r01: shard over every visible
        # device under xla; transport=pallas single-device (the A/B
        # rounds compare one chip's hot path), bucketed builds too
        mesh = (
            jax.sharding.Mesh(np.asarray(devs), ("i",))
            if len(devs) > 1
            and transport != "pallas"
            and live_counts is None
            else None
        )
    return SimProgram(
        tc,
        groups,
        test_plan=plan,
        test_case=case,
        tick_ms=1.0,
        mesh=mesh,
        chunk=chunk,
        transport=transport,
        live_counts=live_counts,
    )


def _timed_ticks(prog, ticks, ledger=None):
    """Warm one chunk (compile excluded from the throughput number but
    REPORTED — the north star says wall-clock, so the one-off cost must
    be visible), run ~`ticks` more, and return (carry, actual_ticks,
    wall, compile_secs). Actual ticks come from the carry's tick counter,
    which stops advancing once every instance is terminal — a workload
    finishing mid-chunk is not credited for no-op ticks.

    ``ledger`` is an optional sim.perf.PerfLedger: each dispatch's wall
    lands in it so the bench emits the SAME per-chunk ledger schema as
    a framework run's journal (chunk 0 carries compile, exactly like
    the executor's first dispatch)."""
    import jax
    import numpy as np

    tc0 = time.perf_counter()
    carry = jax.jit(lambda: prog.init_carry(0))()
    fn = prog.compiled_chunk()
    t_chunk = time.perf_counter()
    carry, _ = fn(carry)
    # D2H forces completion on remotely-tunneled backends where
    # block_until_ready may not block
    warm_t = int(np.asarray(carry.t))
    now = time.perf_counter()
    # compile_secs = init trace/compile + first chunk trace/compile/run;
    # the warm chunk's execution (~chunk ticks of steady-state work) is
    # inside it, so this slightly OVERstates pure compilation — the
    # honest direction for a "wall-clock includes compile" claim
    compile_secs = now - tc0
    if ledger is not None:
        ledger.on_chunk(0, prog.chunk, prog.chunk, now - t_chunk)
    t0 = time.perf_counter()
    dispatched = 0
    index = 1
    while dispatched < ticks:
        t_chunk = time.perf_counter()
        carry, done = fn(carry)
        done_host = bool(done)
        dispatched += prog.chunk
        if ledger is not None:
            ledger.on_chunk(
                index,
                prog.chunk + dispatched,
                prog.chunk,
                time.perf_counter() - t_chunk,
            )
        index += 1
        if done_host:
            break
    run_ticks = int(np.asarray(carry.t)) - warm_t
    return carry, run_ticks, time.perf_counter() - t0, compile_secs


def bench_sustained(n, ticks, transport="xla", mesh_shape=""):
    from testground_tpu.sim.perf import PerfLedger

    plan, case, params, chunk = _bench_shape("sustained", n, ticks)
    prog = _build(
        plan, case, n, params, chunk, transport=transport,
        mesh_shape=mesh_shape,
    )
    import jax

    # the ledger makes bench emit the exact journal sim.perf schema, so
    # BENCH_r*.json and `tg perf --compare` read both interchangeably;
    # on a mesh the second dispatch carries the sharding fixed-point
    # retrace (engine.run), so it too sits outside the steady window
    ledger = PerfLedger(
        n,
        prog.chunk,
        aot=False,
        warmup=2 if prog.mesh is not None else 1,
        transport=transport,
    )
    carry, run_ticks, wall, compile_secs = _timed_ticks(prog, ticks, ledger)
    import numpy as np

    rounds = int(np.asarray(carry.states[0]["rounds"]).sum())
    print(
        f"# full path: {run_ticks} ticks in {wall:.2f}s "
        f"(+{compile_secs:.1f}s compile; {rounds} total rounds exchanged)",
        file=sys.stderr,
    )
    # warm-rerun probe: a FRESH jit of the same program (what a new run
    # of this composition compiles) against the now-populated persistent
    # cache — trace/lower + cache read instead of XLA compile. The
    # wrapper def (a) is a distinct callable, so jit's in-process trace
    # cache cannot shortcut the re-trace a new process would pay, and
    # (b) keeps __name__ = "_chunk_step", so the HLO module sym_name —
    # part of the persistent cache key — matches the cold entry.
    import jax

    def _chunk_step(c):
        return prog._chunk_step(c)

    from testground_tpu.sim.perf import timed_lower_compile

    # ledger compile block: the warm split (trace/lower vs persistent-
    # cache read) plus XLA's cost/memory analysis of one chunk program
    lower_secs, xla_secs, compiled = timed_lower_compile(
        jax.jit(_chunk_step, donate_argnums=0), carry
    )
    warm_compile_secs = lower_secs + xla_secs
    ledger.on_compile(lower_secs, xla_secs, compiled)
    del compiled
    print(
        f"# warm recompile (persistent cache): {warm_compile_secs:.1f}s "
        f"vs {compile_secs:.1f}s cold",
        file=sys.stderr,
    )
    return n * run_ticks / wall, compile_secs, warm_compile_secs, ledger.summary()


def bench_flood(n, ticks, transport="xla", mesh_shape=""):
    plan, case, params, chunk = _bench_shape("flood", n, ticks)
    prog = _build(
        plan, case, n, params, chunk, transport=transport,
        mesh_shape=mesh_shape,
    )
    _, run_ticks, wall, compile_secs = _timed_ticks(prog, ticks)
    print(
        f"# fast path: {run_ticks} ticks in {wall:.2f}s "
        f"(+{compile_secs:.1f}s compile)",
        file=sys.stderr,
    )
    return n * run_ticks / wall, compile_secs


def bench_storm(n, transport="xla", mesh_shape=""):
    plan, case, params, chunk = _bench_shape("storm", n, 0)
    prog = _build(
        plan, case, n, params, chunk, transport=transport,
        mesh_shape=mesh_shape,
    )
    carry, run_ticks, wall, compile_secs = _timed_ticks(prog, 4096)
    import numpy as np

    ok = int((np.asarray(carry.status) == 1).sum())
    print(
        f"# storm: {run_ticks} ticks in {wall:.2f}s ({ok}/{n} ok, "
        f"+{compile_secs:.1f}s compile)",
        file=sys.stderr,
    )
    return n * run_ticks / wall, ok, compile_secs


def bench_pingpong_correctness(n, transport="xla", mesh_shape=""):
    plan, case, params, chunk = _bench_shape("pingpong", n, 0)
    prog = _build(
        plan, case, n, params, chunk, transport=transport,
        mesh_shape=mesh_shape,
    )
    import numpy as np

    carry, run_ticks, wall, compile_secs = _timed_ticks(prog, 2048)
    st = np.asarray(carry.status)
    ok = int((st == 1).sum())
    print(
        f"# ping-pong@{n}: {ok}/{n} ok in {wall:.2f}s post-compile "
        f"(+{compile_secs:.1f}s compile; {run_ticks} timed ticks, "
        "RTT windows asserted in sim time)",
        file=sys.stderr,
    )
    return ok, wall, compile_secs


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--instances", type=int, default=100_000)
    p.add_argument("--ticks", type=int, default=10_000)
    p.add_argument("--skip-secondary", action="store_true")
    # A/B gate for the hand-tiled transport kernels (PERF.md "Pallas
    # transport kernels"; tools/bench_pallas_transport.py is the
    # per-tick micro-harness) — pallas forces single-device programs
    p.add_argument(
        "--transport", choices=("xla", "pallas"), default="xla"
    )
    # explicit mesh rung (sim/meshplan.py): "4" = 4 peer shards, "2x4"
    # = runs x peers. Applies to every arm — pallas included (the
    # shard_map commit). Banked rows key the layout, so meshed and
    # unmeshed rungs never gate each other. Empty = the historical
    # default (1-D over all devices under xla, single-device pallas).
    p.add_argument("--mesh", default="")
    # `tg build` for the bench surface: compile every workload program
    # into the persistent cache and exit — a driver runs this once, and
    # the timed bench that follows is warm for EVERY workload (VERDICT
    # r5 weak #1). --only narrows to a comma-list of BENCH_WORKLOADS.
    p.add_argument("--build", action="store_true")
    p.add_argument("--only", default=None)
    # `tg build --buckets` parity (PERF.md "Serving: buckets +
    # packing"): with --build, additionally precompile the canonical
    # shape-bucket ladder for each workload so a serving daemon on this
    # machine answers ANY instance count warm; per-bucket compile walls
    # land in the emitted JSON. --bucket-ladder overrides the rungs.
    p.add_argument("--buckets", action="store_true")
    p.add_argument("--bucket-ladder", default=None)
    # phase attribution (sim/phases.py; docs/OBSERVABILITY.md "Phase
    # attribution"): emit the per-phase cost ledger of the full-path
    # program for THIS transport as a per-backend "phases" block in the
    # BENCH json — the programmatic per-op breakdown the PERF.md tables
    # were hand-transcribed from. --phase-reps > 0 adds the measured
    # ms/tick calibration (per-phase jit + K timed reps, post-bench).
    p.add_argument("--phases", action="store_true")
    p.add_argument("--phase-reps", type=int, default=0)
    # bench banking (analysis/bench_history.py; ROADMAP item 5's
    # "banked verdicts"): --bank appends this run's headline numbers as
    # ONE env-fingerprinted row (workload, rung, backend, jax version,
    # device kind, cpu count, git sha) to the append-only history file
    # tools/bench_regression.py gates on. --history overrides the
    # default repo-root BENCH_HISTORY.jsonl (tests/smokes bank to a
    # temp file).
    p.add_argument("--bank", action="store_true")
    p.add_argument(
        "--history",
        default=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl"
        ),
    )
    args = p.parse_args()

    # compiled programs are the framework's build artifact: warm processes
    # (and explicit `tg build` precompiles) read compiles from this cache
    from testground_tpu.utils.compile_cache import enable_compile_cache

    cache_dir = enable_compile_cache()
    print(f"# compile cache: {cache_dir or 'disabled'}", file=sys.stderr)

    import jax

    n, ticks = args.instances, args.ticks
    devs = jax.devices()
    print(
        f"# bench: {n} instances on {jax.default_backend()} "
        f"({len(devs)} device(s))"
        + (f", mesh {args.mesh}" if args.mesh else ""),
        file=sys.stderr,
    )

    if args.only and not args.build:
        print("--only is a --build option (it narrows the precompile "
              "pass, not the timed bench)", file=sys.stderr)
        return 2
    if args.build:
        only = set(args.only.split(",")) if args.only else None
        unknown = (only or set()) - set(BENCH_WORKLOADS)
        if unknown:
            print(f"unknown workloads: {sorted(unknown)}", file=sys.stderr)
            return 2
        walls = build_bench_programs(
            n, ticks, args.transport, only=only, mesh_shape=args.mesh
        )
        if args.buckets:
            walls.update(
                build_bucket_programs(
                    n, ticks, ladder=args.bucket_ladder, only=only
                )
            )
        print(json.dumps({"built": walls, "transport": args.transport}))
        return 0
    if args.buckets:
        print("--buckets is a --build option", file=sys.stderr)
        return 2

    full, full_compile, warm_compile, perf_block = bench_sustained(
        n, ticks, args.transport, mesh_shape=args.mesh
    )
    result = {
        "metric": "sim_peer_ticks_per_sec",
        "transport": args.transport,
        **({"mesh": args.mesh} if args.mesh else {}),
        "value": round(full, 1),
        "unit": "peer*ticks/s (full-path pingpong-sustained @ %dk peers)"
        % (n // 1000),
        "vs_baseline": round(full / BASELINE_PEER_TICKS_PER_SEC, 3),
        "vs_baseline_per_chip": round(
            (full / len(devs))
            / (BASELINE_PEER_TICKS_PER_SEC / BASELINE_CHIPS),
            3,
        ),
        "devices": len(devs),
        # one-off cost excluded from the throughput number above — the
        # north star is wall-clock, so report it alongside (VERDICT r3
        # weak #4). The persistent compile cache is wired above (and in
        # the executor + sim:plan precompile), so this drops to the
        # trace/lower+deserialize floor for any process after the first;
        # a driver-fresh bench run reports the cold number honestly.
        "compile_secs": round(full_compile, 2),
        # a fresh jit of the same program against the populated cache —
        # what any warm rerun of this composition pays instead of compile
        "warm_compile_secs": round(warm_compile, 2),
        # the run performance ledger (journal sim.perf schema —
        # docs/OBSERVABILITY.md): per-chunk-derived throughput, the
        # warm lower-vs-compile split, and XLA cost/memory analysis of
        # one chunk program; `tg perf --compare` diffs a task's ledger
        # against this line
        "perf": perf_block,
    }

    if args.phases:
        # per-backend phase attribution of the full-path program
        # (journal sim.phases schema), keyed by transport so merged
        # BENCH lines across A/B rounds nest consistently; the whole-
        # program cost is reused from the ledger's warm-recompile
        # harvest above (no extra compile)
        from testground_tpu.sim.phases import build_phase_ledger

        plan, case, params, chunk = _bench_shape("sustained", n, ticks)
        prog = _build(plan, case, n, params, chunk, args.transport)
        result["phases"] = {
            args.transport: build_phase_ledger(
                prog,
                whole=perf_block.get("compile"),
                measure=max(0, args.phase_reps),
            )
        }
        top = sorted(
            result["phases"][args.transport]["phases"],
            key=lambda r: r.get("bytes_accessed", 0.0) or 0.0,
            reverse=True,
        )
        print(
            "# phases[%s] (x of whole-program bytes/tick): %s"
            % (
                args.transport,
                ", ".join(
                    f"{r['phase']} x{r.get('bytes_frac', 0):.2f}"
                    for r in top[:4]
                ),
            ),
            file=sys.stderr,
        )

    if not args.skip_secondary:
        flood, flood_compile = bench_flood(
            n, ticks, args.transport, mesh_shape=args.mesh
        )
        pp_ok, pp_wall, pp_compile = bench_pingpong_correctness(
            n, args.transport, mesh_shape=args.mesh
        )
        result["secondary"] = {
            "flood_peer_ticks_per_sec": round(flood, 1),
            "flood_vs_baseline": round(
                flood / BASELINE_PEER_TICKS_PER_SEC, 3
            ),
            # per-workload compile cost (VERDICT r5 weak #1): a warm
            # persistent cache shows every workload at cache-hit levels;
            # a cold one names exactly which program paid XLA compile
            "flood_compile_secs": round(flood_compile, 2),
            "pingpong_100ms_ok": pp_ok,
            "pingpong_100ms_wall_secs": round(pp_wall, 2),
            "pingpong_100ms_compile_secs": round(pp_compile, 2),
        }
        if "storm" in _workloads_for(args.transport, n):
            storm, storm_ok, storm_compile = bench_storm(
                n, args.transport, mesh_shape=args.mesh
            )
            result["secondary"].update(
                storm_peer_ticks_per_sec=round(storm, 1),
                storm_ok=storm_ok,
                storm_compile_secs=round(storm_compile, 2),
            )
        else:
            result["secondary"]["storm_skipped"] = (
                "pallas VMEM envelope (sim/pallas_transport.py)"
            )

    print(json.dumps(result))

    if args.bank:
        from datetime import datetime, timezone

        from testground_tpu.analysis.bench_history import (
            bank_row,
            env_fingerprint,
        )

        # one row per banked workload: the sustained headline always,
        # plus a flood row when the secondary pass ran — each gates
        # independently under its own (workload, rung, backend,
        # transport) key
        fp = env_fingerprint()
        ts = datetime.now(timezone.utc).isoformat(timespec="seconds")
        rows = [
            {
                "ts": ts,
                "workload": "sustained",
                "instances": n,
                "ticks": ticks,
                "transport": args.transport,
                **({"mesh": args.mesh} if args.mesh else {}),
                "metric": result["metric"],
                "value": result["value"],
                "compile_secs": result["compile_secs"],
                "warm_compile_secs": result["warm_compile_secs"],
                "fingerprint": fp,
            }
        ]
        sec = result.get("secondary") or {}
        if sec.get("flood_peer_ticks_per_sec") is not None:
            rows.append(
                {
                    "ts": ts,
                    "workload": "flood",
                    "instances": n,
                    "ticks": ticks,
                    "transport": args.transport,
                    **({"mesh": args.mesh} if args.mesh else {}),
                    "metric": "sim_peer_ticks_per_sec",
                    "value": sec["flood_peer_ticks_per_sec"],
                    "compile_secs": sec.get("flood_compile_secs"),
                    "fingerprint": fp,
                }
            )
        for row in rows:
            bank_row(args.history, row)
        print(
            f"# banked {len(rows)} row(s) to {args.history}",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
