"""The exec edition of the network plan: REAL TCP ping-pong between real
processes with sync-service address exchange (BASELINE config 1 — network
ping-pong, 2 instances, local:exec)."""

import io
import re
import tarfile

import pytest

from testground_tpu.engine import Outcome
from testground_tpu.rpc import discard_writer

from tests.test_cross_runner import engine  # noqa: F401 — fixture reuse
from tests.test_local_exec import run_plan


class TestRealSocketPingPong:
    def test_two_instances(self, engine):  # noqa: F811
        t = run_plan(engine, "network", "ping-pong", instances=2)
        assert t.outcome() == Outcome.SUCCESS
        # the dialer measured real RTTs on a real socket
        buf = io.BytesIO()
        engine.do_collect_outputs("local:exec", t.id, buf, discard_writer())
        buf.seek(0)
        out = ""
        with tarfile.open(fileobj=buf, mode="r:gz") as tar:
            for m in tar.getmembers():
                if m.name.endswith("run.out"):
                    out += tar.extractfile(m).read().decode()
        rtts = re.findall(r"round \d rtt: ([0-9.]+) ms", out)
        assert len(rtts) == 2  # one dialer, two rounds
        assert all(float(ms) < 5000 for ms in rtts)

    def test_four_instances_two_pairs(self, engine):  # noqa: F811
        t = run_plan(engine, "network", "ping-pong", instances=4)
        assert t.outcome() == Outcome.SUCCESS

    def test_odd_count_solo_succeeds(self, engine):  # noqa: F811
        t = run_plan(engine, "network", "ping-pong", instances=3)
        assert t.outcome() == Outcome.SUCCESS

    @pytest.mark.slow  # 60-160s (200 real processes; load-sensitive):
    # past the tier-1 870s budget's ~20s per-test ceiling
    def test_local_envelope_200_instances(self, engine):  # noqa: F811
        """The reference's local-runner envelope is 2-300 REAL instances
        per host (``README.md:136-139``); run 200 real SDK processes —
        100 concurrent TCP pairs with sync-service address exchange and
        a 200-wide listening barrier — through the full local:exec
        runner path (rate-limited start, pretty events, outcome
        collection). The earlier 300-client stress hit the sync servers
        directly; this drives the whole runner at envelope scale."""
        t = run_plan(
            engine, "network", "ping-pong", instances=200, timeout=300
        )
        assert t.outcome() == Outcome.SUCCESS, t.error
        assert t.result["outcomes"]["all"] == {"ok": 200, "total": 200}

    def test_sim_only_case_fails_cleanly(self, engine):  # noqa: F811
        """Manifest-advertised cases without an exec edition fail with a
        clear pointer instead of crashing with exit 2."""
        t = run_plan(engine, "network", "traffic-allowed", instances=2)
        assert t.outcome() == Outcome.FAILURE
