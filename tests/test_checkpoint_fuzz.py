"""Property-based fuzz of the checkpoint archive: save → load must be
the identity for ARBITRARY carry pytrees (docs/CHECKPOINT.md satellite).

Two properties:

1. **Round trip**: any pytree of numpy leaves (mixed dtypes/shapes,
   nested dict/tuple/list containers, typed PRNG-key arrays sprinkled
   in) survives ``snapshot_carry`` → ``save_snapshot`` →
   ``load_snapshot`` leaf-for-leaf, dtype-exact, through the real
   on-disk archive.
2. **Damage refuses**: truncating the written archive at any byte
   offset (or flipping its magic) raises the typed
   :class:`CheckpointError` — a damaged snapshot must refuse loudly,
   never load garbage.

Gated on hypothesis like test_sync_fuzz / test_transport_fuzz /
test_chaos_fuzz."""

import os

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property-based tier needs hypothesis"
)
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

import jax  # noqa: E402

from testground_tpu.sim.checkpoint import (  # noqa: E402
    FORMAT_VERSION,
    CheckpointError,
    load_snapshot,
    save_snapshot,
    snapshot_carry,
)

_DTYPES = (np.int32, np.int64, np.float32, np.float64, np.uint8, np.bool_)


@st.composite
def leaf_arrays(draw):
    dtype = draw(st.sampled_from(_DTYPES))
    shape = tuple(
        draw(
            st.lists(
                st.integers(min_value=0, max_value=5),
                min_size=0,
                max_size=3,
            )
        )
    )
    if dtype == np.bool_:
        return np.asarray(
            draw(
                st.lists(
                    st.booleans(),
                    min_size=int(np.prod(shape, dtype=int)),
                    max_size=int(np.prod(shape, dtype=int)),
                )
            ),
            dtype=dtype,
        ).reshape(shape)
    info_ints = st.integers(min_value=-(2**31), max_value=2**31 - 1)
    floats = st.floats(
        allow_nan=False, allow_infinity=False, width=32
    )
    vals = draw(
        st.lists(
            floats if np.issubdtype(dtype, np.floating) else info_ints,
            min_size=int(np.prod(shape, dtype=int)),
            max_size=int(np.prod(shape, dtype=int)),
        )
    )
    return np.asarray(vals, dtype=dtype).reshape(shape)


@st.composite
def prng_leaves(draw):
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    n = draw(st.integers(min_value=1, max_value=4))
    key = jax.random.key(seed)
    return jax.random.split(key, n) if n > 1 else key


def leaves():
    return st.one_of(leaf_arrays(), prng_leaves())


def trees():
    return st.recursive(
        leaves(),
        lambda children: st.one_of(
            st.lists(children, min_size=1, max_size=3).map(tuple),
            st.dictionaries(
                st.text(
                    alphabet="abcdefgh", min_size=1, max_size=4
                ),
                children,
                min_size=1,
                max_size=3,
            ),
        ),
        max_leaves=8,
    )


class TestRoundTripFuzz:
    @settings(max_examples=25, deadline=None)
    @given(tree=trees(), tick=st.integers(min_value=0, max_value=10**9))
    def test_save_load_is_identity(self, tmp_path_factory, tree, tick):
        run_dir = str(tmp_path_factory.mktemp("ckpt"))
        leaves_in, metas = snapshot_carry(tree)
        manifest = {
            "version": FORMAT_VERSION,
            "tick": tick,
            "leaves": metas,
            "aux": {},
        }
        path, size, _ = save_snapshot(run_dir, manifest, leaves_in)
        m2, leaves_out = load_snapshot(path)
        assert m2["tick"] == tick and m2["leaves"] == metas
        assert len(leaves_out) == len(leaves_in)
        for a, b in zip(leaves_in, leaves_out):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b)

    @settings(max_examples=25, deadline=None)
    @given(
        tree=trees(),
        frac=st.floats(min_value=0.01, max_value=0.99),
    )
    def test_truncation_anywhere_refuses_typed(
        self, tmp_path_factory, tree, frac
    ):
        run_dir = str(tmp_path_factory.mktemp("ckpt"))
        leaves_in, metas = snapshot_carry(tree)
        path, size, _ = save_snapshot(
            run_dir,
            {
                "version": FORMAT_VERSION,
                "tick": 8,
                "leaves": metas,
                "aux": {},
            },
            leaves_in,
        )
        cut = max(1, int(size * frac))
        if cut >= size:
            cut = size - 1
        with open(path, "r+b") as f:
            f.truncate(cut)
        assert os.path.getsize(path) == cut
        with pytest.raises(CheckpointError):
            load_snapshot(path)
