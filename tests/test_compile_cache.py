"""Build = compile: the persistent XLA compilation cache (VERDICT r4 #1).

The framework's true build artifact is the compiled XLA program (~44 s at
100k instances — roughly the whole 10k-tick execution), so compilation is
cached like the reference caches image builds (``pkg/engine/supervisor.go:
359-364``; go-build cache ``pkg/build/docker_go.go:266-283``). Pinned here:

- ``utils/compile_cache`` resolves the cache under ``$TESTGROUND_HOME``
  with env override/disable;
- a FRESH PROCESS re-running the same composition skips XLA compile —
  zero new cache entries and a journal ``compile_secs`` that is a fraction
  of the cold run's (the cross-process persistent-cache claim);
- an explicit build task precompiles the composition's programs
  (``sim:plan`` × :class:`~testground_tpu.builders.base.Precompiler`),
  BuildKey-deduped via a marker, so the subsequent run is a pure cache
  read and a rebuild is a marker hit.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from testground_tpu.api import (
    Composition,
    Global,
    Group,
    Instances,
    TestPlanManifest,
    generate_default_run,
)
from testground_tpu.builders.sim_plan import SimPlanBuilder
from testground_tpu.config import EnvConfig
from testground_tpu.engine import Engine, EngineConfig, Outcome, State
from testground_tpu.sim.runner import SimJaxRunner
from testground_tpu.utils.compile_cache import compile_cache_dir

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def cache_entries(cache_dir: str) -> set:
    if not os.path.isdir(cache_dir):
        return set()
    return {f for f in os.listdir(cache_dir) if f != "precompiled"}


class TestCacheDirResolution:
    def test_default_under_testground_home(self, monkeypatch):
        monkeypatch.delenv("TESTGROUND_COMPILE_CACHE", raising=False)
        assert compile_cache_dir("/x/home") == "/x/home/data/compile-cache"

    def test_env_override_and_disable(self, monkeypatch):
        monkeypatch.setenv("TESTGROUND_COMPILE_CACHE", "/elsewhere")
        assert compile_cache_dir("/x/home") == "/elsewhere"
        monkeypatch.setenv("TESTGROUND_COMPILE_CACHE", "off")
        assert compile_cache_dir("/x/home") is None

    def test_dirs_layout(self):
        env = EnvConfig.load()
        assert env.dirs.compile_cache() == os.path.join(
            env.dirs.home, "data", "compile-cache"
        )


_RUN_SCRIPT = """
import json, os, sys, threading
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)
import jax
jax.config.update("jax_platforms", "cpu")

# count persistent-cache hits via jax's own monitoring events — a
# deterministic signal, unlike wall-clock compile_secs on a loaded CI
# container (where trace time and process noise can drown the
# sub-second XLA compile of this tiny program)
_cache_hits = [0]
def _on_event(event, **kw):
    if event == "/jax/compilation_cache/cache_hits":
        _cache_hits[0] += 1
jax.monitoring.register_event_listener(_on_event)

from testground_tpu.api import RunGroup, RunInput
from testground_tpu.config import EnvConfig
from testground_tpu.rpc import discard_writer
from testground_tpu.sim.executor import execute_sim_run

env = EnvConfig.load()
job = RunInput(
    run_id=sys.argv[1],
    test_plan="network",
    test_case="ping-pong",
    total_instances=4,
    groups=[
        RunGroup(
            id="all",
            instances=4,
            artifact_path=sys.argv[2],
            parameters={},
        )
    ],
    env=env,
)
out = execute_sim_run(job, discard_writer(), threading.Event())
print(
    "RESULT "
    + json.dumps(
        {
            "outcome": out.result.outcome.value,
            "compile_secs": out.result.journal["sim"]["compile_secs"],
            "cache_hits": _cache_hits[0],
        }
    )
)
"""


class TestPersistentCacheAcrossProcesses:
    def test_fresh_process_rerun_skips_xla_compile(self, tg_home):
        """Two FRESH processes run the identical composition; the second
        must add zero cache entries AND observe persistent-cache hits
        (jax's /jax/compilation_cache/cache_hits monitoring event) where
        the cold run observed at most its own AOT-pass self-hit — the
        cross-process claim pinned by the cache's own accounting rather
        than wall-clock ratios, which are noise-dominated for this
        sub-second program on a loaded CI container."""
        cache = os.path.join(str(tg_home), "data", "compile-cache")
        artifact = os.path.join(PLANS, "network")

        def run(run_id):
            proc = subprocess.run(
                [sys.executable, "-c", _RUN_SCRIPT, run_id, artifact],
                capture_output=True,
                text=True,
                timeout=600,
                env={**os.environ, "TESTGROUND_HOME": str(tg_home)},
                cwd=REPO_ROOT,
            )
            assert proc.returncode == 0, proc.stderr[-4000:]
            line = [
                ln for ln in proc.stdout.splitlines() if ln.startswith("RESULT ")
            ][-1]
            return json.loads(line[len("RESULT ") :])

        r1 = run("cold")
        assert r1["outcome"] == "success"
        entries_after_cold = cache_entries(cache)
        assert entries_after_cold, "cold run wrote no cache entries"
        # the perf ledger's AOT accounting pass compiles the chunk
        # program out-of-line BEFORE the first dispatch, by design
        # landing it in the persistent cache so the dispatch reads the
        # entry this same process just wrote (sim/perf.py). Whether
        # that read surfaces as a cache_hits event depends on jax's
        # in-memory executable dedup — so a cold run observes 0 or 1
        # self-hits, never a hit it didn't itself write.
        assert r1["cache_hits"] <= 1, (
            f"cold run against an empty cache reported "
            f"{r1['cache_hits']} cache hit(s) — more than the AOT "
            "accounting pass's single self-written entry can explain"
        )

        r2 = run("warm")
        assert r2["outcome"] == "success"
        entries_after_warm = cache_entries(cache)
        assert entries_after_warm == entries_after_cold, (
            "warm process compiled new programs: "
            f"{sorted(entries_after_warm - entries_after_cold)}"
        )
        # the warm process's compiles were DISK READS: jax's own cache
        # accounting must report at least one hit per cached program
        # family it executed (init + chunk variants)
        assert r2["cache_hits"] >= 2, (
            f"warm run reported only {r2['cache_hits']} persistent-cache "
            "hit(s) — the fresh process recompiled instead of reading "
            "the cache"
        )


_FLOOD_SCRIPT = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

_cache_hits = [0]
def _on_event(event, **kw):
    if event == "/jax/compilation_cache/cache_hits":
        _cache_hits[0] += 1
jax.monitoring.register_event_listener(_on_event)

from testground_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache()
import bench

if sys.argv[1] == "build":
    bench.build_bench_programs(4, 8, only={"flood"})
else:
    bench.bench_flood(4, 8)
print("RESULT " + json.dumps({"cache_hits": _cache_hits[0]}))
"""


class TestBenchSurfaceWarm:
    def test_bench_build_warms_flood_for_a_fresh_process(self, tg_home):
        """VERDICT r5 weak #1: BENCH_r05's flood workload paid +54.6 s
        cold compile under a populated cache because NOTHING ever
        precompiled the bench-private flood program — `tg build` warms
        compositions, and the full path alone rode that. `bench.py
        --build` now compiles every bench workload's program (the
        identical shape, via the shared _bench_shape table); pinned
        cross-process: a fresh process timing flood after a build adds
        ZERO cache entries and reads the cache (jax's own cache-hit
        accounting)."""
        cache = os.path.join(str(tg_home), "data", "compile-cache")

        def run(mode):
            proc = subprocess.run(
                [sys.executable, "-c", _FLOOD_SCRIPT, mode],
                capture_output=True,
                text=True,
                timeout=600,
                env={**os.environ, "TESTGROUND_HOME": str(tg_home)},
                cwd=REPO_ROOT,
            )
            assert proc.returncode == 0, proc.stderr[-4000:]
            line = [
                ln
                for ln in proc.stdout.splitlines()
                if ln.startswith("RESULT ")
            ][-1]
            return json.loads(line[len("RESULT ") :])

        run("build")
        after_build = cache_entries(cache)
        assert after_build, "bench --build wrote no cache entries"

        r = run("flood")
        assert cache_entries(cache) == after_build, (
            "a fresh flood bench compiled programs the bench build "
            "should have warmed: "
            f"{sorted(cache_entries(cache) - after_build)}"
        )
        assert r["cache_hits"] >= 1, (
            "the fresh flood bench reported no persistent-cache hits — "
            "it recompiled instead of reading the bench build's entries"
        )


@pytest.fixture()
def engine(tg_home):
    e = Engine(
        EngineConfig(
            env=EnvConfig.load(),
            builders=[SimPlanBuilder()],
            runners=[SimJaxRunner()],
        )
    )
    e.start_workers()
    yield e
    e.stop()


def _composition(instances=4):
    return generate_default_run(
        Composition(
            global_=Global(
                plan="network",
                case="ping-pong",
                builder="sim:plan",
                runner="sim:jax",
            ),
            groups=[Group(id="all", instances=Instances(count=instances))],
        )
    )


def _wait(engine, tid, timeout=300):
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (
            State.COMPLETE,
            State.CANCELED,
        ):
            return t
        time.sleep(0.05)
    raise TimeoutError(f"task {tid} did not finish")


class TestBuildPrecompiles:
    def test_build_compiles_run_reads_rebuild_dedups(self, engine, tg_home):
        """Explicit build → programs precompiled into the cache with a
        BuildKey marker; the run that follows adds zero cache entries;
        a second build is a marker hit (the BuildKey-dedup analog)."""
        manifest = TestPlanManifest.load_file(
            os.path.join(PLANS, "network", "manifest.toml")
        )
        # a program-shaping manifest default: prepare_for_run folds
        # manifest runner config into run_config, and the precompile must
        # coalesce in the same order do_run does — a precompile reading
        # the config before that fill-in would compile chunk-128 programs
        # while the run executes chunk-64 ones (new entries below)
        manifest.runners.setdefault("sim:jax", {})["chunk"] = 64
        sources = os.path.join(PLANS, "network")
        cache = os.path.join(str(tg_home), "data", "compile-cache")

        t1 = _wait(
            engine,
            engine.queue_build(_composition(), manifest, sources_dir=sources),
        )
        assert t1.outcome() == Outcome.SUCCESS, t1.error
        # warm the runner healthcheck's one-per-process mesh probe (a tiny
        # jit outside the plan's programs) so the zero-new-entries
        # assertion below isolates the run's OWN compiles
        from testground_tpu.rpc import discard_writer

        SimJaxRunner().healthcheck(
            fix=True, ow=discard_writer(), env=EnvConfig.load()
        )
        log1 = open(engine.task_log_path(t1.id)).read()
        assert "precompiled run" in log1, log1[-2000:]
        markers = os.listdir(os.path.join(cache, "precompiled"))
        assert len(markers) == 1
        marker = json.load(
            open(os.path.join(cache, "precompiled", markers[0]))
        )
        assert marker["plan"] == "network" and marker["compile_secs"] > 0
        after_build = cache_entries(cache)
        assert after_build, "precompile wrote no cache entries"

        # the run compiles nothing — every program is a cache read,
        # witnessed by jax's own cache-hit accounting (wall-clock ratios
        # are noise-dominated for this sub-second program on a loaded CI
        # container)
        import jax.monitoring

        hits = [0]

        def _on_event(event, **kw):
            if event == "/jax/compilation_cache/cache_hits":
                hits[0] += 1

        jax.monitoring.register_event_listener(_on_event)
        try:
            t2 = _wait(
                engine,
                engine.queue_run(
                    _composition(), manifest, sources_dir=sources
                ),
            )
        finally:
            # best-effort unregister (private — jax.monitoring exposes no
            # public remove); a leaked listener is harmless: it only
            # increments a dead local counter on later events
            try:
                from jax._src import monitoring as _mon

                _mon._unregister_event_listener_by_callback(_on_event)
            except (ImportError, AttributeError):
                pass
        assert t2.outcome() == Outcome.SUCCESS, t2.error
        after_run = cache_entries(cache)
        assert after_run == after_build, (
            "run compiled programs the build should have precompiled: "
            f"{sorted(after_run - after_build)}"
        )
        assert hits[0] >= 1, (
            "the run reported no persistent-cache hits — it recompiled "
            "instead of reading the build's precompiled programs"
        )

        # rebuild of the identical composition: BuildKey marker hit
        t3 = _wait(
            engine,
            engine.queue_build(_composition(), manifest, sources_dir=sources),
        )
        assert t3.outcome() == Outcome.SUCCESS, t3.error
        log3 = open(engine.task_log_path(t3.id)).read()
        assert "precompile: cache hit" in log3, log3[-2000:]

    def test_multi_runs_precompile_one_marker_per_shape(
        self, engine, tg_home
    ):
        """A [[runs]]-bearing composition precompiles each DISTINCT
        program shape once: two runs at different instance counts → two
        markers; a third run repeating the first count adds nothing."""
        from testground_tpu.api import Run

        comp = _composition(instances=4)
        base = comp.runs[0]

        def run_at(rid, count):
            r = Run.from_dict(base.to_dict())
            r.id = rid
            r.groups[0].instances.count = count
            return r

        comp.runs = [
            run_at("a", 4),
            run_at("b", 6),
            run_at("a2", 4),  # same shape as "a" — deduped in-build
        ]
        manifest = TestPlanManifest.load_file(
            os.path.join(PLANS, "network", "manifest.toml")
        )
        t = _wait(
            engine,
            engine.queue_build(
                comp, manifest, sources_dir=os.path.join(PLANS, "network")
            ),
        )
        assert t.outcome() == Outcome.SUCCESS, t.error
        cache = os.path.join(str(tg_home), "data", "compile-cache")
        assert len(os.listdir(os.path.join(cache, "precompiled"))) == 2

    def test_build_single_with_case_precompiles_via_cli(
        self, tg_home, capsys
    ):
        """`tg build single <plan>:<case>` resolves the case (instance
        count from the manifest default) so the sim:plan builder can
        precompile — the CLI face of build = compile."""
        from testground_tpu.cli.main import main

        assert (
            main(
                ["plan", "import", "--from", os.path.join(PLANS, "network")]
            )
            == 0
        )
        capsys.readouterr()
        rc = main(
            ["build", "single", "network:ping-pong", "--builder", "sim:plan"]
        )
        out = capsys.readouterr().out
        assert rc == 0, out
        cache = os.path.join(str(tg_home), "data", "compile-cache")
        markers = os.listdir(os.path.join(cache, "precompiled"))
        assert len(markers) == 1
        marker = json.load(
            open(os.path.join(cache, "precompiled", markers[0]))
        )
        assert marker["case"] == "ping-pong"
