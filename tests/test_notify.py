"""Task-status webhook tests against a mocked HTTP endpoint
(reference: ``pkg/engine/supervisor.go:192-296``)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from testground_tpu.config import EnvConfig
from testground_tpu.engine.notify import (
    notify_task_finished,
    post_status_to_github,
    post_status_to_slack,
)
from testground_tpu.engine.task import (
    CreatedBy,
    DatedState,
    Outcome,
    State,
    Task,
    TaskType,
)


@pytest.fixture()
def sink():
    """A local HTTP server recording every (path, headers, body) POST."""
    received = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            received.append(
                (
                    self.path,
                    dict(self.headers),
                    json.loads(self.rfile.read(n) or b"{}"),
                )
            )
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", received
    httpd.shutdown()


def make_task(outcome=Outcome.SUCCESS, ci=True, error=""):
    now = time.time()
    return Task(
        id="tsk123",
        type=TaskType.RUN,
        plan="network",
        case="ping-pong",
        states=[
            DatedState(state=State.SCHEDULED, created=now - 5),
            DatedState(state=State.PROCESSING, created=now - 4),
            DatedState(state=State.COMPLETE, created=now),
        ],
        result={"outcome": outcome.value},
        error=error,
        created_by=CreatedBy(
            user="ci",
            repo="example/proj" if ci else "",
            branch="main" if ci else "",
            commit="abc123" if ci else "",
        ),
    )


class TestSlack:
    def test_success_posts_text(self, tg_home, sink):
        url, received = sink
        env = EnvConfig.load()
        env.daemon.slack_webhook_url = url
        post_status_to_slack(env, make_task())
        assert len(received) == 1
        text = received[0][2]["text"]
        assert "succeeded" in text and "network:ping-pong" in text
        assert "tsk123" in text

    def test_failure_includes_error(self, tg_home, sink):
        url, received = sink
        env = EnvConfig.load()
        env.daemon.slack_webhook_url = url
        post_status_to_slack(
            env, make_task(outcome=Outcome.FAILURE, error="boom")
        )
        assert "failed" in received[0][2]["text"]
        assert "boom" in received[0][2]["text"]

    def test_unconfigured_is_noop(self, tg_home, sink):
        _, received = sink
        env = EnvConfig.load()
        post_status_to_slack(env, make_task())
        assert received == []


class TestGithub:
    def test_commit_status_posted(self, tg_home, sink):
        url, received = sink
        env = EnvConfig.load()
        env.daemon.github_repo_status_token = "tok"
        env.daemon.root_url = "https://tg.example"
        post_status_to_github(env, make_task(), api_base=url)
        path, headers, body = received[0]
        assert path == "/repos/example/proj/statuses/abc123"
        assert headers["Authorization"] == "Basic tok"
        assert body["state"] == "success"
        assert body["context"] == "testground/network/ping-pong"
        assert body["target_url"].startswith("https://tg.example/dashboard")

    def test_failure_state(self, tg_home, sink):
        url, received = sink
        env = EnvConfig.load()
        env.daemon.github_repo_status_token = "tok"
        post_status_to_github(
            env, make_task(outcome=Outcome.FAILURE), api_base=url
        )
        assert received[0][2]["state"] == "failure"

    def test_pending_status_while_processing(self, tg_home, sink):
        url, received = sink
        env = EnvConfig.load()
        env.daemon.github_repo_status_token = "tok"
        t = make_task()
        t.states = t.states[:2]  # last state: PROCESSING
        post_status_to_github(env, t, api_base=url)
        assert received[0][2]["state"] == "pending"

    def test_non_ci_task_is_skipped(self, tg_home, sink):
        url, received = sink
        env = EnvConfig.load()
        env.daemon.github_repo_status_token = "tok"
        post_status_to_github(env, make_task(ci=False), api_base=url)
        assert received == []


class TestNotifyNeverRaises:
    def test_unreachable_endpoint_is_swallowed(self, tg_home):
        env = EnvConfig.load()
        env.daemon.slack_webhook_url = "http://127.0.0.1:1/nope"
        notify_task_finished(env, make_task())  # must not raise
