"""Metrics time-series pipeline + viewer + daemon dashboard routes
(reference: ``pkg/metrics/viewer.go:35-80``, ``pkg/daemon/dashboard.go:44-75``,
GET routes ``daemon.go:83-91``)."""

import json
import os
import time
import urllib.request

import pytest

from testground_tpu.config import EnvConfig
from testground_tpu.metrics import Viewer, measurement_name

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def _write_ts(env, plan, run_id, rows):
    d = os.path.join(env.dirs.outputs(), plan, run_id)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "timeseries.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


class TestViewer:
    def test_measurements_and_data(self, tg_home):
        env = EnvConfig.load()
        rows = [
            {
                "run": "r1",
                "plan": "network",
                "case": "ping-pong",
                "tick": t,
                "group_id": "all",
                "name": "rtt_ticks",
                "count": 10,
                "mean": 5.0 + t,
                "min": 4.0,
                "max": 6.0,
            }
            for t in (128, 256)
        ]
        rows.append({**rows[0], "name": "other_metric", "tick": 128})
        _write_ts(env, "network", "r1", rows)

        v = Viewer(env)
        ms = v.get_measurements("network", "ping-pong")
        assert ms == [
            "results.network-ping-pong.other_metric",
            "results.network-ping-pong.rtt_ticks",
        ]
        data = v.get_data("network", "ping-pong", "rtt_ticks")
        assert [r.tick for r in data] == [128, 256]
        assert data[0].fields["mean"] == pytest.approx(133.0)
        assert v.get_tags(ms[0]) == []

    def test_case_and_run_filters(self, tg_home):
        env = EnvConfig.load()
        base = {
            "plan": "p",
            "group_id": "all",
            "name": "m",
            "count": 1,
            "mean": 1.0,
            "min": 1.0,
            "max": 1.0,
        }
        _write_ts(env, "p", "r1", [{**base, "run": "r1", "case": "a", "tick": 1}])
        _write_ts(env, "p", "r2", [{**base, "run": "r2", "case": "b", "tick": 2}])
        v = Viewer(env)
        assert len(v.get_data("p", "a", "m")) == 1
        assert len(v.get_data("p", "b", "m")) == 1
        assert v.get_data("p", "a", "m", run_id="r2") == []
        assert v.get_measurements("p", "nope") == []

    def test_missing_outputs_dir_is_empty(self, tg_home):
        v = Viewer(EnvConfig.load())
        assert v.get_measurements("ghost", "x") == []
        assert v.get_data("ghost", "x", "m") == []

    def test_task_scoped_query_matches_multi_run_ids(self, tg_home):
        """Multi-run [[runs]] compositions write run dirs named
        <task-id>-<run-id>; a task_id query must find them."""
        env = EnvConfig.load()
        base = {
            "plan": "p",
            "case": "c",
            "group_id": "all",
            "name": "m",
            "count": 1,
            "mean": 1.0,
            "min": 1.0,
            "max": 1.0,
        }
        _write_ts(env, "p", "t1-alpha", [{**base, "run": "t1-alpha", "tick": 1}])
        _write_ts(env, "p", "t1-beta", [{**base, "run": "t1-beta", "tick": 2}])
        _write_ts(env, "p", "t2", [{**base, "run": "t2", "tick": 3}])
        v = Viewer(env)
        assert len(v.get_data("p", "c", "m", run_id="t1")) == 2
        assert len(v.get_data("p", "c", "m", run_id="t2")) == 1

    def test_malformed_field_rows_are_skipped(self, tg_home):
        """The jsonl is an open format: rows whose fields aren't numeric
        must not reach consumers (e.g. raw HTML injection via count)."""
        env = EnvConfig.load()
        base = {"run": "r1", "plan": "p", "case": "c", "tick": 1,
                "group_id": "all", "name": "m", "mean": 1.0, "min": 1.0,
                "max": 1.0}
        _write_ts(
            env, "p", "r1",
            [
                {**base, "count": "<img src=x onerror=alert(1)>"},
                {**base, "count": 3},
            ],
        )
        rows = Viewer(env).get_data("p", "c", "m")
        assert len(rows) == 1 and rows[0].fields["count"] == 3

    def test_dotted_metric_names_survive(self, tg_home):
        env = EnvConfig.load()
        _write_ts(
            env,
            "p",
            "r1",
            [
                {
                    "run": "r1",
                    "plan": "p",
                    "case": "c",
                    "tick": 4,
                    "group_id": "all",
                    "name": "latency.p99",
                    "count": 2,
                    "mean": 9.0,
                    "min": 8.0,
                    "max": 10.0,
                }
            ],
        )
        v = Viewer(env)
        data = v.get_all_data("p", "c")
        assert list(data) == ["latency.p99"]
        assert v.get_data("p", "c", "latency.p99")[0].fields["mean"] == 9.0


class TestSimTelemetryFamily:
    """The viewer's second measurement family: per-tick engine counters
    from sim_timeseries.jsonl surface as ``sim.<counter>`` measurements
    (group_id ``_run``) and ``sim.live`` per group — rendered by the
    same dashboard tables and Influx mirror as plan metrics."""

    def _write_sim(self, env, plan, run_id, rows):
        d = os.path.join(env.dirs.outputs(), plan, run_id)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "sim_timeseries.jsonl"), "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    def _rows(self, run="r1", plan="p", case="c", ticks=3):
        return [
            {
                "run": run,
                "plan": plan,
                "case": case,
                "tick": t,
                "delivered": t,
                "dropped": 0,
                "rejected": 0,
                "cal_depth": 2 * t,
                "live": {"a": 4 - t, "b": 2},
            }
            for t in range(ticks)
        ]

    def test_measurements_and_data(self, tg_home):
        env = EnvConfig.load()
        self._write_sim(env, "p", "r1", self._rows())
        v = Viewer(env)
        ms = v.get_measurements("p", "c")
        assert measurement_name("p", "c", "sim.delivered") in ms
        assert measurement_name("p", "c", "sim.live") in ms
        data = v.get_data("p", "c", "sim.delivered")
        assert [r.tick for r in data] == [0, 1, 2]
        assert [r.fields["count"] for r in data] == [0, 1, 2]
        assert all(r.group_id == "_run" for r in data)
        live = v.get_data("p", "c", "sim.live")
        by_group = {}
        for r in live:
            by_group.setdefault(r.group_id, []).append(r.fields["count"])
        assert by_group == {"a": [4, 3, 2], "b": [2, 2, 2]}

    def test_families_coexist_in_one_run_dir(self, tg_home):
        env = EnvConfig.load()
        _write_ts(
            env,
            "p",
            "r1",
            [
                {
                    "run": "r1",
                    "plan": "p",
                    "case": "c",
                    "tick": 1,
                    "group_id": "all",
                    "name": "m",
                    "count": 1,
                    "mean": 1.0,
                    "min": 1.0,
                    "max": 1.0,
                }
            ],
        )
        self._write_sim(env, "p", "r1", self._rows())
        v = Viewer(env)
        ms = v.get_measurements("p", "c")
        assert measurement_name("p", "c", "m") in ms
        assert measurement_name("p", "c", "sim.delivered") in ms

    def test_case_and_run_filters_apply(self, tg_home):
        env = EnvConfig.load()
        self._write_sim(env, "p", "r1", self._rows(run="r1", case="a"))
        self._write_sim(env, "p", "r2", self._rows(run="r2", case="b"))
        v = Viewer(env)
        assert v.get_data("p", "a", "sim.delivered")
        assert v.get_data("p", "a", "sim.delivered", run_id="r2") == []
        assert v.get_data("p", "nope", "sim.delivered") == []

    def test_non_numeric_values_skipped(self, tg_home):
        env = EnvConfig.load()
        self._write_sim(
            env,
            "p",
            "r1",
            [
                {
                    "run": "r1",
                    "plan": "p",
                    "case": "c",
                    "tick": 0,
                    "delivered": "<b>x</b>",
                    "cal_depth": 3,
                    "live": {"a": "nope"},
                }
            ],
        )
        v = Viewer(env)
        assert v.get_data("p", "c", "sim.delivered") == []
        assert v.get_data("p", "c", "sim.live") == []
        assert len(v.get_data("p", "c", "sim.cal_depth")) == 1

    def test_end_to_end_sim_run_round_trip(self, tg_home):
        """Viewer/CLI round-trip on a real telemetry-enabled run: rows
        written by the executor surface through get_data, and the influx
        serializer accepts them unchanged."""
        from tests.test_sim_runner import run_sim
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.engine import Engine, EngineConfig, Outcome
        from testground_tpu.metrics.influx import rows_to_lines
        from testground_tpu.sim.runner import SimJaxRunner

        env = EnvConfig.load()
        e = Engine(
            EngineConfig(
                env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
            )
        )
        e.start_workers()
        try:
            t = run_sim(
                e,
                "network",
                "ping-pong",
                instances=2,
                run_params={"telemetry": True, "chunk": 16},
            )
        finally:
            e.stop()
        assert t.outcome() == Outcome.SUCCESS
        v = Viewer(env)
        data = v.get_data(
            "network", "ping-pong", "sim.delivered", run_id=t.id
        )
        assert data
        assert (
            sum(r.fields["count"] for r in data)
            == t.result["journal"]["sim"]["msgs_delivered"]
        )
        # the expanded rows serialize to line protocol (Influx mirror)
        lines = rows_to_lines([r.to_dict() | {"name": "sim.delivered",
                                             "plan": "network",
                                             "case": "ping-pong"}
                               for r in data])
        assert len(lines) == len(data)


class TestTimeSeriesRecorder:
    def test_final_sample_not_duplicated_on_cadence_boundary(self):
        from testground_tpu.rpc import discard_writer
        from testground_tpu.sim.executor import _TimeSeriesRecorder

        import numpy as np

        class TC:
            def collect_metrics(self, group, state, status):
                return {"m": state["x"]}

        class G:
            id = "all"
            offset = 0
            count = 2

        rec = _TimeSeriesRecorder(TC(), [G()], 128, discard_writer())
        states = [{"x": np.asarray([1.0, 2.0])}]
        status = np.asarray([1, 1])
        rec.sample(128, states, status)
        rec.sample(128, states, status)  # the run-end resample at same tick
        assert len(rec.rows) == 1
        rec.sample(256, states, status)
        assert len(rec.rows) == 2


def test_page_escapes_title():
    from testground_tpu.daemon.server import _page

    out = _page("<script>alert(1)</script>", "<p>ok</p>")
    assert "<script>alert(1)" not in out
    assert "&lt;script&gt;" in out


class TestSimTimeSeries:
    def test_sim_run_writes_timeseries(self, tg_home):
        """A sim:jax run of a metrics-bearing testcase persists sampled
        rows (at minimum the final sample) to timeseries.jsonl."""
        from tests.test_sim_runner import run_sim
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.engine import Engine, EngineConfig, Outcome
        from testground_tpu.sim.runner import SimJaxRunner

        env = EnvConfig.load()
        e = Engine(
            EngineConfig(
                env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
            )
        )
        e.start_workers()
        try:
            t = run_sim(e, "benchmarks", "netinit", instances=8)
        finally:
            e.stop()
        assert t.outcome() == Outcome.SUCCESS
        assert t.result["journal"]["timeseries"]["samples"] > 0
        v = Viewer(env)
        ms = v.get_measurements("benchmarks", "netinit")
        assert (
            measurement_name("benchmarks", "netinit", "time_to_network_init_ticks")
            in ms
        )
        rows = v.get_data(
            "benchmarks", "netinit", "time_to_network_init_ticks", run_id=t.id
        )
        assert rows and rows[-1].fields["count"] == 8


def _get(daemon, path):
    req = urllib.request.Request(daemon.address + path)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read()


class TestDaemonDashboardRoutes:
    @pytest.fixture()
    def daemon(self, tg_home):
        from testground_tpu.daemon import Daemon

        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        yield d
        d.stop()

    @pytest.fixture()
    def finished_sim_task(self, daemon):
        from testground_tpu.client import Client

        client = Client(daemon.address)
        client.import_plan(os.path.join(PLANS, "benchmarks"))
        task_id = client.run(
            {
                "global": {
                    "plan": "benchmarks",
                    "case": "netinit",
                    "builder": "sim:plan",
                    "runner": "sim:jax",
                    "total_instances": 4,
                },
                "groups": [{"id": "all", "instances": {"count": 4}}],
            }
        )
        deadline = time.time() + 180
        while time.time() < deadline:
            t = client.status(task_id)
            if t["states"][-1]["state"] in ("complete", "canceled"):
                assert t["outcome"] == "success"
                return task_id
            time.sleep(0.2)
        raise TimeoutError(task_id)

    def test_dashboard_list_and_task_pages(self, daemon, finished_sim_task):
        code, ctype, body = _get(daemon, "/dashboard")
        assert code == 200 and "text/html" in ctype
        assert finished_sim_task in body.decode()

        code, ctype, body = _get(
            daemon, f"/dashboard?task_id={finished_sim_task}"
        )
        page = body.decode()
        assert code == 200 and "text/html" in ctype
        assert "results.benchmarks-netinit.time_to_network_init_ticks" in page
        assert "<table>" in page

    def test_journal_route(self, daemon, finished_sim_task):
        code, _, body = _get(daemon, f"/journal?task_id={finished_sim_task}")
        assert code == 200
        j = json.loads(body)
        assert j["journal"]["sim"]["ticks"] > 0
        assert "timeseries" in j["journal"]

    def test_data_route(self, daemon, finished_sim_task):
        code, _, body = _get(
            daemon,
            f"/data?task_id={finished_sim_task}"
            "&metric=time_to_network_init_ticks",
        )
        assert code == 200
        d = json.loads(body)
        assert d["measurement"].endswith(".time_to_network_init_ticks")
        assert d["rows"] and d["rows"][-1]["count"] == 4

    def test_unknown_task_404s(self, daemon):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(daemon, "/journal?task_id=ghost")
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(daemon, "/data?task_id=ghost&metric=m")
        assert ei.value.code == 404
