"""Shape bucketing (PERF.md "Serving: buckets + packing"): the padded
instance axis with runtime-exact counts.

Contracts pinned here:

1. **Padded-run equivalence**: every workload of the dryrun feature
   matrix (the `test_transport_pallas.WORKLOADS` set — sorted transport,
   filters+regions, direct slots, control lanes, far pairs, duplicate
   shaping, bandwidth queue, filter rules, storm) runs BIT-IDENTICALLY
   at a padded bucket size and at its exact size: status, finished_at,
   every state leaf, every flow total, sync counters — on the xla AND
   the pallas (interpret) transport.
2. **Program canonicalism**: two different live sizes in the same
   bucket lower to the IDENTICAL init and chunk HLO — the property that
   makes the persistent compile cache "warm-for-anyone".
3. **PRNG reconstruction**: the bucketed per-lane key derivation
   bit-matches ``jax.random.split(root, live_n)`` for the live lanes.
4. **Chaos equivalence**: a remapped fault schedule (crash + restart +
   partition + loss burst) over a padded run reproduces the exact run's
   results, telemetry counter stream, and latency histograms bit for
   bit — plus a hypothesis fuzz arm mixing padding with random chaos
   schedules.
5. **Gating**: the resolve_buckets mesh-divisibility/cohort/coverage
   bounds, ladder/mode parsing, and the engine-level refusals.
6. **Exact-N normalization**: the perf ledger divides by live
   instances, never the bucket size (the `tg perf --compare` /
   runners/pretty fix), shape-tolerantly.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import __graft_entry__ as ge
from testground_tpu.api import RunGroup
from testground_tpu.sim.buckets import (
    DEFAULT_LADDER,
    bucketed_counts,
    parse_bucket_mode,
    parse_ladder,
    plan_buckets,
    remap_lane_masks,
    resolve_rung,
)
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import (
    instantiate_testcase,
    load_sim_testcases,
    resolve_buckets,
)
from testground_tpu.sim.faults import build_fault_schedule, remap_schedule

from tests.test_transport_pallas import (
    RESULT_KEYS,
    WORKLOADS,
    assert_runs_equal,
)

# tiny test ladder: every gate workload (≤ 16 instances) pads into the
# first rung with real dead lanes
LADDER = (32, 64)


def _bucketize(prog_factory):
    """Rebuild a WORKLOADS program factory so the single group pads to
    the test ladder and the exact count rides as live_counts."""

    def make(transport, n):
        # the gate factories bake their own group layouts; rebuild via
        # the same SimProgram ctor with a padded layout
        base = prog_factory(transport)
        bp = plan_buckets([g.count for g in base.groups], "auto", LADDER)
        assert bp is not None
        padded = build_groups(
            [
                RunGroup(id=g.id, instances=p, parameters=dict(g.params))
                for g, p in zip(base.groups, bp.padded_counts)
            ]
        )
        tc = instantiate_testcase(
            type(base.tc), padded, tick_ms=base.tick_ms
        )
        return SimProgram(
            tc,
            padded,
            test_plan=base.meta["test_plan"],
            test_case=base.meta["test_case"],
            tick_ms=base.tick_ms,
            chunk=base.chunk,
            hosts=base.hosts,
            transport=transport,
            live_counts=bp.live_counts,
        )

    return make


class TestPaddedEquivalence:
    @pytest.mark.parametrize(
        "label,make_prog,n,max_ticks",
        WORKLOADS,
        ids=[w[0] for w in WORKLOADS],
    )
    @pytest.mark.parametrize("transport", ["xla", "pallas"])
    def test_workload_bit_equal_padded(
        self, label, make_prog, n, max_ticks, transport
    ):
        exact = make_prog(transport).run(max_ticks=max_ticks)
        padded = _bucketize(make_prog)(transport, n).run(
            max_ticks=max_ticks
        )
        ok = int((np.asarray(exact["status"]) == 1).sum())
        assert ok == n, f"[{label}] exact arm not all-SUCCESS: {ok}/{n}"
        assert exact["msgs_delivered"] > 0, f"[{label}] no traffic"
        # exact-N demux: the padded run reports arrays of the EXACT size
        assert np.asarray(padded["status"]).shape == (n,)
        assert_runs_equal(f"{label}/padded/{transport}", exact, padded)
        # the returned groups carry exact counts (virtual layout)
        assert [g.count for g in padded["groups"]] == [
            g.count for g in exact["groups"]
        ]


class TestProgramCanonicalism:
    def _prog(self, n):
        factory = load_sim_testcases("plans/network")["ping-pong"]
        bp = plan_buckets([n], "auto", LADDER)
        groups = build_groups(
            [
                RunGroup(
                    id="all",
                    instances=bp.padded_counts[0],
                    parameters={
                        "latency_ms": "4",
                        "latency2_ms": "2",
                        "tolerance_ms": "15",
                    },
                )
            ]
        )
        tc = instantiate_testcase(factory, groups, tick_ms=1.0)
        return (
            SimProgram(
                tc,
                groups,
                test_plan="network",
                test_case="ping-pong",
                tick_ms=1.0,
                chunk=8,
                live_counts=bp.live_counts,
            ),
            bp,
        )

    def test_same_bucket_identical_hlo(self):
        """Different live sizes (and seeds) in one bucket lower to the
        IDENTICAL init and chunk HLO — the compile-cache reuse claim."""

        def hlos(n):
            prog, bp = self._prog(n)
            lc = np.asarray(bp.live_counts, np.int32)
            init = jax.jit(lambda s, l: prog.init_carry(s, l))
            init_txt = init.lower(np.int32(0), lc).as_text()
            carry = init(np.int32(3), lc)
            chunk_txt = (
                jax.jit(prog._chunk_step, donate_argnums=0)
                .lower(carry)
                .as_text()
            )
            return init_txt, chunk_txt

        ia, ca = hlos(8)
        ib, cb = hlos(14)
        assert ia == ib, "init HLO differs across live sizes in a bucket"
        assert ca == cb, "chunk HLO differs across live sizes in a bucket"

    def test_default_program_has_no_bucket_leaf(self):
        """Zero-overhead off-path: an unbucketed program's carry keeps
        live_counts=None (no new leaves, no new ops — the pre-bucket
        program unchanged; jaxpr identity is pinned by the transport
        suite's zero-overhead test on the same construction)."""
        prog = ge._pingpong_program(8)
        carry = jax.jit(lambda: prog.init_carry(0))()
        assert carry.live_counts is None
        assert "live_counts" not in str(
            jax.make_jaxpr(prog._chunk_step)(carry)
        )


class TestKeyDerivation:
    @pytest.mark.parametrize("n", [1, 2, 5, 8, 14, 31])
    def test_matches_jax_random_split(self, n):
        """The split-reconstruction: live lanes of a padded program get
        EXACTLY the keys ``jax.random.split(inst_root, n)`` hands an
        unpadded run (the bit-equality bedrock)."""
        bp = plan_buckets([n], "auto", LADDER)
        groups = build_groups(
            [RunGroup(id="all", instances=bp.padded_counts[0], parameters={})]
        )
        from tests.test_transport_pallas import _ChaosBarrierTraffic

        prog = SimProgram(
            _ChaosBarrierTraffic(),
            groups,
            test_plan="t",
            test_case="c",
            tick_ms=1.0,
            chunk=8,
            live_counts=(n,),
        )
        root = jax.random.key(42)
        _, inst_root = jax.random.split(root)
        virt = prog._virt(jnp.asarray([n], jnp.int32))
        derived = prog._derive_keys(inst_root, virt)
        want = jax.random.key_data(jax.random.split(inst_root, n))
        got = jax.random.key_data(derived)[:n]
        assert np.array_equal(np.asarray(want), np.asarray(got))


CHAOS_EVENTS = [
    {"kind": "crash", "instances": "2:4", "start_ms": 4.0},
    {"kind": "restart", "instances": "2:3", "start_ms": 9.0},
    {
        "kind": "partition",
        "instances": "0:2",
        "to_instances": "4:6",
        "start_ms": 3.0,
        "duration_ms": 6.0,
        "bidirectional": True,
    },
    {
        "kind": "loss_burst",
        "instances": "0:6",
        "start_ms": 6.0,
        "duration_ms": 8.0,
        "loss": 50.0,
    },
]


def _chaos_run(n, bucket, events, seed=7, max_ticks=2048):
    from tests.test_transport_pallas import _ChaosBarrierTraffic

    vgroups = build_groups(
        [RunGroup(id="all", instances=n, parameters={})]
    )
    faults = build_fault_schedule(vgroups, {"all": events}, 1.0)
    if bucket:
        bp = plan_buckets([n], "auto", LADDER)
        groups = build_groups(
            [
                RunGroup(
                    id="all", instances=bp.padded_counts[0], parameters={}
                )
            ]
        )
        if faults is not None:
            faults = remap_schedule(
                faults, bp.index_map(), bp.padded_n
            )
        live = bp.live_counts
    else:
        groups, live = vgroups, None
    prog = SimProgram(
        _ChaosBarrierTraffic(),
        groups,
        test_plan="t",
        test_case="c",
        tick_ms=1.0,
        chunk=16,
        telemetry=True,
        faults=faults,
        live_counts=live,
    )
    blocks = []
    res = prog.run(
        seed=seed,
        max_ticks=max_ticks,
        telemetry_cb=lambda b: blocks.append(np.asarray(b).copy()),
    )
    return res, np.concatenate(blocks) if blocks else np.zeros((0,))


class TestChaosEquivalence:
    def test_remapped_schedule_bit_equal_incl_loss(self):
        """Crash + restart + partition + 50% loss burst: the padded run
        reproduces the exact run bit for bit — results, the per-tick
        telemetry counter stream, and the latency histograms. The loss
        dice only survive padding because the transport hashes VIRTUAL
        message indices (net.enqueue dice_idx)."""
        exact, stream_x = _chaos_run(6, False, CHAOS_EVENTS)
        padded, stream_p = _chaos_run(6, True, CHAOS_EVENTS)
        assert exact["faults_crashed"] > 0
        assert exact["msgs_delivered"] > 0
        assert_runs_equal("chaos/padded", exact, padded)
        assert np.array_equal(stream_x, stream_p), (
            "telemetry counter streams diverge under padding"
        )
        assert np.array_equal(
            np.asarray(exact["lat_hist"]), np.asarray(padded["lat_hist"])
        )

    def test_remap_schedule_masks(self):
        vg = build_groups(
            [
                RunGroup(id="a", instances=3, parameters={}),
                RunGroup(id="b", instances=2, parameters={}),
            ]
        )
        sched = build_fault_schedule(
            vg, {"a": [{"kind": "crash", "start_ms": 1.0}]}, 1.0
        )
        bp = plan_buckets([3, 2], "auto", (4, 8))
        re = remap_schedule(sched, bp.index_map(), bp.padded_n)
        assert re.n == 8  # 4 + 4
        # group a's 3 live lanes sit at physical 0..3; group b's at 4..6
        assert re.crash_masks[0].tolist() == [
            True,
            True,
            True,
            False,
            False,
            False,
            False,
            False,
        ]

    def test_remap_refuses_wrong_layout(self):
        vg = build_groups([RunGroup(id="a", instances=3, parameters={})])
        sched = build_fault_schedule(
            vg, {"a": [{"kind": "crash", "start_ms": 1.0}]}, 1.0
        )
        with pytest.raises(ValueError, match="virtual-layout"):
            remap_schedule(sched, np.arange(5, dtype=np.int32), 8)


try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - optional dep
    HAVE_HYPOTHESIS = False


if HAVE_HYPOTHESIS:

    _kinds = st.sampled_from(
        ["crash", "restart", "partition", "link_flap", "loss_burst"]
    )

    @st.composite
    def _schedules(draw):
        n = draw(st.integers(min_value=4, max_value=10))
        events = []
        for _ in range(draw(st.integers(min_value=1, max_value=3))):
            kind = draw(_kinds)
            lo = draw(st.integers(min_value=0, max_value=n - 2))
            hi = draw(st.integers(min_value=lo + 1, max_value=n - 1))
            ev = {
                "kind": kind,
                "instances": f"{lo}:{hi}",
                "start_ms": float(
                    draw(st.integers(min_value=1, max_value=24))
                ),
            }
            if kind == "partition":
                # the other side: everything past hi (must be non-empty
                # and disjoint)
                if hi >= n:
                    continue
                ev["to_instances"] = f"{hi}:{n}"
                ev["duration_ms"] = float(
                    draw(st.integers(min_value=1, max_value=16))
                )
            elif kind in ("link_flap", "loss_burst"):
                ev["duration_ms"] = float(
                    draw(st.integers(min_value=1, max_value=16))
                )
                if kind == "loss_burst":
                    ev["loss"] = float(
                        draw(st.integers(min_value=10, max_value=90))
                    )
            events.append(ev)
        return n, events

    class TestPaddedChaosFuzz:
        @settings(max_examples=8, deadline=None)
        @given(_schedules())
        def test_padding_mixed_with_chaos_stays_bit_equal(self, case):
            """Fuzz arm (the ISSUE's padded/chaos mix): any random
            schedule over any small n must produce a padded run
            bit-equal to the exact run — conservation and determinism
            follow from equality with the already-fuzzed exact path."""
            n, events = case
            try:
                exact, stream_x = _chaos_run(
                    n, False, events, max_ticks=1024
                )
            except ValueError:
                # schedule refused (overlapping partition, same-tick
                # crash+restart, empty selection) — refusal parity is
                # the exact path's contract, not this suite's
                return
            padded, stream_p = _chaos_run(n, True, events, max_ticks=1024)
            for key in RESULT_KEYS:
                assert np.array_equal(
                    np.asarray(exact[key]), np.asarray(padded[key])
                ), f"{key} diverged (n={n}, events={events})"
            assert np.array_equal(stream_x, stream_p)


class TestGatingAndUnits:
    def test_parse_ladder(self):
        assert parse_ladder(None) == DEFAULT_LADDER
        assert parse_ladder("") == DEFAULT_LADDER
        assert parse_ladder("64,32,64") == (32, 64)
        assert parse_ladder([128, 32]) == (32, 128)
        with pytest.raises(ValueError, match="bucket_ladder"):
            parse_ladder("a,b")
        with pytest.raises(ValueError, match="positive"):
            parse_ladder("0,32")

    def test_parse_bucket_mode(self):
        assert parse_bucket_mode(None) == "off"
        assert parse_bucket_mode("off") == "off"
        assert parse_bucket_mode("auto") == "auto"
        assert parse_bucket_mode(True) == "auto"
        assert parse_bucket_mode("4096") == 4096
        with pytest.raises(ValueError, match="unknown bucket mode"):
            parse_bucket_mode("huge")
        with pytest.raises(ValueError, match="positive"):
            parse_bucket_mode("-4")

    def test_resolve_rung_and_counts(self):
        assert resolve_rung(1, (32, 64)) == 32
        assert resolve_rung(33, (32, 64)) == 64
        assert resolve_rung(65, (32, 64)) is None
        assert bucketed_counts([5, 40], "auto", (32, 64)) == (32, 64)
        assert bucketed_counts([5], "off", (32,)) is None
        assert bucketed_counts([5, 100], "auto", (32, 64)) is None
        assert bucketed_counts([5, 7], 16, (32,)) == (16, 16)
        assert bucketed_counts([20], 16, (32,)) is None

    def test_bucket_plan_maps(self):
        bp = plan_buckets([3, 2], "auto", (4, 8))
        assert bp.live_n == 5 and bp.padded_n == 8
        assert bp.virt_offsets == (0, 3)
        assert bp.phys_offsets == (0, 4)
        assert bp.index_map().tolist() == [0, 1, 2, 4, 5]
        assert "5 live" in bp.summary()
        masks = remap_lane_masks(
            np.asarray([[True, False, True, False, True]]),
            bp.index_map(),
            8,
        )
        assert masks[0].tolist() == [
            True, False, True, False, False, True, False, False,
        ]

    def test_resolve_buckets_gates(self):
        cfg = dataclasses.make_dataclass(
            "Cfg",
            [
                ("bucket", str),
                ("bucket_ladder", str),
                ("coordinator_address", str),
            ],
        )
        assert resolve_buckets(cfg("off", "", ""), [5]) is None
        plan = resolve_buckets(cfg("auto", "32,64", ""), [5])
        assert plan is not None and plan.padded_counts == (32,)
        # cohort configs run bucket-free, loudly
        warned = []
        assert (
            resolve_buckets(
                cfg("auto", "32", "host:1234"),
                [5],
                warn=lambda fmt, *a: warned.append(fmt % a),
            )
            is None
        )
        assert warned and "cohort" in warned[0]
        # a divisible mesh buckets exactly like an unmeshed run
        devs = jax.devices()[:2]
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        warned.clear()
        plan = resolve_buckets(
            cfg("auto", "32", ""),
            [5],
            mesh=mesh,
            warn=lambda fmt, *a: warned.append(fmt % a),
        )
        assert plan is not None and plan.padded_counts == (32,)
        assert not warned
        # an indivisible rung runs exact shapes, loudly
        warned.clear()
        assert (
            resolve_buckets(
                cfg("auto", "33", ""),
                [5],
                mesh=mesh,
                warn=lambda fmt, *a: warned.append(fmt % a),
            )
            is None
        )
        assert warned and "divide" in warned[0]
        # over-coverage groups run exact shapes, loudly
        warned.clear()
        assert (
            resolve_buckets(
                cfg("auto", "32", ""),
                [100],
                warn=lambda fmt, *a: warned.append(fmt % a),
            )
            is None
        )
        assert warned and "coverage" in warned[0]

    def test_engine_refusals(self):
        groups = build_groups(
            [RunGroup(id="all", instances=32, parameters={})]
        )
        from tests.test_transport_pallas import _ChaosBarrierTraffic

        with pytest.raises(ValueError, match="live count"):
            SimProgram(
                _ChaosBarrierTraffic(),
                groups,
                test_plan="t",
                test_case="c",
                live_counts=(40,),
            )
        with pytest.raises(ValueError, match="same group layout"):
            SimProgram(
                _ChaosBarrierTraffic(),
                groups,
                test_plan="t",
                test_case="c",
                live_counts=(4, 4),
            )
        # flight recorder + bucketing is refused (exact-layout lanes)
        from testground_tpu.sim.trace import build_trace_plan

        tp = build_trace_plan(groups, {"all": {"instances": "0:2"}})
        with pytest.raises(ValueError, match="flight recorder"):
            SimProgram(
                _ChaosBarrierTraffic(),
                groups,
                test_plan="t",
                test_case="c",
                trace=tp,
                live_counts=(8,),
            )
        # filter_rules + multiple groups is refused
        rr = ge._ruled_ring_testcase()
        two = build_groups(
            [
                RunGroup(id="a", instances=4, parameters={}),
                RunGroup(id="b", instances=4, parameters={}),
            ]
        )
        with pytest.raises(ValueError, match="filter_rules"):
            SimProgram(
                rr(),
                two,
                test_plan="t",
                test_case="c",
                live_counts=(2, 2),
            )


class TestPerfNormalization:
    def test_ledger_normalizes_by_live_n(self):
        """The perf ledger divides by the EXACT live count — a padded
        (or packed) run can never report inflated peer·ticks/s. Shape
        tolerant: the bucket annotation rides beside, absent when
        unbucketed."""
        from testground_tpu.sim.perf import PerfLedger

        led = PerfLedger(7, 16, bucket=32)
        led.on_chunk(0, 16, 16, 0.5)
        led.on_chunk(1, 32, 16, 0.5)
        s = led.summary()
        assert s["instances"] == 7
        assert s["bucket"] == 32
        ex = s["execute"]
        assert ex["peer_ticks_per_sec"] == pytest.approx(7 * 32 / 1.0)
        # un-bucketed ledgers carry no bucket key at all
        plain = PerfLedger(7, 16)
        plain.on_chunk(0, 16, 16, 0.5)
        assert "bucket" not in plain.summary()

    def test_pretty_renders_bucket_line(self):
        from testground_tpu.runners.pretty import render_perf_summary

        out = render_perf_summary(
            {
                "plan": "p",
                "case": "c",
                "perf": {
                    "instances": 7,
                    "bucket": 32,
                    "execute": {
                        "ticks": 64,
                        "wall_secs": 1.0,
                        "ticks_per_sec": 64.0,
                        "peer_ticks_per_sec": 448.0,
                        "chunks": 4,
                    },
                },
                "sim": {"bucket": {"compile_cache": "hit"}},
            }
        )
        assert "7 live instance(s) padded to 32" in out
        assert "compile cache hit" in out
        # peer rate is the live-normalized number
        assert "448" in out

    def test_prometheus_bucket_counters(self):
        import time as _t

        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )
        from testground_tpu.metrics.prometheus import render_prometheus

        tsk = Task(
            id="t1",
            type=TaskType.RUN,
            plan="p",
            case="c",
            runner="sim:jax",
            states=[
                DatedState(state=State.COMPLETE, created=_t.time())
            ],
            result={
                "outcome": "success",
                "journal": {
                    "sim": {
                        "bucket": {
                            "padded_instances": 32,
                            "instances": 7,
                            "compile_cache": "hit",
                        },
                        "pack": {"width": 4, "members": 3, "index": 1},
                    }
                },
            },
        )
        text = render_prometheus([tsk])
        assert 'tg_compile_bucket_hit{task="t1"' in text
        assert "tg_compile_bucket_miss" in text
        assert "tg_bucket_padded_instances" in text
        assert "tg_pack_width" in text
        assert "tg_pack_members" in text
        # the hit counter reads 1, the miss 0 for a hit verdict
        hit_line = [
            l
            for l in text.splitlines()
            if l.startswith("tg_compile_bucket_hit{")
        ]
        miss_line = [
            l
            for l in text.splitlines()
            if l.startswith("tg_compile_bucket_miss{")
        ]
        assert hit_line and hit_line[0].rstrip().endswith(" 1")
        assert miss_line and miss_line[0].rstrip().endswith(" 0")
