"""additional_hosts plan + control-route lane tests (sim twin of
/root/reference/plans/additional_hosts — whitelisted control routes)."""

import os

import numpy as np
import pytest

from testground_tpu.sim.api import SUCCESS
from testground_tpu.sim.engine import SimProgram

from test_sim_engine import make_groups, mesh8, plan_case

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def run_case(case, n, hosts=("http-echo",), mesh=None, max_ticks=256):
    prog = SimProgram(
        plan_case("additional_hosts", case),
        make_groups(n),
        test_plan="additional_hosts",
        test_case=case,
        mesh=mesh,
        chunk=16,
        hosts=hosts,
    )
    return prog.run(max_ticks=max_ticks)


class TestAdditionalHosts:
    def test_echo_roundtrip(self):
        res = run_case("additional_hosts", 8)
        assert (res["status"] == SUCCESS).all()
        # staggered sends over ⌈n/2⌉ ticks + the 1-tick control floor
        # each way: the last request (t=5) echoes back by t=8
        assert int(np.asarray(res["finished_at"]).max()) <= 8

    def test_drop_all_still_reaches_host(self):
        """The control-route property: a BLACKHOLE over the whole data
        plane must not cut off whitelisted hosts."""
        res = run_case("additional_hosts_drop", 8)
        assert (res["status"] == SUCCESS).all()

    def test_missing_host_raises(self):
        with pytest.raises(KeyError, match="http-echo"):
            run_case("additional_hosts", 2, hosts=())

    def test_sharded_equals_single(self):
        res_s = run_case("additional_hosts", 16)
        res_m = run_case("additional_hosts", 16, mesh=mesh8())
        assert (res_s["status"] == res_m["status"]).all()
        np.testing.assert_array_equal(
            res_s["finished_at"], res_m["finished_at"]
        )

    def test_string_config_is_comma_split_not_char_split(self):
        """additional_hosts = \"http-echo\" in TOML run_config must become
        one host, not four phantom single-char lanes."""
        from testground_tpu.sim.executor import _parse_hosts

        assert _parse_hosts("http-echo, other") == ("http-echo", "other")
        assert _parse_hosts("http-echo") == ("http-echo",)
        assert _parse_hosts(["a", "b"]) == ("a", "b")
        assert _parse_hosts(None) == ()
        assert _parse_hosts("") == ()

    def test_plans_without_hosts_unchanged(self):
        """hosts=() leaves every shape exactly as before (zero-cost when
        unused)."""
        prog = SimProgram(
            plan_case("placebo", "ok"), make_groups(4), chunk=8
        )
        assert prog.n_lanes == prog.n == 4
        res = prog.run(max_ticks=32)
        assert (res["status"] == SUCCESS).all()


class TestEngineEndToEnd:
    def test_manifest_runner_config_flows_hosts(self, tg_home):
        """The manifest's [runners."sim:jax"] additional_hosts entry must
        reach the executor through run-config coalescing — the e2e path a
        user actually exercises."""
        from tests.test_sim_runner import run_sim
        from testground_tpu.builders.sim_plan import SimPlanBuilder
        from testground_tpu.config import EnvConfig
        from testground_tpu.engine import Engine, EngineConfig, Outcome
        from testground_tpu.sim.runner import SimJaxRunner

        e = Engine(
            EngineConfig(
                env=EnvConfig.load(),
                builders=[SimPlanBuilder()],
                runners=[SimJaxRunner()],
            )
        )
        e.start_workers()
        try:
            t = run_sim(e, "additional_hosts", "additional_hosts", instances=4)
            assert t.outcome() == Outcome.SUCCESS
            t2 = run_sim(
                e, "additional_hosts", "additional_hosts_drop", instances=4
            )
            assert t2.outcome() == Outcome.SUCCESS
        finally:
            e.stop()
