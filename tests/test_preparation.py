"""Preparation pipeline tests, mirroring the reference's
``pkg/api/composition_preparation_test.go`` scenarios."""

import pytest

from testground_tpu.api import (
    Build,
    Composition,
    CompositionRunGroup,
    Dependency,
    Global,
    Group,
    InstanceConstraints,
    Instances,
    Parameter,
    Run,
    RunParams,
    TestCase,
    TestPlanManifest,
    generate_default_run,
    prepare_for_build,
    prepare_for_run,
)


def manifest(**kwargs):
    defaults = dict(
        name="foo_plan",
        builders={"docker:go": {}},
        runners={"local:docker": {}},
        testcases=[
            TestCase(
                name="foo_case",
                instances=InstanceConstraints(minimum=1, maximum=100),
                parameters={
                    "param4": Parameter(
                        type="string", default="value4:default:manifest"
                    )
                },
            )
        ],
    )
    defaults.update(kwargs)
    return TestPlanManifest(**defaults)


class TestDefaultTestParams:
    """composition_preparation_test.go:11 TestDefaultTestParamsApplied."""

    def test_precedence(self):
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                total_instances=3,
                builder="docker:go",
                runner="local:docker",
                run=RunParams(
                    test_params={
                        "param1": "value1:default:composition",
                        "param2": "value2:default:composition",
                        "param3": "value3:default:composition",
                    }
                ),
            ),
            groups=[
                Group(
                    id="all_set",
                    instances=Instances(count=1),
                    run=RunParams(
                        test_params={
                            "param1": "value1:set",
                            "param2": "value2:set",
                            "param3": "value3:set",
                        }
                    ),
                ),
                Group(id="none_set", instances=Instances(count=1)),
                Group(
                    id="first_set",
                    instances=Instances(count=1),
                    run=RunParams(test_params={"param1": "value1:set"}),
                ),
            ],
        )

        ret = prepare_for_run(c, manifest())
        g = ret.runs[0].groups

        assert g[0].test_params["param1"] == "value1:set"
        assert g[0].test_params["param2"] == "value2:set"
        assert g[0].test_params["param3"] == "value3:set"
        assert g[0].test_params["param4"] == "value4:default:manifest"

        assert g[1].test_params["param1"] == "value1:default:composition"
        assert g[1].test_params["param2"] == "value2:default:composition"
        assert g[1].test_params["param3"] == "value3:default:composition"
        assert g[1].test_params["param4"] == "value4:default:manifest"

        assert g[2].test_params["param1"] == "value1:set"
        assert g[2].test_params["param2"] == "value2:default:composition"
        assert g[2].test_params["param4"] == "value4:default:manifest"


class TestDefaultBuildParams:
    """composition_preparation_test.go:101 TestDefaultBuildParamsApplied."""

    def _comp(self):
        return Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                total_instances=3,
                builder="docker:go",
                runner="local:docker",
                build=Build(
                    selectors=["default_selector_1", "default_selector_2"],
                    dependencies=[
                        Dependency(module="dependency:a", version="1.0.0.default"),
                        Dependency(module="dependency:b", version="2.0.0.default"),
                    ],
                ),
            ),
            groups=[
                Group(id="no_local_settings"),
                Group(
                    id="dep_override",
                    build=Build(
                        dependencies=[
                            Dependency(
                                module="dependency:a", version="1.0.0.overridden"
                            ),
                            Dependency(
                                module="dependency:c", version="1.0.0.locally_set"
                            ),
                        ]
                    ),
                ),
                Group(
                    id="selector_override",
                    build=Build(selectors=["overridden"]),
                ),
            ],
        )

    def test_build_defaults(self):
        ret = prepare_for_build(self._comp(), manifest())

        g0 = ret.groups[0]
        assert g0.build.selectors == ["default_selector_1", "default_selector_2"]
        assert {(d.module, d.version) for d in g0.build.dependencies} == {
            ("dependency:a", "1.0.0.default"),
            ("dependency:b", "2.0.0.default"),
        }

        g1 = ret.groups[1]
        assert {(d.module, d.version) for d in g1.build.dependencies} == {
            ("dependency:a", "1.0.0.overridden"),
            ("dependency:b", "2.0.0.default"),
            ("dependency:c", "1.0.0.locally_set"),
        }

        g2 = ret.groups[2]
        assert g2.build.selectors == ["overridden"]

    def test_unsupported_builder_rejected(self):
        c = self._comp()
        c.global_.builder = "docker:nope"
        with pytest.raises(ValueError, match="does not support builder"):
            prepare_for_build(c, manifest())


class TestBuildConfigTrickleDown:
    """composition_preparation_test.go:187 TestDefaultBuildConfigTrickleDown."""

    def test_precedence_group_global_manifest(self):
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                builder="docker:go",
                runner="local:docker",
                build_config={"build_base_image": "base_image_global"},
            ),
            groups=[
                Group(id="from_global"),
                Group(
                    id="from_group",
                    build_config={"build_base_image": "base_image_group"},
                ),
            ],
        )
        m = manifest(
            builders={"docker:go": {"build_base_image": "base_image_manifest",
                                    "enabled": True}}
        )
        ret = prepare_for_build(c, m)
        assert ret.groups[0].build_config["build_base_image"] == "base_image_global"
        assert ret.groups[0].build_config["enabled"] is True
        assert ret.groups[1].build_config["build_base_image"] == "base_image_group"


class TestPrepareForRun:
    def test_generates_default_run(self):
        """composition_preparation.go:93-110 GenerateDefaultRun."""
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                builder="docker:go",
                runner="local:docker",
            ),
            groups=[
                Group(id="a", instances=Instances(count=2)),
                Group(id="b", instances=Instances(count=3)),
            ],
        )
        ret = prepare_for_run(c, manifest())
        assert len(ret.runs) == 1
        assert ret.runs[0].id == "default"
        assert ret.runs[0].total_instances == 5
        assert [g.calculated_instance_count for g in ret.runs[0].groups] == [2, 3]

    def test_instance_bounds_enforced(self):
        """composition_preparation.go:223-227."""
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                builder="docker:go",
                runner="local:docker",
            ),
            groups=[Group(id="a", instances=Instances(count=500))],
        )
        with pytest.raises(ValueError, match="outside of allowable range"):
            prepare_for_run(c, manifest())

    def test_unknown_case_rejected(self):
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="nope",
                builder="docker:go",
                runner="local:docker",
            ),
            groups=[Group(id="a", instances=Instances(count=1))],
        )
        with pytest.raises(ValueError, match="not found"):
            prepare_for_run(c, manifest())

    def test_unsupported_runner_rejected(self):
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                builder="docker:go",
                runner="cluster:nope",
            ),
            groups=[Group(id="a", instances=Instances(count=1))],
        )
        with pytest.raises(ValueError, match="does not support runner"):
            prepare_for_run(c, manifest())

    def test_runner_config_trickle_down(self):
        """composition_preparation_test.go:412 TestRunConfigTrickleDown."""
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                builder="docker:go",
                runner="local:docker",
                run_config={"keep": "composition"},
            ),
            groups=[Group(id="a", instances=Instances(count=1))],
        )
        m = manifest(
            runners={"local:docker": {"keep": "manifest", "extra": "manifest"}}
        )
        ret = prepare_for_run(c, m)
        assert ret.global_.run_config["keep"] == "composition"
        assert ret.global_.run_config["extra"] == "manifest"

    def test_runs_preserved_when_present(self):
        """composition_test.go:290 issue-1493: explicit [[runs]] survive."""
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                builder="docker:go",
                runner="local:docker",
            ),
            groups=[Group(id="a", instances=Instances(count=1))],
            runs=[
                Run(
                    id="custom",
                    groups=[
                        CompositionRunGroup(id="a", instances=Instances(count=2))
                    ],
                )
            ],
        )
        ret = prepare_for_run(c, manifest())
        assert [r.id for r in ret.runs] == ["custom"]
        assert ret.runs[0].total_instances == 2

    def test_run_group_inherits_group_instances(self):
        """Run groups fall back to the backing group's instances
        (composition.go:472-489 merge)."""
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                builder="docker:go",
                runner="local:docker",
            ),
            groups=[Group(id="a", instances=Instances(count=4))],
            runs=[Run(id="r", groups=[CompositionRunGroup(id="a")])],
        )
        ret = prepare_for_run(c, manifest())
        assert ret.runs[0].groups[0].calculated_instance_count == 4

    def test_default_parameters_json_encoded(self):
        m = manifest(
            testcases=[
                TestCase(
                    name="foo_case",
                    instances=InstanceConstraints(minimum=1, maximum=10),
                    parameters={
                        "num": Parameter(type="int", default=5),
                        "s": Parameter(type="string", default="x"),
                    },
                )
            ]
        )
        assert m.default_parameters("foo_case") == {"num": "5", "s": "x"}

    def test_inputs_not_mutated(self):
        c = Composition(
            global_=Global(
                plan="foo_plan",
                case="foo_case",
                builder="docker:go",
                runner="local:docker",
            ),
            groups=[Group(id="a", instances=Instances(count=1))],
        )
        prepare_for_run(c, manifest())
        assert c.runs == []  # original untouched

    def test_generate_default_run_only_when_absent(self):
        c = Composition(
            groups=[Group(id="a", instances=Instances(count=1))],
            runs=[Run(id="keep")],
        )
        assert [r.id for r in generate_default_run(c).runs] == ["keep"]
