"""Sharded serving plane (PERF.md "Sharded serving plane"): the
partition-rule table (`sim/meshplan.py`) and its four consumers.

Contracts pinned here:

1. **The rule table**: `parse_mesh_shape`/`make_mesh` layouts, the
   regex rules resolving leaf paths to PartitionSpecs (with `lead` and
   `ndim` clamping), `layout_str`/`peer_shards` duck-typing, and the
   divisibility arithmetic (`indivisible_counts`,
   `cross_shard_bytes_est`).
2. **Bit-equality on a mesh**: an engine run on a 4-virtual-device
   mesh — xla AND pallas (interpret, shard_map'ed commit) — matches
   its unsharded twin leaf for leaf; same for a BUCKETED (padded +
   live_counts) program and a PACKED (vmapped) batch on 1-D and 2-D
   layouts. The full workload matrix rides the dryrun gate
   (`__graft_entry__.dryrun_multichip`, MULTICHIP_r06.json); this file
   keeps fast representatives in tier-1.
3. **Divisibility refusals**: indivisible lane counts refuse loudly at
   every gate (engine backstop, pack admission) instead of computing
   wrong shards.
4. **Mesh-keyed decisions**: the transport decision cache and its key
   include the mesh layout — a meshed and an unmeshed run never share
   a decision.
5. **Sharded checkpoint/resume**: a run on a mesh snapshotted mid-way
   and resumed on a mesh reproduces the uninterrupted meshed run leaf
   for leaf.
"""

import dataclasses

import jax
import numpy as np
import pytest

import __graft_entry__ as ge
from testground_tpu.api import RunGroup
from testground_tpu.sim.buckets import plan_buckets
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import instantiate_testcase
from testground_tpu.sim.meshplan import (
    MeshPlan,
    cross_shard_bytes_est,
    indivisible_counts,
    layout_str,
    make_mesh,
    mesh_axis_names,
    parse_mesh_shape,
    peer_shards,
    plan_for,
)
from testground_tpu.sim.pack import PackMember, PackRunner
from testground_tpu.sim.transport_model import (
    _cache_key,
    clear_decision_cache,
    decide_transport,
)

from tests.test_sim_checkpoint import assert_results_equal

P = jax.sharding.PartitionSpec


def _assert_runs_equal(label, res_a, res_b):
    for key in (
        "status",
        "finished_at",
        "ticks",
        "msgs_delivered",
        "msgs_sent",
        "msgs_enqueued",
        "msgs_dropped",
        "msgs_rejected",
        "cal_depth",
    ):
        a, b = np.asarray(res_a[key]), np.asarray(res_b[key])
        assert np.array_equal(a, b), f"[{label}] {key}: {a} vs {b}"
    la, ta = jax.tree.flatten(res_a["states"])
    lb, tb = jax.tree.flatten(res_b["states"])
    assert ta == tb, f"[{label}] state structure drifted"
    for i, (a, b) in enumerate(zip(la, lb)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"[{label}] state leaf {i} differs"
        )


# ------------------------------------------------------- the rule table


class TestMeshPlanUnits:
    def test_parse_mesh_shape(self):
        assert parse_mesh_shape("4") == (4,)
        assert parse_mesh_shape("2x4") == (2, 4)
        assert parse_mesh_shape("2×4") == (2, 4)  # unicode ×
        for bad in ("nope", "", "2x2x2", "0", "-1x2"):
            with pytest.raises(ValueError):
                parse_mesh_shape(bad)

    def test_axis_names_and_layouts(self):
        assert mesh_axis_names(1) == ("i",)
        assert mesh_axis_names(2) == ("runs", "i")
        assert layout_str(None) == "1"
        assert layout_str(make_mesh("4")) == "4"
        assert layout_str(make_mesh("2x4")) == "2x4"
        assert make_mesh("1") is None  # a 1-extent mesh IS single-device
        # `--run-cfg mesh=4` coalesces as a bare int, not a str
        assert layout_str(make_mesh(4)) == "4"
        assert make_mesh(1) is None
        with pytest.raises(ValueError, match="needs 16 devices"):
            make_mesh("4x4")

    def test_explicit_shape_may_use_fewer_devices(self):
        mesh = make_mesh("4")
        assert mesh.devices.size == 4 < len(jax.devices())
        assert peer_shards(mesh) == 4
        assert peer_shards(make_mesh("2x4")) == 4
        assert peer_shards(None) == 1

    def test_peer_shards_duck_types_device_count_standins(self):
        # `tg check` probes with an offline stand-in exposing only
        # devices.size (sim/check.py _FakeMesh)
        fake = dataclasses.make_dataclass("F", [("devices", object)])(
            np.zeros(4)
        )
        assert peer_shards(fake) == 4
        assert layout_str(fake) == "4"

    def test_rule_table_resolves_known_paths(self):
        plan = MeshPlan(make_mesh("4"))
        assert plan.spec_for("status") == P("i")
        assert plan.spec_for("finished_at") == P("i")
        assert plan.spec_for("cal.payload.0") == P(None, "i")
        assert plan.spec_for("cal.src") == P(None, "i")
        assert plan.spec_for("unmatched.anything") == P()

    def test_spec_lead_and_ndim_clamp(self):
        plan = MeshPlan(make_mesh("2x4"))
        assert plan.shards == 4 and plan.runs == 2
        # a stacked [R, ...] leaf maps the run axis to the mesh's runs
        assert plan.spec_for("status", lead="runs") == P("runs", "i")
        # 1-D mesh has no runs axis: the lead entry replicates
        plan1 = MeshPlan(make_mesh("4"))
        assert plan1.spec_for("status", lead="runs") == P(None, "i")
        # a FLAT plane keeps only the leading entries at its real rank
        assert plan.spec_for("cal.payload.0", lead="runs", ndim=2) == P(
            "runs", None
        )
        assert plan_for(None) is None

    def test_divisibility_arithmetic(self):
        assert indivisible_counts((32, 64), 4) == ()
        assert indivisible_counts((32, 33), 4) == (33,)
        assert indivisible_counts((5,), 1) == ()
        # each shard receives the (shards-1)/shards fraction it lacks
        assert cross_shard_bytes_est(stream_bytes=1024, shards=4) == 768
        assert cross_shard_bytes_est(stream_bytes=1024, shards=1) == 0


# ----------------------------------------------- engine mesh bit-equality


class TestShardedEngineEquality:
    @pytest.mark.parametrize("transport", ["xla", "pallas"])
    def test_pingpong_mesh_bit_equal(self, transport):
        mesh = make_mesh("4")
        res_m = ge._pingpong_program(
            32, mesh=mesh, transport=transport
        ).run(max_ticks=512)
        res_s = ge._pingpong_program(32, transport=transport).run(
            max_ticks=512
        )
        assert int((np.asarray(res_m["status"]) == 1).sum()) == 32
        _assert_runs_equal(f"pingpong/{transport}", res_m, res_s)

    def test_pallas_mesh_equals_xla_mesh(self):
        mesh = make_mesh("4")
        res_p = ge._pingpong_program(
            32, mesh=mesh, transport="pallas"
        ).run(max_ticks=512)
        res_x = ge._pingpong_program(32, mesh=mesh, transport="xla").run(
            max_ticks=512
        )
        _assert_runs_equal("pingpong/pallas-vs-xla-meshed", res_p, res_x)


# ------------------------------------------------- bucketed mesh equality


class TestBucketedMeshEquality:
    def _padded_prog(self, live_n, rung, mesh, transport="xla"):
        base = ge._pingpong_program(live_n, transport=transport)
        bp = plan_buckets([g.count for g in base.groups], "auto", (rung,))
        assert bp is not None and bp.padded_counts == (rung,)
        padded = build_groups(
            [
                RunGroup(id=g.id, instances=p, parameters=dict(g.params))
                for g, p in zip(base.groups, bp.padded_counts)
            ]
        )
        tc = instantiate_testcase(
            type(base.tc), padded, tick_ms=base.tick_ms
        )
        return SimProgram(
            tc,
            padded,
            test_plan=base.meta["test_plan"],
            test_case=base.meta["test_case"],
            tick_ms=base.tick_ms,
            chunk=base.chunk,
            hosts=base.hosts,
            transport=transport,
            live_counts=bp.live_counts,
            mesh=mesh,
        )

    def test_padded_mesh_bit_equal_to_padded_unmeshed(self):
        # 24 live lanes padded to a 32 rung: the PADDED axis (not the
        # live count) is what must divide across the 4 peer shards
        mesh = make_mesh("4")
        res_m = self._padded_prog(24, 32, mesh).run(max_ticks=512)
        res_s = self._padded_prog(24, 32, None).run(max_ticks=512)
        assert np.asarray(res_m["status"]).shape == (24,)  # exact-N demux
        _assert_runs_equal("pingpong/padded-meshed", res_m, res_s)


# --------------------------------------------------- packed mesh equality


class TestPackedMeshEquality:
    def _solo(self, seed):
        return ge._pingpong_program(32).run(max_ticks=512, seed=seed)

    def test_pack_1d_mesh_bit_equal_to_solo(self):
        runner = PackRunner(
            ge._pingpong_program(32), 4, mesh=make_mesh("4")
        )
        members = [PackMember(seed=s, max_ticks=512) for s in (1, 2)]
        for m, res in zip(members, runner.run(members)):
            _assert_runs_equal(f"pack-1d/seed{m.seed}", res, self._solo(m.seed))

    def test_pack_2d_mesh_bit_equal_to_solo(self):
        # the stacked [R, ...] carry maps its run axis to "runs"
        runner = PackRunner(
            ge._pingpong_program(32), 4, mesh=make_mesh("2x2")
        )
        members = [PackMember(seed=s, max_ticks=512) for s in (1, 2)]
        for m, res in zip(members, runner.run(members)):
            _assert_runs_equal(f"pack-2d/seed{m.seed}", res, self._solo(m.seed))

    def test_pack_refuses_pallas_inner_program_on_mesh(self):
        with pytest.raises(ValueError, match="pallas"):
            PackRunner(
                ge._pingpong_program(32, transport="pallas"),
                4,
                mesh=make_mesh("4"),
            )


# ------------------------------------------------- mesh-keyed decisions


class TestDecisionCacheMeshKeying:
    def test_cache_key_includes_layout(self):
        from tests.test_transport_model import _sorted_ctx

        ctx = _sorted_ctx()
        k1 = _cache_key(ctx, "cpu", None)
        k4 = _cache_key(ctx, "cpu", make_mesh("4"))
        k24 = _cache_key(ctx, "cpu", make_mesh("2x4"))
        assert len({k1, k4, k24}) == 3
        assert k1[:-1] == k4[:-1] == k24[:-1]  # ONLY the layout differs

    def test_meshed_and_unmeshed_decisions_never_shared(self):
        from tests.test_transport_model import Cfg, _sorted_ctx

        clear_decision_cache()
        try:
            d1 = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
            dm = decide_transport(
                Cfg("auto"), make_mesh("4"), context=_sorted_ctx()
            )
            assert dm is not d1
            # each layout then hits its OWN cached decision
            assert decide_transport(
                Cfg("auto"), None, context=_sorted_ctx()
            ) is d1
            assert decide_transport(
                Cfg("auto"), make_mesh("4"), context=_sorted_ctx()
            ) is dm
        finally:
            clear_decision_cache()


# -------------------------------------------- sharded checkpoint/resume


class TestShardedCheckpointResume:
    def test_meshed_resume_bit_equal(self, tmp_path):
        """A meshed run cut mid-way and resumed ON THE MESH through the
        real on-disk snapshot format reproduces the uninterrupted
        meshed run leaf for leaf (which TestShardedEngineEquality pins
        equal to the unsharded run)."""
        from testground_tpu.sim.checkpoint import (
            FORMAT_VERSION,
            load_snapshot,
            restore_carry,
            save_snapshot,
            snapshot_carry,
        )

        mesh = make_mesh("4")

        def prog():
            return ge._pingpong_program(32, mesh=mesh, chunk=4)

        res_full = prog().run(seed=3, max_ticks=64)
        cut = 8
        assert res_full["ticks"] > cut

        captured = {}

        def observer(ticks, carry):
            if ticks == cut:
                captured["leaves"], captured["metas"] = snapshot_carry(
                    carry
                )

        prog().run(seed=3, max_ticks=cut, observer=observer)
        path, _, _ = save_snapshot(
            str(tmp_path),
            {
                "version": FORMAT_VERSION,
                "tick": cut,
                "leaves": captured["metas"],
                "aux": {},
            },
            captured["leaves"],
        )
        manifest, leaves = load_snapshot(path)
        prog_res = prog()
        carry = restore_carry(prog_res, 3, manifest, leaves)
        res_res = prog_res.run(
            seed=3, max_ticks=64, resume_carry=carry, resume_ticks=cut
        )
        assert_results_equal(res_full, res_res, label="meshed-resume")
