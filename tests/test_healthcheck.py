"""Runner healthchecks: real per-check results that can actually fail
(reference: ``pkg/healthcheck`` + runner-side enlistment,
``local_exec.go:49-72``)."""

import os
import shutil

from testground_tpu.config import EnvConfig
from testground_tpu.rpc import discard_writer
from testground_tpu.runners.local_exec import LocalExecRunner
from testground_tpu.sim.runner import SimJaxRunner


class TestLocalExecHealthcheck:
    def test_healthy_env_all_ok(self, tg_home):
        EnvConfig.load()  # creates the directory layout
        report = LocalExecRunner().healthcheck(False, discard_writer())
        assert {c.name for c in report.checks} == {
            "outputs-dir-writable",
            "work-dir-writable",
            "sync-service-port-bindable",
            "python-interpreter-runs",
        }
        assert report.ok(), str(report)

    def test_missing_dir_fails_then_fixer_repairs(self, tg_home):
        env = EnvConfig.load()
        shutil.rmtree(env.dirs.outputs())
        runner = LocalExecRunner()

        report = runner.healthcheck(False, discard_writer())
        by_name = {c.name: c for c in report.checks}
        assert by_name["outputs-dir-writable"].status == "failed"
        assert not report.ok()

        # fix=True runs the mkdir fixer and re-checks
        report = runner.healthcheck(True, discard_writer())
        by_name = {c.name: c for c in report.checks}
        assert by_name["outputs-dir-writable"].status == "ok"
        assert os.path.isdir(env.dirs.outputs())

    def test_unfixable_check_reports_failure(self, tg_home):
        """A file squatting on the outputs path defeats the mkdir fixer —
        the report must surface the failure, not paper over it."""
        env = EnvConfig.load()
        shutil.rmtree(env.dirs.outputs())
        with open(env.dirs.outputs(), "w") as f:
            f.write("squatter")
        try:
            report = LocalExecRunner().healthcheck(True, discard_writer())
            by_name = {c.name: c for c in report.checks}
            assert by_name["outputs-dir-writable"].status == "failed"
            fixes = {f.name: f for f in report.fixes}
            assert fixes["outputs-dir-writable"].status == "failed"
            assert not report.ok()
        finally:
            os.unlink(env.dirs.outputs())


class TestSyncServiceChecks:
    """Cross-host sync-plane checks (docs/CROSSHOST.md): the bindability
    probe must target the CONFIGURED bind host, and a configured remote
    sync service must answer a real ping RPC."""

    def test_bindability_probes_configured_host(self, tg_home):
        env = EnvConfig.load()
        # an address this machine cannot bind (TEST-NET-1)
        env.runners["local:exec"] = {"sync_bind_host": "192.0.2.1"}
        report = LocalExecRunner().healthcheck(False, discard_writer(), env=env)
        by_name = {c.name: c for c in report.checks}
        assert by_name["sync-service-port-bindable"].status == "failed"
        assert "192.0.2.1" in by_name["sync-service-port-bindable"].message

    def test_remote_sync_service_checked_by_ping(self, tg_home):
        from testground_tpu.sync import SyncServiceServer

        env = EnvConfig.load()
        srv = SyncServiceServer().start()
        try:
            host, port = srv.address
            env.runners["local:exec"] = {
                "sync_service_address": f"{host}:{port}"
            }
            report = LocalExecRunner().healthcheck(
                False, discard_writer(), env=env
            )
            by_name = {c.name: c for c in report.checks}
            assert by_name["sync-service-reachable"].status == "ok"
            assert "answered ping" in by_name["sync-service-reachable"].message
        finally:
            srv.stop()
        # dead endpoint: the check fails with the address in the message
        env.runners["local:exec"] = {"sync_service_address": f"{host}:{port}"}
        report = LocalExecRunner().healthcheck(False, discard_writer(), env=env)
        by_name = {c.name: c for c in report.checks}
        assert by_name["sync-service-reachable"].status == "failed"
        assert f"{host}:{port}" in by_name["sync-service-reachable"].message

    def test_connect_level_liveness_is_not_enough(self, tg_home):
        """A plain TCP listener that never speaks the protocol must fail
        the ping check (the listen-backlog lie)."""
        import socket

        from testground_tpu.healthcheck.checkers import check_sync_service

        lis = socket.socket()
        lis.bind(("127.0.0.1", 0))
        lis.listen(1)
        try:
            host, port = lis.getsockname()
            ok, msg = check_sync_service(host, port, timeout=0.5)()
            assert not ok
        finally:
            lis.close()


class TestEnvThreading:
    def test_engine_env_wins_over_environ(self, tmp_path, monkeypatch):
        """An explicitly-constructed env must be what gets checked, not a
        re-resolve of $TESTGROUND_HOME (the engine passes its own env)."""
        monkeypatch.setenv("TESTGROUND_HOME", str(tmp_path / "env-home"))
        custom = tmp_path / "custom-home"
        env = EnvConfig.load(home=str(custom))
        report = LocalExecRunner().healthcheck(False, discard_writer(), env=env)
        msgs = " ".join(c.message for c in report.checks)
        assert str(custom) in msgs
        assert str(tmp_path / "env-home") not in msgs


class TestSimJaxHealthcheck:
    def test_device_checks_pass_on_cpu_mesh(self, tg_home):
        EnvConfig.load()
        report = SimJaxRunner().healthcheck(False, discard_writer())
        by_name = {c.name: c for c in report.checks}
        assert set(by_name) == {
            "jax-importable",
            "device-available",
            "mesh-buildable",
            "device-memory",
            "outputs-dir-writable",
        }
        assert report.ok(), str(report)
        # the mesh check really ran a program over every device
        assert "mesh compiled and executed" in by_name["mesh-buildable"].message
