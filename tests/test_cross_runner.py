"""Cross-runner equivalence (BASELINE config 2's spirit: the simulator
validated against real-process ground truth): the SAME plan, run through
``local:exec`` (real OS processes + TCP sync service) and ``sim:jax``
(vectorized simulation), must produce the same per-group outcomes for
every behavior class — success, app failure, crash, and stall."""

import pytest

from testground_tpu.builders.exec_py import ExecPyBuilder
from testground_tpu.builders.sim_plan import SimPlanBuilder
from testground_tpu.config import EnvConfig
from testground_tpu.engine import Engine, EngineConfig, Outcome
from testground_tpu.runners.local_exec import LocalExecRunner
from testground_tpu.sim.runner import SimJaxRunner

from tests.test_local_exec import run_plan


@pytest.fixture()
def engine(tg_home):
    e = Engine(
        EngineConfig(
            env=EnvConfig.load(),
            builders=[ExecPyBuilder(), SimPlanBuilder()],
            runners=[LocalExecRunner(), SimJaxRunner()],
        )
    )
    e.start_workers()
    yield e
    e.stop()


def _real(engine, case, **kw):
    return run_plan(
        engine, "placebo", case, instances=3, timeout=90,
        builder="exec:py", runner="local:exec", **kw,
    )


def _sim(engine, case, **kw):
    return run_plan(
        engine, "placebo", case, instances=3, timeout=90,
        builder="sim:plan", runner="sim:jax", **kw,
    )


# behavior class -> expected outcome on BOTH substrates
CASES = [
    ("ok", Outcome.SUCCESS),
    ("abort", Outcome.FAILURE),
    ("panic", Outcome.FAILURE),
]


class TestSimMatchesRealProcesses:
    @pytest.mark.parametrize("case,expected", CASES)
    def test_outcomes_agree(self, engine, case, expected):
        real = _real(engine, case)
        sim = _sim(engine, case)
        assert real.outcome() == expected, f"local:exec {case}"
        assert sim.outcome() == expected, f"sim:jax {case}"
        # per-group ok counts agree too (single-run results are flattened
        # to a top-level outcomes dict)
        assert real.result["outcomes"] == sim.result["outcomes"]

    def test_stall_bounded_on_both(self, engine):
        """`stall` is bounded by each runner's own budget (run_timeout for
        real processes, max_ticks for the sim) and must come back FAILURE,
        not hang."""
        real = _real(engine, "stall", run_config={"run_timeout_secs": 3})
        sim = _sim(
            engine, "stall", run_config={"max_ticks": 64, "chunk": 16}
        )
        assert real.outcome() == Outcome.FAILURE
        assert sim.outcome() == Outcome.FAILURE
