"""Cross-runner equivalence (BASELINE config 2's spirit: the simulator
validated against real-process ground truth): the SAME plan, run through
``local:exec`` (real OS processes + TCP sync service) and ``sim:jax``
(vectorized simulation), must produce the same per-group outcomes for
every behavior class — success, app failure, crash, and stall."""

import os
import time

import pytest

from testground_tpu.api import (
    Composition,
    Global,
    Group,
    Instances,
    TestPlanManifest,
    generate_default_run,
)
from testground_tpu.builders.exec_py import ExecPyBuilder
from testground_tpu.builders.sim_plan import SimPlanBuilder
from testground_tpu.config import EnvConfig
from testground_tpu.engine import Engine, EngineConfig, Outcome, State
from testground_tpu.runners.local_exec import LocalExecRunner
from testground_tpu.sim.runner import SimJaxRunner

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


@pytest.fixture()
def engine(tg_home):
    e = Engine(
        EngineConfig(
            env=EnvConfig.load(),
            builders=[ExecPyBuilder(), SimPlanBuilder()],
            runners=[LocalExecRunner(), SimJaxRunner()],
        )
    )
    e.start_workers()
    yield e
    e.stop()


def _run(engine, case, builder, runner, instances=3, run_config=None):
    comp = generate_default_run(
        Composition(
            global_=Global(
                plan="placebo",
                case=case,
                builder=builder,
                runner=runner,
                run_config=dict(run_config or {}),
            ),
            groups=[Group(id="all", instances=Instances(count=instances))],
        )
    )
    manifest = TestPlanManifest.load_file(
        os.path.join(PLANS, "placebo", "manifest.toml")
    )
    tid = engine.queue_run(
        comp, manifest, sources_dir=os.path.join(PLANS, "placebo")
    )
    deadline = time.time() + 90
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (
            State.COMPLETE,
            State.CANCELED,
        ):
            return t
        time.sleep(0.05)
    raise TimeoutError(tid)


# behavior class -> expected outcome on BOTH substrates. `stall` is
# bounded by the runner's own budget in each world (run_timeout for real
# processes, max_ticks for the sim) and must come back FAILURE, not hang.
CASES = [
    ("ok", Outcome.SUCCESS),
    ("abort", Outcome.FAILURE),
    ("panic", Outcome.FAILURE),
]


class TestSimMatchesRealProcesses:
    @pytest.mark.parametrize("case,expected", CASES)
    def test_outcomes_agree(self, engine, case, expected):
        real = _run(engine, case, "exec:py", "local:exec")
        sim = _run(engine, case, "sim:plan", "sim:jax")
        assert real.outcome() == expected, f"local:exec {case}"
        assert sim.outcome() == expected, f"sim:jax {case}"
        # per-group ok counts agree too (single-run results are flattened
        # to a top-level outcomes dict)
        assert real.result["outcomes"] == sim.result["outcomes"]

    def test_stall_bounded_on_both(self, engine):
        real = _run(
            engine,
            "stall",
            "exec:py",
            "local:exec",
            run_config={"run_timeout_secs": 3},
        )
        sim = _run(
            engine,
            "stall",
            "sim:plan",
            "sim:jax",
            run_config={"max_ticks": 64, "chunk": 16},
        )
        assert real.outcome() == Outcome.FAILURE
        assert sim.outcome() == Outcome.FAILURE
