"""Composition type tests, mirroring the reference's
``pkg/api/composition_test.go`` scenarios."""

import pytest

from testground_tpu.api import (
    Build,
    Composition,
    CompositionError,
    Dependency,
    Global,
    Group,
    Instances,
    Run,
    CompositionRunGroup,
    validate_for_build,
    validate_for_run,
)


def make_composition(**kwargs):
    defaults = dict(
        global_=Global(
            plan="foo_plan",
            case="foo_case",
            builder="docker:go",
            runner="local:docker",
            total_instances=0,
        ),
        groups=[Group(id="a", instances=Instances(count=1))],
    )
    defaults.update(kwargs)
    return Composition(**defaults)


class TestValidation:
    def test_groups_unique(self):
        c = make_composition(
            groups=[
                Group(id="dup", instances=Instances(count=1)),
                Group(id="dup", instances=Instances(count=1)),
            ]
        )
        with pytest.raises(CompositionError, match="not unique"):
            validate_for_build(c)

    def test_missing_builder(self):
        c = make_composition()
        c.global_.builder = ""
        with pytest.raises(CompositionError, match="missing a builder"):
            validate_for_build(c)

    def test_group_level_builder_is_enough(self):
        c = make_composition(
            groups=[Group(id="a", builder="exec:py", instances=Instances(count=1))]
        )
        c.global_.builder = ""
        validate_for_build(c)  # must not raise

    def test_count_xor_percentage(self):
        c = make_composition(
            groups=[Group(id="a", instances=Instances(count=2, percentage=0.5))]
        )
        with pytest.raises(CompositionError, match="count"):
            validate_for_build(c)

    def test_run_references_unknown_group(self):
        c = make_composition(
            runs=[
                Run(
                    id="r1",
                    groups=[
                        CompositionRunGroup(id="nope", instances=Instances(count=1))
                    ],
                )
            ]
        )
        with pytest.raises(CompositionError, match="non-existent group"):
            validate_for_run(c)

    def test_run_ids_unique(self):
        rg = lambda: CompositionRunGroup(id="a", instances=Instances(count=1))
        c = make_composition(
            runs=[Run(id="r", groups=[rg()]), Run(id="r", groups=[rg()])]
        )
        with pytest.raises(CompositionError, match="runs ids not unique"):
            validate_for_run(c)


class TestInstanceCounts:
    def test_total_computed_from_counts(self):
        """composition_test.go:93 TestTotalInstancesIsComputedWhenPossible."""
        r = Run(
            id="r",
            groups=[
                CompositionRunGroup(id="a", instances=Instances(count=2)),
                CompositionRunGroup(id="b", instances=Instances(count=3)),
            ],
        )
        r.recalculate_instance_counts()
        assert r.total_instances == 5
        assert [g.calculated_instance_count for g in r.groups] == [2, 3]

    def test_percentage_requires_total(self):
        r = Run(
            id="r",
            groups=[CompositionRunGroup(id="a", instances=Instances(percentage=0.5))],
        )
        with pytest.raises(ValueError, match="total_instance"):
            r.recalculate_instance_counts()

    def test_percentage_resolution(self):
        r = Run(
            id="r",
            total_instances=10,
            groups=[
                CompositionRunGroup(id="a", instances=Instances(percentage=0.3)),
                CompositionRunGroup(id="b", instances=Instances(percentage=0.7)),
            ],
        )
        r.recalculate_instance_counts()
        assert [g.calculated_instance_count for g in r.groups] == [3, 7]

    def test_total_mismatch_rejected(self):
        r = Run(
            id="r",
            total_instances=10,
            groups=[CompositionRunGroup(id="a", instances=Instances(count=3))],
        )
        with pytest.raises(ValueError, match="mismatch"):
            r.recalculate_instance_counts()


class TestBuildKey:
    def test_requires_builder(self):
        """composition_test.go:246 TestBuildKeyWithoutBuilderPanics."""
        with pytest.raises(ValueError):
            Group(id="a").build_key()

    def test_depends_on_builder(self):
        """composition_test.go:257 TestBuildKeyDependsOnBuilder."""
        a = Group(id="a", builder="docker:go")
        b = Group(id="a", builder="exec:py")
        assert a.build_key() != b.build_key()

    def test_selector_order_canonicalized(self):
        a = Group(id="a", builder="b", build=Build(selectors=["x", "y"]))
        b = Group(id="b", builder="b", build=Build(selectors=["y", "x"]))
        assert a.build_key() == b.build_key()

    def test_dependency_order_canonicalized(self):
        a = Group(
            id="a",
            builder="b",
            build=Build(
                dependencies=[
                    Dependency(module="m1", version="1"),
                    Dependency(module="m2", version="2"),
                ]
            ),
        )
        b = Group(
            id="b",
            builder="b",
            build=Build(
                dependencies=[
                    Dependency(module="m2", version="2"),
                    Dependency(module="m1", version="1"),
                ]
            ),
        )
        assert a.build_key() == b.build_key()

    def test_dependency_target_differentiates_key(self):
        """Deviation from the reference (which keys only module:version):
        two groups overriding the same module at different local paths
        must not share one artifact — the runner reads targets from the
        built snapshot's deps.json at launch."""

        def grp(gid, target):
            return Group(
                id=gid,
                builder="b",
                build=Build(
                    dependencies=[
                        Dependency(module="m", version="1", target=target)
                    ]
                ),
            )

        assert grp("a", "/a").build_key() != grp("b", "/b").build_key()


class TestAccessors:
    def _comp(self):
        return make_composition(
            groups=[
                Group(id="g1", instances=Instances(count=1)),
                Group(id="g2", builder="exec:py", instances=Instances(count=1)),
            ],
            runs=[
                Run(
                    id="r1",
                    groups=[CompositionRunGroup(id="g1", instances=Instances(count=1))],
                ),
                Run(
                    id="r2",
                    groups=[
                        CompositionRunGroup(
                            id="x", group_id="g2", instances=Instances(count=1)
                        )
                    ],
                ),
            ],
        )

    def test_list_builders(self):
        """composition_test.go:223 TestListBuilders."""
        assert self._comp().list_builders() == ["docker:go", "exec:py"]

    def test_list_ids(self):
        c = self._comp()
        assert c.list_run_ids() == ["r1", "r2"]
        assert c.list_group_ids() == ["g1", "g2"]

    def test_frame_for_runs(self):
        """composition_test.go:367 TestFrameForRun."""
        c = self._comp().frame_for_runs("r2")
        assert [r.id for r in c.runs] == ["r2"]
        assert [g.id for g in c.groups] == ["g2"]

    def test_frame_for_unknown_run(self):
        with pytest.raises(KeyError):
            self._comp().frame_for_runs("nope")

    def test_pick_groups(self):
        c = self._comp().pick_groups(1)
        assert [g.id for g in c.groups] == ["g2"]


class TestTomlRoundTrip:
    def test_marshal_is_idempotent(self):
        """composition_test.go:517 TestMarshalIsIdempotent."""
        c = make_composition()
        c2 = Composition.from_toml(c.to_toml())
        assert c2.to_dict() == c.to_dict()
        assert Composition.from_toml(c2.to_toml()).to_dict() == c.to_dict()

    def test_parses_reference_style_toml(self):
        """Reference compositions parse unchanged (issue-1493 style with
        [[runs]]; composition_test.go:290)."""
        text = """
[metadata]
name = "pingpong"

[global]
plan = "network"
case = "ping-pong"
total_instances = 2
builder = "exec:py"
runner = "local:exec"

[global.run]
[global.run.test_params]
maxlat = "100"

[[groups]]
id = "nodes"
[groups.instances]
count = 2

[[runs]]
id = "with-runs"
[runs.test_params]
extra = "1"
[[runs.groups]]
id = "nodes"
[runs.groups.instances]
count = 2
"""
        c = Composition.from_toml(text)
        assert c.metadata.name == "pingpong"
        assert c.global_.plan == "network"
        assert c.global_.run.test_params["maxlat"] == "100"
        assert c.groups[0].instances.count == 2
        assert c.runs[0].id == "with-runs"
        assert c.runs[0].test_params["extra"] == "1"
        validate_for_run(c)


def test_run_group_may_inherit_instances_from_backing_group():
    """Reference-valid pattern: [[runs.groups]] with no instances inherits
    from the backing group at prepare time; validation must accept it."""
    c = make_composition(
        groups=[Group(id="a", instances=Instances(count=2))],
        runs=[Run(id="r", groups=[CompositionRunGroup(id="a")])],
    )
    validate_for_run(c)  # must not raise


def test_pick_groups_rejects_negative_index():
    c = make_composition()
    with pytest.raises(IndexError):
        c.pick_groups(-1)
