"""The ``transport=auto`` measured cost model (ISSUE 14;
``sim/transport_model.py``) — the contracts every gate consumer relies
on:

1. **deterministic scores**: the same workload context scores to the
   identical decision block, fresh-cache or cached.
2. **cache per build-key**: one scoring pass per distinct program
   shape; a changed shape re-scores, an identical context (even a
   freshly-built equal one) does not.
3. **auto == explicit program identity**: the program built from an
   auto resolution traces the identical chunk jaxpr as the explicitly
   chosen backend — the gate only picks a NAME, never a variant.
4. **hard gates**: mesh → xla loudly, direct slot mode → xla, unknown
   knob values refused, context-less auto falls back to xla loudly.
5. **banked verdicts** beat static scoring when a real measurement for
   this backend kind exists (``TG_TRANSPORT_BANK``).
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

import __graft_entry__ as ge
from testground_tpu.sim.executor import resolve_transport
from testground_tpu.sim.transport_model import (
    PALLAS_BYTE_MARGIN,
    TransportContext,
    clear_decision_cache,
    decide_transport,
)

Cfg = dataclasses.make_dataclass("Cfg", [("transport", str)])

SUSTAINED_PARAMS = {
    "duration_ticks": "640",
    "latency_ms": "4",
    "latency2_ms": "2",
    "reshape_every": "1000",
}


def _sorted_ctx(n=512, chunk=32, **kw):
    prog = ge._plan_program(
        "network", "pingpong-sustained", n, SUSTAINED_PARAMS, chunk=chunk
    )
    return TransportContext(
        testcase=prog.tc,
        groups=tuple(prog.groups),
        test_plan="network",
        test_case="pingpong-sustained",
        chunk=chunk,
        **kw,
    )


def _direct_ctx(n=512):
    prog = ge._plan_program(
        "benchmarks",
        "pingpong-flood",
        n,
        {"duration_ticks": "640", "latency_ms": "4"},
    )
    return TransportContext(
        testcase=prog.tc,
        groups=tuple(prog.groups),
        test_plan="benchmarks",
        test_case="pingpong-flood",
        chunk=32,
    )


@pytest.fixture(autouse=True)
def _fresh_cache():
    clear_decision_cache()
    yield
    clear_decision_cache()


class TestDeterministicScores:
    def test_same_context_same_block_across_cache_resets(self):
        d1 = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        clear_decision_cache()
        d2 = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        assert d1.block() == d2.block()
        assert d1.scores["source"] == "static"
        assert d1.scores["ratio"] > 0
        assert d1.scores["margin"] == PALLAS_BYTE_MARGIN

    def test_sorted_flagship_scores_to_pallas_with_reason(self):
        d = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        assert d.requested == "auto"
        assert d.resolved == "pallas"
        assert "kernel estimate" in d.reason
        block = d.block()
        assert set(block) == {"requested", "resolved", "reason", "scores"}


class TestDecisionCache:
    def test_identical_context_hits_cache(self):
        d1 = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        # a FRESH equal context (new objects, same shapes) must hit
        d2 = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        assert d2 is d1

    def test_shape_change_rescores(self):
        d1 = decide_transport(Cfg("auto"), None, context=_sorted_ctx(512))
        d2 = decide_transport(
            Cfg("auto"), None, context=_sorted_ctx(1024)
        )
        assert d2 is not d1
        assert d1.scores["xla_bytes_per_tick"] != (
            d2.scores["xla_bytes_per_tick"]
        )

    def test_shared_gate_identity(self):
        """The executor, the pack path, and the precompile all build
        equivalent contexts independently — the cache key makes them
        resolve identically by construction (the shared-gate test)."""
        seen = {
            resolve_transport(
                Cfg("auto"), None, context=_sorted_ctx()
            )
            for _ in range(3)
        }
        assert seen == {"pallas"}


class TestProgramIdentity:
    def test_auto_program_jaxpr_identical_to_explicit(self):
        resolved = resolve_transport(
            Cfg("auto"), None, context=_sorted_ctx(512, chunk=8)
        )
        assert resolved == "pallas"

        def build(transport):
            return ge._plan_program(
                "network",
                "pingpong-sustained",
                512,
                SUSTAINED_PARAMS,
                chunk=8,
                transport=transport,
            )

        auto_prog = build(resolved)
        explicit = build("pallas")
        carry = jax.jit(lambda: auto_prog.init_carry(0))()
        assert str(jax.make_jaxpr(auto_prog._chunk_step)(carry)) == str(
            jax.make_jaxpr(explicit._chunk_step)(carry)
        )


class TestHardGates:
    def test_indivisible_mesh_resolves_to_xla_loudly(self):
        # 512 lanes across 3 peer shards: no equal per-chip blocks
        devs = jax.devices()[:3]
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        warned = []
        d = decide_transport(
            Cfg("auto"),
            mesh,
            context=_sorted_ctx(),
            warn=lambda fmt, *a: warned.append(fmt % a),
        )
        assert d.resolved == "xla"
        assert "divide" in d.reason
        assert warned and "3 peer shard(s)" in warned[0]

    def test_divisible_mesh_scores_statically(self):
        # 512 lanes across 2 peer shards divide — auto SCORES the mesh
        # arms (per-shard bytes + modeled ICI) instead of refusing
        devs = jax.devices()[:2]
        mesh = jax.sharding.Mesh(np.asarray(devs), ("i",))
        warned = []
        d = decide_transport(
            Cfg("auto"),
            mesh,
            context=_sorted_ctx(),
            warn=lambda fmt, *a: warned.append(fmt % a),
        )
        assert not warned
        assert d.scores is not None
        assert "2 peer shard(s)" in d.reason

    def test_direct_slot_mode_resolves_to_xla(self):
        d = decide_transport(Cfg("auto"), None, context=_direct_ctx())
        assert d.resolved == "xla"
        assert "direct slot mode" in d.reason

    def test_unknown_transport_refused(self):
        with pytest.raises(ValueError, match="unknown transport"):
            decide_transport(Cfg("cuda"), None)

    def test_contextless_auto_falls_back_loudly(self):
        warned = []
        d = decide_transport(
            Cfg("auto"),
            None,
            warn=lambda fmt, *a: warned.append(fmt % a),
        )
        assert d.resolved == "xla"
        assert warned and "context" in warned[0]

    def test_explicit_choices_skip_scoring(self):
        for knob, expect in (("xla", "xla"), ("pallas", "pallas")):
            d = decide_transport(Cfg(knob), None)
            assert (d.requested, d.resolved) == (knob, expect)
            assert d.scores is None


class TestBankedVerdicts:
    def _bank(self, tmp_path, monkeypatch, **rec):
        path = tmp_path / "BENCH_PALLAS_test.json"
        path.write_text(json.dumps(rec) + "\n")
        monkeypatch.setenv("TG_TRANSPORT_BANK", str(path))

    def test_banked_win_overrides_static(self, tmp_path, monkeypatch):
        self._bank(
            tmp_path,
            monkeypatch,
            workload="sustained",
            backend=jax.default_backend(),
            pallas_interpreted=False,
            instances=512,
            pallas_vs_xla=1.62,
        )
        d = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        assert d.resolved == "pallas"
        assert d.scores["source"] == "banked"
        assert "banked bench verdict" in d.reason

    def test_banked_loss_forces_xla(self, tmp_path, monkeypatch):
        self._bank(
            tmp_path,
            monkeypatch,
            workload="sustained",
            backend=jax.default_backend(),
            pallas_interpreted=False,
            instances=512,
            pallas_vs_xla=0.71,
        )
        d = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        assert d.resolved == "xla"
        assert d.scores["source"] == "banked"

    def test_thin_banked_edge_stays_xla(self, tmp_path, monkeypatch):
        """A 1.03x measured win is inside one bench run's spread — the
        banked path demands its own margin (the chip-lottery rule)."""
        self._bank(
            tmp_path,
            monkeypatch,
            workload="sustained",
            backend=jax.default_backend(),
            pallas_interpreted=False,
            instances=512,
            pallas_vs_xla=1.03,
        )
        d = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        assert d.resolved == "xla"
        assert d.scores["source"] == "banked"

    def test_foreign_workload_bank_ignored(self, tmp_path, monkeypatch):
        """A verdict measured on a different workload shape is not
        evidence for this run — static scoring decides instead."""
        self._bank(
            tmp_path,
            monkeypatch,
            workload="storm",  # run context is pingpong-sustained
            backend=jax.default_backend(),
            pallas_interpreted=False,
            instances=512,
            pallas_vs_xla=9.0,
        )
        d = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        assert d.scores["source"] == "static"

    def test_interpreted_and_foreign_backend_rows_ignored(
        self, tmp_path, monkeypatch
    ):
        """Functional-gate rows (interpreted) and other-backend rows
        are never evidence — static scoring decides instead."""
        path = tmp_path / "BENCH_PALLAS_test.json"
        rows = [
            {
                "workload": "sustained",
                "backend": jax.default_backend(),
                "pallas_interpreted": True,  # functional gate only
                "instances": 512,
                "pallas_vs_xla": 0.1,
            },
            {
                "workload": "sustained",
                "backend": "tpu-v99",  # not this backend kind
                "pallas_interpreted": False,
                "instances": 512,
                "pallas_vs_xla": 0.1,
            },
        ]
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        monkeypatch.setenv("TG_TRANSPORT_BANK", str(path))
        d = decide_transport(Cfg("auto"), None, context=_sorted_ctx())
        assert d.scores["source"] == "static"
