"""Manifest tests (``pkg/api/manifest_test.go`` + reference manifest TOML
compatibility)."""

from testground_tpu.api import TestPlanManifest


REFERENCE_STYLE = """
name = "placebo"

[defaults]
builder = "exec:py"
runner = "local:exec"

[builders."exec:py"]
enabled = true

[runners."local:exec"]
enabled = true

[runners."sim:jax"]
enabled = true

[[testcases]]
name = "ok"
instances = { min = 1, max = 200, default = 1 }

  [testcases.params]
  some_param = { type = "int", desc = "some param", unit = "peers" }

[[testcases]]
name = "stall"
instances = { min = 1, max = 250, default = 1 }

[[testcases]]
name = "barrier"
instances = { min = 1, max = 50000, default = 1 }

  [testcases.params]
  barrier_iterations = { type = "int", desc = "iterations", unit = "n", default = 10 }
"""


def test_parses_reference_style_manifest():
    m = TestPlanManifest.from_toml(REFERENCE_STYLE)
    assert m.name == "placebo"
    assert m.has_builder("exec:py")
    assert m.has_runner("local:exec") and m.has_runner("sim:jax")
    assert not m.has_builder("docker:go")
    assert m.defaults["builder"] == "exec:py"

    tc = m.testcase_by_name("ok")
    assert tc.instances.minimum == 1
    assert tc.instances.maximum == 200
    assert tc.instances.default == 1
    assert tc.parameters["some_param"].type == "int"
    assert tc.parameters["some_param"].unit == "peers"

    assert m.testcase_by_name("nope") is None


def test_default_parameters_json_encodes_non_strings():
    m = TestPlanManifest.from_toml(REFERENCE_STYLE)
    assert m.default_parameters("barrier") == {"barrier_iterations": "10"}
    # params with no default are omitted
    assert m.default_parameters("ok") == {}


def test_describe():
    m = TestPlanManifest.from_toml(REFERENCE_STYLE)
    text = m.describe()
    assert '"placebo"' in text
    assert "3 test cases" in text
