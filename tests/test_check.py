"""Static-analysis plane (docs/CHECKING.md): the rule catalog, the
executor/checker NO-DRIFT pin (the checker must refuse exactly the
configs the executor refuses, with the identical message, over a matrix
of bad configs — and pass exactly the configs the executor runs), the
eval_shape/jaxpr plan lints against the deliberately-broken fixture
plan, the ``--json`` schema, CLI exit codes, and the pack
``solo_reason`` classification."""

import json
import threading
import os
import types

import pytest

from testground_tpu.api import (
    Composition,
    Global,
    Group,
    Instances,
    RunGroup,
    RunInput,
    TestPlanManifest,
    generate_default_run,
    prepare_for_run,
)
from testground_tpu.config import CoalescedConfig
from testground_tpu.sim.check import (
    RULES,
    check_composition,
    findings_payload,
    rule_by_id,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")
BADPLAN = os.path.join(REPO_ROOT, "tests", "fixtures", "badplan")


def manifest_of(plan: str) -> TestPlanManifest:
    return TestPlanManifest.load_file(
        os.path.join(PLANS, plan, "manifest.toml")
    )


def make_comp(
    plan="placebo",
    case="ok",
    count=2,
    run_cfg=None,
    slo=None,
    faults=None,
    trace=None,
    params=None,
    disable_metrics=False,
) -> Composition:
    comp = Composition(
        global_=Global(
            plan=plan,
            case=case,
            builder="sim:plan",
            runner="sim:jax",
            run_config=dict(run_cfg or {}),
            disable_metrics=disable_metrics,
        ),
        groups=[Group(id="all", instances=Instances(count=count))],
    )
    if slo:
        # run-GLOBAL tables ([[global.run.slo]]): the run-global metrics
        # (drop_rate/crashed_fraction) refuse a group scope outright
        from testground_tpu.api.composition import RunParams

        comp.global_.run = RunParams(slo=[dict(s) for s in slo])
    if faults:
        comp.groups[0].run.faults = [dict(f) for f in faults]
    if trace:
        comp.groups[0].run.trace = dict(trace)
    if params:
        comp.groups[0].run.test_params = dict(params)
    return generate_default_run(comp)


class _WarnRecorder:
    """OutputWriter stand-in that records rendered warn lines."""

    def __init__(self):
        self.warns: list[str] = []

    def warn(self, fmt, *args):
        self.warns.append(str(fmt) % args if args else str(fmt))

    def infof(self, fmt, *args):
        pass

    def write_error(self, msg):
        pass


def drive_executor(comp: Composition):
    """Run the composition through the REAL executor the way do_run
    does (prepare → coalesce → RunInput → execute_sim_run). Returns
    ``(exception_or_None, warn_lines)``."""
    from testground_tpu.sim.executor import SimJaxConfig, execute_sim_run

    plan = comp.global_.plan
    prepared = prepare_for_run(comp, manifest_of(plan))
    cfg = (
        CoalescedConfig()
        .append(prepared.global_.run_config)
        .coalesce_into(SimJaxConfig)
    )
    run = prepared.runs[0]
    src = os.path.join(PLANS, plan)
    job = RunInput(
        # the [[runs]] id, so the cohort spec-size estimate — which
        # embeds the run id — is byte-identical to the checker's (at
        # engine runtime the id is the task id; the estimate is within
        # len(task_id) bytes of exact, negligible vs the 64 KiB bound)
        run_id=run.id,
        test_plan=prepared.global_.plan,
        test_case=prepared.global_.case,
        total_instances=run.total_instances,
        groups=[
            RunGroup(
                id=rg.id,
                instances=rg.calculated_instance_count,
                artifact_path=src,
                parameters=dict(rg.test_params),
                faults=[dict(f) for f in rg.faults],
                trace=dict(rg.trace),
                slo=[dict(s) for s in rg.slo],
            )
            for rg in run.groups
        ],
        runner_config=cfg,
        disable_metrics=prepared.global_.disable_metrics,
        faults=[
            dict(f)
            for f in (
                prepared.global_.run.faults
                if prepared.global_.run is not None
                else []
            )
        ],
        trace=dict(
            prepared.global_.run.trace
            if prepared.global_.run is not None
            else {}
        ),
        slo=[
            dict(s)
            for s in (
                prepared.global_.run.slo
                if prepared.global_.run is not None
                else []
            )
        ],
    )
    ow = _WarnRecorder()
    try:
        execute_sim_run(job, ow, threading.Event())
    except Exception as e:  # noqa: BLE001 — the refusal under test
        return e, ow.warns
    return None, ow.warns


def checker(comp: Composition, **kw):
    plan = comp.global_.plan
    return check_composition(comp, manifest_of(plan), **kw)


def errors_of(findings):
    return [f for f in findings if f.severity == "error"]


# -------------------------------------------------------------- catalog


class TestCatalog:
    def test_rule_ids_unique_and_valid(self):
        ids = [r.id for r in RULES]
        assert len(ids) == len(set(ids))
        for r in RULES:
            assert r.severity in ("error", "warn"), r
            assert r.layer and r.summary, r
            assert rule_by_id(r.id) is r

    def test_findings_reference_catalogued_rules(self):
        fs = checker(make_comp(run_cfg={"transport": "bogus"}))
        assert fs
        for f in fs:
            r = rule_by_id(f.rule)  # KeyError = uncatalogued finding
            assert f.severity == r.severity
            assert f.layer == r.layer


# ---------------------------------------------------- executor no-drift

# The bad-config matrix: (label, composition kwargs, expected rule id).
# Every entry must (a) make the checker report exactly this error rule
# and (b) make the executor raise — with the IDENTICAL message.
BAD_MATRIX = [
    (
        "transport-unknown",
        dict(run_cfg={"transport": "warp", "max_ticks": 32}),
        "transport.unknown",
    ),
    (
        "bucket-mode",
        dict(run_cfg={"bucket": "sideways", "shard": False, "max_ticks": 32}),
        "buckets.mode-invalid",
    ),
    (
        "bucket-ladder",
        dict(
            run_cfg={
                "bucket": "auto",
                "bucket_ladder": "x,y",
                "shard": False,
                "max_ticks": 32,
            }
        ),
        "buckets.ladder-invalid",
    ),
    (
        "fault-kind",
        dict(
            faults=[{"kind": "meteor", "start_ms": 1.0}],
            run_cfg={"max_ticks": 32},
        ),
        "faults.invalid",
    ),
    (
        "fault-range",
        dict(
            faults=[{"kind": "crash", "instances": "0:99", "start_ms": 1.0}],
            run_cfg={"max_ticks": 32},
        ),
        "faults.invalid",
    ),
    (
        "trace-fraction",
        dict(trace={"fraction": 7.0}, run_cfg={"max_ticks": 32}),
        "trace.invalid",
    ),
    (
        "slo-metric",
        dict(
            slo=[{"metric": "vibes", "op": "<", "threshold": 1}],
            run_cfg={"telemetry": True, "max_ticks": 32},
        ),
        "slo.invalid",
    ),
    (
        "slo-no-telemetry",
        dict(
            slo=[{"metric": "drop_rate", "op": "<", "threshold": 0.5}],
            run_cfg={"max_ticks": 32},
        ),
        "slo.needs-telemetry",
    ),
    (
        "slo-disable-metrics",
        dict(
            slo=[{"metric": "drop_rate", "op": "<", "threshold": 0.5}],
            run_cfg={"telemetry": True, "max_ticks": 32},
            disable_metrics=True,
        ),
        "slo.needs-telemetry",
    ),
    (
        "netmatrix-no-telemetry",
        dict(run_cfg={"netmatrix": True, "max_ticks": 32}),
        "netmatrix.needs-telemetry",
    ),
    (
        "netmatrix-disable-metrics",
        dict(
            run_cfg={
                "netmatrix": True,
                "telemetry": True,
                "max_ticks": 32,
            },
            disable_metrics=True,
        ),
        "netmatrix.needs-telemetry",
    ),
    (
        "cohort-spec-oversize",
        dict(
            run_cfg={"coordinator_address": "127.0.0.1:1", "max_ticks": 32},
            params={"blob": "x" * 70000},
        ),
        "cohort.spec-oversize",
    ),
]


class TestNoDrift:
    """The acceptance pin: the executor cannot refuse a config the
    checker passes, and the checker cannot flag an error the executor
    would run — with IDENTICAL refusal text."""

    @pytest.mark.parametrize(
        "label,kwargs,rule", BAD_MATRIX, ids=[m[0] for m in BAD_MATRIX]
    )
    def test_bad_config_refused_identically(self, label, kwargs, rule):
        comp = make_comp(**kwargs)
        findings = errors_of(checker(make_comp(**kwargs)))
        assert findings, f"checker passed a config the executor refuses"
        assert [f.rule for f in findings] == [rule]
        exc, _ = drive_executor(comp)
        assert exc is not None, (
            f"executor ran a config the checker refuses ({rule})"
        )
        assert str(exc) == findings[0].message

    def test_clean_config_passes_both(self):
        kwargs = dict(run_cfg={"max_ticks": 32})
        assert errors_of(checker(make_comp(**kwargs))) == []
        exc, _ = drive_executor(make_comp(**kwargs))
        assert exc is None

    def test_clean_kitchen_sink_passes_both(self):
        """Faults + trace + telemetry + SLO, all compatible: zero
        findings and a clean run — the checker must not over-refuse."""
        kwargs = dict(
            case="stall",
            count=4,
            run_cfg={"telemetry": True, "max_ticks": 48, "chunk": 16},
            faults=[{"kind": "crash", "instances": "0:1", "start_ms": 4.0}],
            trace={"instances": "0:2"},
            slo=[
                {
                    "metric": "crashed_fraction",
                    "op": "<=",
                    "threshold": 1.0,
                }
            ],
        )
        fs = checker(make_comp(**kwargs))
        assert fs == []
        exc, _ = drive_executor(make_comp(**kwargs))
        assert exc is None


class TestWarnParity:
    """Warn-severity rules: the checker's finding mirrors the warn the
    executor emits when it falls back (matched by content — executor
    lines carry run-id prefixes)."""

    def test_transport_mesh_indivisible(self):
        # conftest pins an 8-device virtual CPU mesh, so shard=True
        # meshes — and 2 lanes do not divide across 8 peer shards, so
        # the gate must fall back to xla loudly (a DIVISIBLE layout
        # runs sharded instead; tests/test_sim_mesh.py pins that side)
        kwargs = dict(run_cfg={"transport": "pallas", "max_ticks": 32})
        fs = checker(make_comp(**kwargs), devices=8)
        fired = [f for f in fs if f.rule == "transport.mesh-indivisible"]
        assert len(fired) == 1
        assert "2 lane(s)" in fired[0].message
        exc, warns = drive_executor(make_comp(**kwargs))
        assert exc is None
        assert any(fired[0].message == w for w in warns), (
            fired[0].message,
            warns,
        )

    def test_bucket_mesh_indivisible(self):
        # rung 6 holds the 2 instances but does not divide across the
        # 8 peer shards — bucketing falls back to exact shapes loudly
        kwargs = dict(
            run_cfg={
                "bucket": "auto",
                "bucket_ladder": "6",
                "max_ticks": 32,
            }
        )
        fs = checker(make_comp(**kwargs), devices=8)
        fired = [f for f in fs if f.rule == "buckets.mesh-indivisible"]
        assert len(fired) == 1
        exc, warns = drive_executor(make_comp(**kwargs))
        assert exc is None
        assert any(fired[0].message == w for w in warns)

    def test_mesh_shape_invalid(self):
        fs = checker(
            make_comp(run_cfg={"mesh": "nope", "max_ticks": 32}),
            devices=8,
        )
        fired = [f for f in fs if f.rule == "mesh.shape-invalid"]
        assert len(fired) == 1
        assert "'nope'" in fired[0].message

    def test_trace_disabled_under_bucketing(self):
        kwargs = dict(
            trace={"instances": "0:1"},
            run_cfg={
                "bucket": "auto",
                "bucket_ladder": "16",
                "shard": False,
                "max_ticks": 32,
            },
        )
        fs = checker(make_comp(**kwargs), devices=1)
        fired = [f for f in fs if f.rule == "trace.bucket-disabled"]
        assert len(fired) == 1
        exc, warns = drive_executor(make_comp(**kwargs))
        assert exc is None
        assert any(
            "flight recorder disabled under shape bucketing" in w
            for w in warns
        )

    def test_cohort_gates_warn_without_running(self):
        """Cohort exclusions (telemetry/slo/trace/checkpoint/nan_guard
        off, resume refused) — checker-side only: a real cohort join
        would hang on the fake coordinator, so these rules are pinned
        to the executor by the shared message constants instead."""
        kwargs = dict(
            run_cfg={
                "coordinator_address": "127.0.0.1:1",
                "telemetry": True,
                "netmatrix": True,
                "checkpoint_chunks": 2,
                "nan_guard": True,
                "resume_from": "sometask",
            },
            trace={"instances": "0:1"},
            slo=[{"metric": "drop_rate", "op": "<", "threshold": 0.5}],
        )
        fs = checker(make_comp(**kwargs), devices=1)
        fired = {f.rule for f in fs}
        assert {
            "telemetry.cohort-disabled",
            "netmatrix.cohort-disabled",
            "trace.cohort-disabled",
            "slo.cohort-disabled",
            "checkpoint.cohort-disabled",
            "checkpoint.resume-cohort",
            "debug.nan-guard-cohort",
        } <= fired
        # resume-under-cohort is the one ERROR in the set, and its text
        # is the executor's own (shared constant — drift-proof)
        from testground_tpu.sim.check import resume_cohort_message

        err = [f for f in fs if f.rule == "checkpoint.resume-cohort"]
        assert err[0].message == resume_cohort_message()

    def test_unknown_run_cfg_key(self):
        fs = checker(make_comp(run_cfg={"trasnport": "pallas"}))
        fired = [f for f in fs if f.rule == "run-cfg.unknown-key"]
        assert len(fired) == 1 and "trasnport" in fired[0].message


# ------------------------------------------------------ pack solo reason


class TestPackSoloReason:
    def _comp_dict(self, run_cfg=None, faults=None, runs=1):
        comp = make_comp(run_cfg=run_cfg, faults=faults)
        d = comp.to_dict()
        if runs > 1:
            d["runs"] = [dict(d["runs"][0], id=f"r{i}") for i in range(runs)]
        return d

    def test_not_requested_is_none(self):
        from testground_tpu.engine.pack import solo_reason_for_composition

        assert (
            solo_reason_for_composition(self._comp_dict(run_cfg={}))
            is None
        )

    def test_packable_is_none(self):
        from testground_tpu.engine.pack import solo_reason_for_composition

        assert (
            solo_reason_for_composition(
                self._comp_dict(run_cfg={"pack": True})
            )
            is None
        )

    @pytest.mark.parametrize(
        "run_cfg,needle",
        [
            ({"pack": True, "checkpoint_chunks": 2}, "checkpoint"),
            ({"pack": True, "coordinator_address": "h:1"}, "cohort"),
            ({"pack": True, "resume_from": "t"}, "resume_from"),
            ({"pack": True, "profile": True}, "profiler"),
            ({"pack": True, "phases": True}, "phase"),
            ({"pack": True, "additional_hosts": ["svc"]}, "additional_hosts"),
            ({"pack": True, "bucket": "sideways"}, "bucket"),
        ],
    )
    def test_exclusion_reasons(self, run_cfg, needle):
        from testground_tpu.engine.pack import solo_reason_for_composition

        reason = solo_reason_for_composition(self._comp_dict(run_cfg=run_cfg))
        assert reason and needle in reason, (run_cfg, reason)

    def test_faults_and_multi_runs_reasons(self):
        from testground_tpu.engine.pack import solo_reason_for_composition

        reason = solo_reason_for_composition(
            self._comp_dict(
                run_cfg={"pack": True},
                faults=[{"kind": "crash", "start_ms": 1.0}],
            )
        )
        assert reason and "chaos schedule" in reason
        reason = solo_reason_for_composition(
            self._comp_dict(run_cfg={"pack": True}, runs=3)
        )
        assert reason and "multi-[[runs]]" in reason

    def test_signature_unchanged_for_packable_tasks(self):
        """The refactor must not move any packable task out of (or
        into) a pack: same composition → same signature, and a solo
        cause → None signature."""
        from testground_tpu.engine.pack import pack_signature
        from testground_tpu.engine.task import TaskType

        def tsk(run_cfg):
            return types.SimpleNamespace(
                type=TaskType.RUN,
                runner="sim:jax",
                composition=self._comp_dict(run_cfg=run_cfg),
                input={"manifest": {}, "sources_dir": "x"},
            )

        a = pack_signature(tsk({"pack": True}))
        b = pack_signature(tsk({"pack": True}))
        assert a is not None and a == b
        assert pack_signature(tsk({"pack": True, "profile": True})) is None
        assert pack_signature(tsk({})) is None

    def test_checker_pack_solo_rule(self):
        fs = checker(
            make_comp(run_cfg={"pack": True, "checkpoint_chunks": 4})
        )
        fired = [f for f in fs if f.rule == "pack.solo"]
        assert len(fired) == 1 and "checkpoint" in fired[0].message
        # a packable composition fires nothing
        fs = checker(make_comp(run_cfg={"pack": True}))
        assert not [f for f in fs if f.rule == "pack.solo"]

    def test_resume_multi_runs_rule(self):
        comp = make_comp(run_cfg={"resume_from": "oldtask"})
        comp.runs = [comp.runs[0], comp.runs[0].__class__.from_dict(
            dict(comp.runs[0].to_dict(), id="second")
        )]
        fs = checker(comp)
        assert any(f.rule == "checkpoint.resume-multi-runs" for f in fs)


# --------------------------------------------------- eval_shape plan lints


def badplan_comp(case: str) -> Composition:
    return make_comp(
        plan="badplan",
        case=case,
        count=5,
        run_cfg={
            "bucket": "auto",
            "bucket_ladder": "16,64",
            "shard": False,
        },
    )


def badplan_check(case: str):
    return check_composition(
        badplan_comp(case),
        TestPlanManifest.load_file(os.path.join(BADPLAN, "manifest.toml")),
        trace_plans=True,
        plan_sources=BADPLAN,
    )


class TestPlanLints:
    def test_python_int_on_traced_count(self):
        fs = badplan_check("int-on-count")
        fired = [f for f in fs if f.rule == "plan.traced-int"]
        assert len(fired) == 1
        assert fired[0].severity == "error"
        assert "padded shapes" in fired[0].message

    def test_host_callback_in_tick(self):
        fs = badplan_check("debug-print")
        fired = [f for f in fs if f.rule == "plan.host-callback"]
        assert len(fired) == 1
        assert "debug_callback" in fired[0].message

    def test_while_loop_in_tick(self):
        fs = badplan_check("while-tick")
        assert any(f.rule == "plan.while-loop" for f in fs)

    def test_weak_type_state(self):
        fs = badplan_check("weak-state")
        fired = [f for f in fs if f.rule == "plan.weak-type"]
        assert len(fired) == 1 and "dtype" in fired[0].message

    def test_clean_control_is_silent(self):
        assert badplan_check("clean") == []

    def test_missing_case_is_load_failure(self):
        fs = check_composition(
            make_comp(plan="badplan", case="clean", count=2),
            # manifest that declares a case the sim module lacks
            TestPlanManifest.from_dict(
                {
                    "name": "badplan",
                    "builders": {"sim:plan": {"enabled": True}},
                    "runners": {"sim:jax": {"enabled": True}},
                    "testcases": [
                        {
                            "name": "clean",
                            "instances": {
                                "min": 1,
                                "max": 16,
                                "default": 2,
                            },
                        }
                    ],
                }
            ),
            trace_plans=True,
            plan_sources=os.path.join(PLANS, "placebo"),
        )
        fired = [f for f in fs if f.rule == "plan.load-failed"]
        assert len(fired) == 1
        assert "unknown sim test case" in fired[0].message

    def test_repo_plans_lint_clean(self):
        """Dogfood: the chaos smoke composition (faults + trace +
        telemetry + SLO) must trace to zero findings."""
        from testground_tpu.api import load_composition

        comp = load_composition(
            os.path.join(PLANS, "chaos", "_compositions", "smoke.toml")
        )
        fs = check_composition(
            comp,
            manifest_of("chaos"),
            trace_plans=True,
            plan_sources=os.path.join(PLANS, "chaos"),
        )
        assert fs == []


# ------------------------------------------------------- json + CLI


class TestJsonSchema:
    def test_payload_schema_v1(self):
        fs = checker(make_comp(run_cfg={"transport": "warp"}))
        doc = findings_payload([("x.toml", fs)])
        assert doc["version"] == 1
        assert set(doc) == {"version", "compositions", "errors", "warnings"}
        comp = doc["compositions"][0]
        assert set(comp) == {"file", "findings", "errors", "warnings"}
        assert comp["file"] == "x.toml"
        assert comp["errors"] == 1
        f = comp["findings"][0]
        assert {"rule", "severity", "layer", "message"} <= set(f)
        assert f["rule"] == "transport.unknown"
        json.dumps(doc)  # must be serializable as-is

    def test_run_attribution(self):
        fs = checker(
            make_comp(faults=[{"kind": "meteor", "start_ms": 1.0}])
        )
        f = [x for x in fs if x.rule == "faults.invalid"][0]
        assert f.to_dict()["run"] == "default"


class TestCli:
    @pytest.fixture()
    def chdir_repo(self, monkeypatch):
        monkeypatch.chdir(REPO_ROOT)

    def _write(self, tmp_path, body: str) -> str:
        p = tmp_path / "comp.toml"
        p.write_text(body)
        return str(p)

    CLEAN = """\
[metadata]
name = "ok"
[global]
plan = "placebo"
case = "ok"
builder = "sim:plan"
runner = "sim:jax"
[[groups]]
id = "all"
[groups.instances]
count = 2
"""

    BAD = CLEAN + """
[[global.run.slo]]
metric = "drop_rate"
op = "<"
threshold = 0.1
"""

    def test_exit_0_on_clean(self, tg_home, chdir_repo, tmp_path, capsys):
        from testground_tpu.cli.main import main

        rc = main(["check", self._write(tmp_path, self.CLEAN)])
        out = capsys.readouterr().out
        assert rc == 0 and "ok (no findings)" in out

    def test_exit_1_on_error_findings(
        self, tg_home, chdir_repo, tmp_path, capsys
    ):
        from testground_tpu.cli.main import main

        rc = main(["check", self._write(tmp_path, self.BAD), "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert doc["errors"] == 1
        assert (
            doc["compositions"][0]["findings"][0]["rule"]
            == "slo.needs-telemetry"
        )

    def test_exit_2_on_unloadable_file(
        self, tg_home, chdir_repo, tmp_path, capsys
    ):
        from testground_tpu.cli.main import main

        rc = main(["check", str(tmp_path / "missing.toml")])
        assert rc == 2
        assert "cannot check" in capsys.readouterr().out

    def test_unloadable_file_lands_in_json_document(
        self, tg_home, chdir_repo, tmp_path, capsys
    ):
        """A load failure is a finding, not a stderr aside: --json
        consumers must see WHICH file was unloadable and why, and the
        document's error count must disagree with a clean run."""
        import json as _json

        from testground_tpu.cli.main import main

        missing = str(tmp_path / "missing.toml")
        rc = main(["check", "--json", missing])
        assert rc == 2
        doc = _json.loads(capsys.readouterr().out)
        assert doc["errors"] == 1
        (entry,) = doc["compositions"]
        assert entry["file"] == missing
        (f,) = entry["findings"]
        assert f["rule"] == "composition.invalid"
        assert "cannot check" in f["message"]

    def test_run_cfg_override(self, tg_home, chdir_repo, tmp_path, capsys):
        """--run-cfg lets the operator probe a knob combination without
        editing the file: the clean composition + a bad transport."""
        from testground_tpu.cli.main import main

        rc = main(
            [
                "check",
                self._write(tmp_path, self.CLEAN),
                "--run-cfg",
                "transport=warp",
            ]
        )
        assert rc == 1
        assert "transport.unknown" in capsys.readouterr().out
