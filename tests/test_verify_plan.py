"""verify plan tests (sim twin of /root/reference/plans/verify — the
transport-invariant plan: data network delivers exactly, control plane and
DROPped routes deliver nothing)."""

import numpy as np

from testground_tpu.sim.api import FAILURE, SUCCESS
from testground_tpu.sim.engine import SimProgram

from test_sim_engine import make_groups, mesh8, plan_case


def run_case(case, n, params=None, mesh=None, max_ticks=4096, chunk=32):
    prog = SimProgram(
        plan_case("verify", case),
        make_groups(n, params=params),
        test_plan="verify",
        test_case=case,
        mesh=mesh,
        chunk=chunk,
    )
    return prog.run(max_ticks=max_ticks)


class TestUsesDataNetwork:
    def test_all_success_and_exact_delivery(self):
        n, pings = 8, 4
        res = run_case("uses-data-network", n, params={"pings": str(pings)})
        assert (res["status"] == SUCCESS).all()
        tc = plan_case("verify", "uses-data-network")

        class G:
            id = "g0"
            offset = 0
            count = n
            params = {"pings": str(pings)}

        m = tc.collect_metrics(G, res["states"][0], res["status"])
        pongs = np.asarray(m["pongs_received"])
        recv = np.asarray(m["pings_delivered_to_target"])
        # every pinger got every data pong; the target saw exactly the
        # data pings (control pings never delivered)
        assert int(recv.max()) == (n - 1) * pings
        assert int(pongs.sum()) == (n - 1) * pings

    def test_two_instances(self):
        res = run_case("uses-data-network", 2, params={"pings": "3"})
        assert (res["status"] == SUCCESS).all()

    def test_sharded_equals_single(self):
        params = {"pings": "3"}
        res_s = run_case("uses-data-network", 16, params=params)
        res_m = run_case("uses-data-network", 16, params=params, mesh=mesh8())
        assert (res_s["status"] == res_m["status"]).all()
        np.testing.assert_array_equal(
            np.asarray(res_s["states"][0]["pongs_data"]),
            np.asarray(res_m["states"][0]["pongs_data"]),
        )


class TestUsesDataNetworkDrop:
    def test_drop_all_delivers_zero(self):
        n, pings = 8, 4
        res = run_case(
            "uses-data-network-drop", n, params={"pings": str(pings)}
        )
        assert (res["status"] == SUCCESS).all()
        st = res["states"][0]
        # the invariant itself: zero delivery anywhere
        assert int(np.asarray(st["recv"]).sum()) == 0
        assert int(np.asarray(st["pongs_data"]).sum()) == 0
        # and the pingers really did send into the blackhole
        assert int(np.asarray(st["sent"]).max()) == pings

    def test_drop_invariant_catches_leaks(self):
        """Sanity of the verdict logic: the plain case run with DROP_ALL
        expectations would fail — i.e., the testcase can actually fail."""
        n = 6
        tc_cls = type(plan_case("verify", "uses-data-network"))

        class LeakExpected(tc_cls):
            DROP_ALL = True
            SHAPING = ("latency",)  # filters compiled out → traffic flows

        prog = SimProgram(
            LeakExpected(),
            make_groups(n, params={"pings": "2"}),
            test_plan="verify",
            test_case="leak",
            chunk=32,
        )
        res = prog.run(max_ticks=1024)
        assert (np.asarray(res["status"]) == FAILURE).any()
