"""Fleet controller: preemption-tolerant orchestration (docs/FLEET.md).

Pins the acceptance contracts:

- **live migration**: a preempted RUN requeues itself with
  ``resume_from`` pointing at its OWN snapshots and completes; the
  journal records ``task.preempt_requested/preempted/migrated`` in
  causal order;
- **races**: double-preempt is idempotent (one journal record); a
  preempt landing between queue-pop and claim is not lost (the engine
  pre-registers the event the worker later adopts);
- **eviction policy**: only lower-priority tasks are evictable; lowest
  priority first, checkpointed preferred, most-recently-started breaks
  ties;
- **drain**: ``engine.drain()`` preempts running work, refuses to claim
  while draining, journals ``daemon.drain``, and is idempotent;
- **resume hardening**: snapshot loads retry with bounded exponential
  backoff; a corrupt newest snapshot falls back LOUDLY to the previous
  retained one; only an all-corrupt dir refuses;
- **admission-at-submit**: the daemon refuses compositions ``tg check``
  rejects with the same rule ids (HTTP 422 + ``task.refused``), while
  the in-process engine still queues them (back-compat);
- **observability**: ``tg_fleet_preemptions/evictions/refused_total``
  render, ``tg top`` shows the PRE column + DRAINING banner, the CLI
  grew ``tg preempt`` and ``tg terminate --drain``;
- **bit-equality** (slow — real sim runs): a preempted-and-resumed solo
  run, a twice-preempted run, an evicted victim, and a preempted pack
  member all land journal- and stream-equal with an uninterrupted
  baseline.
"""

import json
import os
import shutil
import time

import numpy as np
import pytest

from testground_tpu.api import (
    Composition,
    Global,
    Group,
    Instances,
    RunOutput,
    TestPlanManifest,
    generate_default_run,
)
from testground_tpu.config import EnvConfig
from testground_tpu.engine import Outcome, State
from testground_tpu.engine.controller import (
    TaskPreemptedError,
    pick_eviction_victim,
)
from testground_tpu.runners.base import Runner
from testground_tpu.runners.result import Result
from tests.test_engine import (
    make_engine,
    simple_composition,
    simple_manifest,
    wait_complete,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


# ------------------------------------------------------- eviction policy


class TestEvictionPolicy:
    def _c(self, cid, priority=0, started=0.0, checkpointed=False):
        return {
            "id": cid,
            "priority": priority,
            "started": started,
            "checkpointed": checkpointed,
        }

    def test_only_lower_priority_is_evictable(self):
        assert pick_eviction_victim([self._c("a", 5)], 5) is None
        assert pick_eviction_victim([self._c("a", 7)], 5) is None
        assert pick_eviction_victim([], 5) is None
        assert pick_eviction_victim([self._c("a", 4)], 5)["id"] == "a"

    def test_lowest_priority_first(self):
        got = pick_eviction_victim(
            [self._c("a", 3), self._c("b", 0), self._c("c", 1)], 5
        )
        assert got["id"] == "b"

    def test_checkpointed_preferred_then_most_recent(self):
        got = pick_eviction_victim(
            [
                self._c("plain", 0, started=10.0),
                self._c("ckpt", 0, started=5.0, checkpointed=True),
            ],
            5,
        )
        assert got["id"] == "ckpt"  # cheap to migrate: snapshots exist
        got = pick_eviction_victim(
            [self._c("old", 0, started=5.0), self._c("new", 0, started=9.0)],
            5,
        )
        assert got["id"] == "new"  # least sunk work lost


# ------------------------------------------------ fake-runner preemption


class PreemptOnceRunner(Runner):
    """The sim executor's preemption contract without JAX: the first
    invocation blocks until its RunInput's preempt event fires, then
    raises the typed TaskPreemptedError; later invocations (the
    resumed/rerun attempt) succeed immediately."""

    def __init__(self, resumable=True, wait_secs=10.0):
        self.jobs = []
        self.resumable = resumable
        self.wait_secs = wait_secs

    def id(self):
        return "fake:runner"

    def compatible_builders(self):
        return ["fake:builder"]

    def run(self, job, ow, cancel):
        self.jobs.append(job)
        if len(self.jobs) == 1:
            ev = getattr(job, "preempt", None)
            assert ev is not None, "solo RunInput carries no preempt event"
            if not ev.wait(timeout=self.wait_secs):
                raise RuntimeError("preempt event never fired")
            raise TaskPreemptedError(
                job.run_id,
                tick=32,
                snapshot_tick=32,
                snapshots=2,
                resumable=self.resumable,
            )
        r = Result.for_input(job)
        for g in job.groups:
            for _ in range(g.instances):
                r.add_outcome(g.id, Outcome.SUCCESS)
        r.update_outcome()
        return RunOutput(run_id=job.run_id, result=r)


def _wait_state(engine, tid, state, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state == state:
            return t
        time.sleep(0.01)
    raise TimeoutError(f"task {tid} never reached {state}")


def _journal_rows(engine, tid=None):
    with open(engine.events.path) as f:
        rows = [json.loads(line) for line in f]
    if tid is not None:
        rows = [r for r in rows if r.get("task") == tid]
    return rows


class TestPreemptRequeue:
    def test_preempt_requeues_resumes_and_journals(self, tg_home):
        runner = PreemptOnceRunner(resumable=True)
        engine = make_engine(tg_home, runner=runner)
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            _wait_state(engine, tid, State.PROCESSING)
            assert engine.preempt(tid) == {"ok": True, "queued": False}
            # double-preempt: idempotent, no second journal record
            assert engine.preempt(tid)["ok"] is True
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.SUCCESS, t.error
            assert int(t.trace["preemptions"]) == 1
            # the requeue pointed the resume at the task's OWN snapshots
            rc = t.composition["global"]["run_config"]
            assert rc["resume_from"] == tid
            rows = _journal_rows(engine, tid)
            types = [r["type"] for r in rows]
            assert types.count("task.preempt_requested") == 1
            order = [
                "task.scheduled",
                "task.claimed",
                "task.preempt_requested",
                "task.preempted",
                "task.migrated",
                "task.finished",
            ]
            idx = [types.index(x) for x in order]
            assert idx == sorted(idx), types
            # the requeued task was claimed a SECOND time after the
            # migration, then finished
            assert types.count("task.claimed") == 2
            last_claim = len(types) - 1 - types[::-1].index("task.claimed")
            assert types.index("task.migrated") < last_claim
            assert last_claim < types.index("task.finished")
            mig = next(r for r in rows if r["type"] == "task.migrated")
            assert mig["resume_from"] == tid
            pre = next(r for r in rows if r["type"] == "task.preempted")
            assert pre["resumable"] is True and pre["preemptions"] == 1
            assert engine.fleet_info()["preemptions"] == 1
            # both attempts actually ran through the runner
            assert len(runner.jobs) == 2
        finally:
            engine.stop()

    def test_non_resumable_reruns_without_rewriting_composition(
        self, tg_home
    ):
        runner = PreemptOnceRunner(resumable=False)
        engine = make_engine(tg_home, runner=runner)
        engine.start_workers()
        try:
            comp = simple_composition()
            comp.global_.run_config["resume_from"] = "user-chose-this"
            tid = engine.queue_run(
                generate_default_run(comp), simple_manifest()
            )
            _wait_state(engine, tid, State.PROCESSING)
            engine.preempt(tid)
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.SUCCESS, t.error
            # a non-resumable preemption must NOT clobber the user's
            # own resume_from with the task's (snapshot-less) id
            rc = t.composition["global"]["run_config"]
            assert rc["resume_from"] == "user-chose-this"
            mig = next(
                r
                for r in _journal_rows(engine, tid)
                if r["type"] == "task.migrated"
            )
            assert mig["resume_from"] == ""
        finally:
            engine.stop()

    def test_preempt_before_claim_is_not_lost(self, tg_home):
        """The pop-to-claim race: a preempt registered before any worker
        claims must be the SAME event the executor later observes."""
        runner = PreemptOnceRunner(resumable=True, wait_secs=0.5)
        engine = make_engine(tg_home, runner=runner)
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            # arm the preempt while the task is still queued and no
            # worker exists — the claim must adopt this very event
            engine.register_preempt(tid).set()
            engine.start_workers()
            t = wait_complete(engine, tid)
            assert t.outcome() == Outcome.SUCCESS, t.error
            assert int(t.trace["preemptions"]) == 1
        finally:
            engine.stop()

    def test_preempt_refusals(self, tg_home):
        engine = make_engine(tg_home)  # workers NOT started
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            # queued: durable no-op success, stays queued
            assert engine.preempt(tid) == {"ok": True, "queued": True}
            assert engine.get_task(tid).state().state == State.SCHEDULED
            # unknown task
            assert engine.preempt("nope")["ok"] is False
            # terminal task
            engine.kill(tid)
            res = engine.preempt(tid)
            assert res["ok"] is False and "only running" in res["error"]
        finally:
            engine.stop()


class TestDrain:
    def test_drain_preempts_running_and_parks(self, tg_home):
        runner = PreemptOnceRunner(resumable=True)
        engine = make_engine(tg_home, runner=runner)
        engine.start_workers()
        try:
            tid = engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            _wait_state(engine, tid, State.PROCESSING)
            res = engine.drain(timeout_secs=10.0)
            assert res["drained"] is True
            assert res["preempted"] == [tid]
            # requeued but NOT reclaimed: workers refuse to claim while
            # draining
            t = engine.get_task(tid)
            assert t.state().state == State.SCHEDULED
            assert int(t.trace["preemptions"]) == 1
            time.sleep(0.3)
            assert engine.get_task(tid).state().state == State.SCHEDULED
            assert engine.draining() and engine.fleet_info()["draining"]
            assert engine.fleet_payload()["draining"]
            types = [r["type"] for r in _journal_rows(engine)]
            assert "daemon.drain" in types
        finally:
            engine.stop()

    def test_drain_idle_is_immediate_and_idempotent(self, tg_home):
        engine = make_engine(tg_home)
        try:
            res = engine.drain(timeout_secs=1.0)
            assert res == {"drained": True, "preempted": [], "canceled": []}
            again = engine.drain(timeout_secs=1.0)
            assert again["drained"] is True
            drains = [
                r
                for r in _journal_rows(engine)
                if r["type"] == "daemon.drain"
            ]
            assert len(drains) == 2
            assert drains[0]["already_draining"] is False
            assert drains[1]["already_draining"] is True
        finally:
            engine.stop()


# ------------------------------------------------------ resume hardening


def _mk_snapshot(run_dir, tick):
    from testground_tpu.sim.checkpoint import save_snapshot

    from testground_tpu.sim.checkpoint import FORMAT_VERSION

    path = save_snapshot(
        run_dir,
        {
            "tick": tick,
            "marker": f"snap-{tick}",
            "version": FORMAT_VERSION,
            "leaves": [{"i": 0}],
        },
        [np.arange(4) + tick],
    )[0]
    return path


def _truncate(path):
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 3)


class TestResumeHardening:
    def test_retry_backoff_is_bounded_exponential(self, tmp_path, monkeypatch):
        import testground_tpu.sim.checkpoint as ckpt

        path = _mk_snapshot(str(tmp_path), 16)
        _truncate(path)
        delays = []
        monkeypatch.setattr(ckpt.time, "sleep", delays.append)
        monkeypatch.setattr(ckpt, "_RETRY_JITTER_SECS", 0.0)
        with pytest.raises(ckpt.CheckpointError):
            ckpt._load_snapshot_retrying(path)
        # attempts-1 sleeps, doubling from the base
        base = ckpt._RETRY_BASE_SECS
        assert delays == [base * 2**i for i in range(ckpt._RETRY_ATTEMPTS - 1)]

    def test_corrupt_newest_falls_back_loudly(self, tmp_path, monkeypatch):
        import testground_tpu.sim.checkpoint as ckpt

        monkeypatch.setattr(ckpt, "_RETRY_BASE_SECS", 0.001)
        monkeypatch.setattr(ckpt, "_RETRY_JITTER_SECS", 0.0)
        run_dir = str(tmp_path)
        _mk_snapshot(run_dir, 16)
        newest = _mk_snapshot(run_dir, 32)
        _truncate(newest)
        manifest, leaves, path = ckpt.load_latest(run_dir)
        assert manifest["marker"] == "snap-16"
        assert np.array_equal(leaves[0], np.arange(4) + 16)
        fb = manifest["_fallback"]
        assert fb["skipped"] == [os.path.basename(newest)]
        assert fb["error"]

    def test_all_corrupt_refuses_loudly(self, tmp_path, monkeypatch):
        import testground_tpu.sim.checkpoint as ckpt

        monkeypatch.setattr(ckpt, "_RETRY_BASE_SECS", 0.001)
        monkeypatch.setattr(ckpt, "_RETRY_JITTER_SECS", 0.0)
        run_dir = str(tmp_path)
        for tick in (16, 32):
            _truncate(_mk_snapshot(run_dir, tick))
        with pytest.raises(ckpt.CheckpointError, match="refusing to resume"):
            ckpt.load_latest(run_dir)


# --------------------------------------------------- admission-at-submit


def _network_comp(run_config=None, case="ping-pong", params=None):
    comp = generate_default_run(
        Composition(
            global_=Global(
                plan="network",
                case=case,
                builder="sim:plan",
                runner="sim:jax",
                run_config=dict(run_config or {}),
            ),
            groups=[Group(id="all", instances=Instances(count=2))],
        )
    )
    if params:
        comp.runs[0].groups[0].test_params.update(params)
    return comp


BAD_RUN_CFG = {"transport": "bogus", "chunk": 16}


class TestAdmissionAtSubmit:
    def test_daemon_refuses_with_rule_ids(self, tg_home):
        from testground_tpu.client import Client, DaemonError
        from testground_tpu.daemon import Daemon

        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        try:
            c = Client(d.address)
            assert c.import_plan(os.path.join(PLANS, "network")) == "network"
            with pytest.raises(DaemonError) as ei:
                c.run(_network_comp(BAD_RUN_CFG).to_dict())
            assert "transport.unknown" in str(ei.value)
            assert "refused at submit" in str(ei.value)
            # nothing queued; the refusal is journaled + counted
            assert d.engine.fleet_info()["refused"] == 1
            ref = next(
                r
                for r in _journal_rows(d.engine)
                if r["type"] == "task.refused"
            )
            assert "transport.unknown" in ref["rules"]
        finally:
            d.stop()

    def test_in_process_queue_run_still_accepts(self, tg_home):
        """Back-compat pin: admission gates the daemon boundary only —
        the in-process engine queues what it is given (tests and tools
        construct deliberately-bad compositions on purpose)."""
        engine = make_engine(tg_home)  # workers NOT started
        try:
            comp = generate_default_run(simple_composition())
            comp.global_.run_config.update(BAD_RUN_CFG)
            tid = engine.queue_run(comp, simple_manifest())
            assert engine.get_task(tid).state().state == State.SCHEDULED
        finally:
            engine.stop()


# --------------------------------------------------------- observability


class TestFleetObservability:
    def test_preempt_counters_render_prometheus(self, tg_home):
        from testground_tpu.metrics.prometheus import render_prometheus

        engine = make_engine(tg_home)
        try:
            engine.fleet_note_preemption()
            engine.fleet_note_preemption()
            with engine._fleet_lock:
                engine._fleet_evictions += 1
            engine.note_refused(simple_composition(), ["transport.unknown"])
            text = render_prometheus([], fleet=engine.fleet_info())
            assert "tg_fleet_preemptions_total 2" in text
            assert "tg_fleet_evictions_total 1" in text
            assert "tg_fleet_refused_total 1" in text
        finally:
            engine.stop()

    def test_render_fleet_pre_column_and_draining_banner(self, tg_home):
        from testground_tpu.runners.pretty import render_fleet

        engine = make_engine(tg_home)
        try:
            engine.queue_run(
                generate_default_run(simple_composition()), simple_manifest()
            )
            out = render_fleet(engine.fleet_payload())
            assert "PRE" in out and "DRAINING" not in out
            engine._draining.set()
            assert "DRAINING" in render_fleet(engine.fleet_payload())
            # PRE column renders the per-task migration count
            solo = render_fleet(
                {"tasks": [{"id": "t", "state": "processing",
                            "preemptions": 3}]}
            )
            assert "PRE" in solo and "3" in solo
        finally:
            engine.stop()

    def test_cli_preempt_and_terminate_drain(self, tg_home, capsys):
        from testground_tpu.cli.main import main

        assert main(["preempt", "no-such-task"]) == 1
        assert "unknown task" in capsys.readouterr().err
        assert main(["terminate", "--drain"]) == 0
        assert "drained" in capsys.readouterr().out

    def test_events_carry_new_types_over_http(self, tg_home):
        from testground_tpu.client import Client
        from testground_tpu.daemon import Daemon

        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        try:
            d.engine.events.emit("task.preempted", task="x" * 20)
            d.engine.events.emit("task.evicted", task="x" * 20)
            types = [r["type"] for r in Client(d.address).events()]
            assert "task.preempted" in types and "task.evicted" in types
        finally:
            d.stop()


# ----------------------------------------------- bit-equality pins (sim)


SUSTAINED_CFG = {
    "chunk": 16,
    "seed": 5,
    "max_ticks": 512,
    "telemetry": True,
    "checkpoint_chunks": 1,
    "checkpoint_keep": 3,
}

_COMPARE_KEYS = (
    "ticks",
    "msgs_delivered",
    "msgs_sent",
    "msgs_enqueued",
    "msgs_dropped",
    "msgs_in_flight",
)


def _sim_engine(env):
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.engine import Engine, EngineConfig
    from testground_tpu.sim.runner import SimJaxRunner

    env.daemon.scheduler.workers = 1
    return Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )


def _queue_sustained(engine, duration=400, priority=0, extra_cfg=None):
    cfg = dict(SUSTAINED_CFG)
    cfg.update(extra_cfg or {})
    comp = _network_comp(
        cfg,
        case="pingpong-sustained",
        params={"duration_ticks": str(duration)},
    )
    manifest = TestPlanManifest.load_file(
        os.path.join(PLANS, "network", "manifest.toml")
    )
    return engine.queue_run(
        comp,
        manifest,
        sources_dir=os.path.join(PLANS, "network"),
        priority=priority,
    )


def _wait_done(engine, tid, budget=240):
    deadline = time.time() + budget
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t.state().state in (State.COMPLETE, State.CANCELED):
            return t
        time.sleep(0.05)
    raise TimeoutError(f"task {tid} not done in {budget}s")


def _stream_rows(engine, tid):
    path = os.path.join(
        engine.env.dirs.outputs(), "network", tid, "sim_timeseries.jsonl"
    )
    with open(path) as f:
        return [
            {k: v for k, v in json.loads(line).items() if k != "run"}
            for line in f
        ]


def _assert_sim_equal(engine, base, other):
    jb = base.result["journal"]["sim"]
    jo = other.result["journal"]["sim"]
    for key in _COMPARE_KEYS:
        assert jo.get(key) == jb.get(key), (key, jo.get(key), jb.get(key))
    assert _stream_rows(engine, other.id) == _stream_rows(engine, base.id)


@pytest.mark.slow  # real sim runs (compile + several hundred ticks each):
# well past the tier-1 ~20s per-test ceiling; CI covers the same
# contracts per-push via `make preempt-smoke`
class TestPreemptBitEquality:
    @pytest.fixture(scope="class")
    def fleet_runs(self, tmp_path_factory):
        """One shared single-worker sim engine: baseline, migrate-solo,
        double-preempt soak, and priority-evict run once; the tests
        assert against the shared results (compile once, pin many)."""
        home = tmp_path_factory.mktemp("tgfleet")
        old = os.environ.get("TESTGROUND_HOME")
        os.environ["TESTGROUND_HOME"] = str(home)
        try:
            engine = _sim_engine(EnvConfig.load())
            engine.start_workers()
            try:
                out = {"engine": engine}
                base_id = _queue_sustained(engine)
                out["base"] = _wait_done(engine, base_id)

                # migrate-solo: preempt while running, auto-resume
                mig_id = _queue_sustained(engine)
                _wait_state(engine, mig_id, State.PROCESSING, timeout=120)
                assert engine.preempt(mig_id)["ok"]
                out["migrated"] = _wait_done(engine, mig_id)

                # soak: preempt the SAME task twice across attempts
                soak_id = _queue_sustained(engine)
                _wait_state(engine, soak_id, State.PROCESSING, timeout=120)
                assert engine.preempt(soak_id)["ok"]
                deadline = time.time() + 120
                second = False
                while time.time() < deadline:
                    t = engine.get_task(soak_id)
                    st = t.state().state
                    if st == State.COMPLETE:
                        break
                    if (
                        st == State.PROCESSING
                        and int(t.trace.get("preemptions", 0)) == 1
                    ):
                        second = engine.preempt(soak_id).get("ok", False)
                        if second:
                            break
                    time.sleep(0.02)
                out["soak_second"] = second
                out["soak"] = _wait_done(engine, soak_id)

                # priority eviction: busy worker, high-priority arrival
                victim_id = _queue_sustained(engine)
                _wait_state(engine, victim_id, State.PROCESSING, timeout=120)
                # eviction triggers only when every worker slot is busy
                # (engine._maybe_evict_for); the busy gauge is stamped
                # at dispatch, a hair after the PROCESSING state
                deadline = time.time() + 30
                while time.time() < deadline:
                    w = engine.fleet_info()["workers"]
                    if w["busy"] >= w["total"]:
                        break
                    time.sleep(0.01)
                hi_id = _queue_sustained(
                    engine,
                    duration=50,
                    priority=5,
                    extra_cfg={"max_ticks": 128, "checkpoint_chunks": 0},
                )
                out["hi"] = _wait_done(engine, hi_id)
                out["victim"] = _wait_done(engine, victim_id)
                yield out
            finally:
                engine.stop()
        finally:
            if old is None:
                os.environ.pop("TESTGROUND_HOME", None)
            else:
                os.environ["TESTGROUND_HOME"] = old

    def test_baseline_succeeds(self, fleet_runs):
        base = fleet_runs["base"]
        assert base.outcome() == Outcome.SUCCESS, base.error
        assert int(base.trace.get("preemptions", 0)) == 0

    def test_migrated_solo_is_bit_equal(self, fleet_runs):
        engine, mig = fleet_runs["engine"], fleet_runs["migrated"]
        assert mig.outcome() == Outcome.SUCCESS, mig.error
        assert int(mig.trace["preemptions"]) == 1
        # the requeue resumed from the task's own snapshots
        resumed = mig.result["journal"]["sim"]["checkpoint"]["resumed"]
        assert resumed["from_run"] == mig.id
        assert resumed["from_tick"] > 0
        _assert_sim_equal(engine, fleet_runs["base"], mig)
        types = [r["type"] for r in _journal_rows(engine, mig.id)]
        for ev in ("task.preempted", "task.migrated"):
            assert ev in types

    def test_double_preempt_soak_is_bit_equal(self, fleet_runs):
        engine, soak = fleet_runs["engine"], fleet_runs["soak"]
        assert soak.outcome() == Outcome.SUCCESS, soak.error
        want = 2 if fleet_runs["soak_second"] else 1
        assert int(soak.trace["preemptions"]) == want
        _assert_sim_equal(engine, fleet_runs["base"], soak)

    def test_eviction_victim_resumes_bit_equal(self, fleet_runs):
        engine = fleet_runs["engine"]
        hi, victim = fleet_runs["hi"], fleet_runs["victim"]
        assert hi.outcome() == Outcome.SUCCESS, hi.error
        assert victim.outcome() == Outcome.SUCCESS, victim.error
        assert int(victim.trace["preemptions"]) >= 1
        _assert_sim_equal(engine, fleet_runs["base"], victim)
        ev = next(
            r
            for r in _journal_rows(engine, victim.id)
            if r["type"] == "task.evicted"
        )
        assert ev["by"] == hi.id and ev["victim_priority"] == 0
        assert engine.fleet_info()["evictions"] == 1


@pytest.mark.slow  # a real packed sim run (bucket warmup + vmapped pack)
class TestPackMemberPreempt:
    def test_preempted_pack_member_reruns_bit_equal(self, tg_home):
        """Evicting one member of a running pack freezes its lane
        (never resumable — packed lanes live on-device, not on disk)
        and requeues it; the deterministic rerun lands on the same
        totals as its identically-configured pack sibling."""
        env = EnvConfig.load()
        plans = env.dirs.plans()
        os.makedirs(plans, exist_ok=True)
        shutil.copytree(
            os.path.join(PLANS, "network"), os.path.join(plans, "network")
        )
        engine = _sim_engine(env)
        try:
            # pack-compatible config: NO checkpointing (checkpoint_chunks
            # > 0 is a pack solo reason — engine/pack.py), identical
            # seed/shape so the two tasks pack into one vmapped run and
            # the rerun's totals are comparable to the sibling's
            cfg = {
                "pack": True,
                "bucket": "auto",
                "bucket_ladder": "32,64",
                "chunk": 16,
                "seed": 5,
                "max_ticks": 1024,
                "telemetry": True,
                "checkpoint_chunks": 0,
            }
            # queue BOTH before starting the single worker so the first
            # claim packs them together (tests/test_sim_pack.py idiom)
            ids = [
                _queue_sustained(engine, duration=800, extra_cfg=cfg)
                for _ in range(2)
            ]
            engine.start_workers()
            for tid in ids:
                _wait_state(engine, tid, State.PROCESSING, timeout=120)
            res = engine.preempt(ids[1])
            assert res["ok"], res
            done = [_wait_done(engine, tid) for tid in ids]
            for t in done:
                assert t.outcome() == Outcome.SUCCESS, (t.id, t.error)
            sibling, member = done
            assert int(member.trace["preemptions"]) == 1
            pre = next(
                r
                for r in _journal_rows(engine, member.id)
                if r["type"] == "task.preempted"
            )
            assert pre["resumable"] is False
            # same seed + same config: the rerun must land on the
            # sibling's exact totals
            js, jm = (
                sibling.result["journal"]["sim"],
                member.result["journal"]["sim"],
            )
            for key in _COMPARE_KEYS:
                assert jm.get(key) == js.get(key), (key, jm, js)
        finally:
            engine.stop()
