"""Run health plane (docs/OBSERVABILITY.md "Run health plane"): SLO
table validation, plan lowering, the per-chunk evaluator's metric math
and windowing, warn-vs-fail behavior, the zero-overhead contract
(program untouched, host-sync count unchanged), and the end-to-end
journal / jsonl / stats / Prometheus surfaces."""

import json
import os
import types

import numpy as np
import pytest

from testground_tpu.config import EnvConfig
from testground_tpu.sim.slo import (
    SLO_FILE,
    SloEvaluator,
    SloBreachError,
    build_slo_plan,
    parse_slo,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def gspec(gid, count):
    """The id/count view the slo plane needs of a GroupSpec."""
    return types.SimpleNamespace(id=gid, count=count)


# ------------------------------------------------------------- validation


class TestParse:
    def test_minimal_rule(self):
        r = parse_slo(
            {"metric": "drop_rate", "op": "<=", "threshold": 0.01}
        )
        assert r.metric == "drop_rate"
        assert r.severity == "warn"  # default
        assert r.window_ticks == 0  # whole run
        assert r.name  # auto-named

    def test_unknown_key_refused(self):
        with pytest.raises(ValueError, match="unknown key"):
            parse_slo(
                {"metric": "drop_rate", "op": "<", "threshold": 1, "oops": 2}
            )

    def test_unknown_metric_refused(self):
        with pytest.raises(ValueError, match="unknown slo metric"):
            parse_slo({"metric": "p99", "op": "<", "threshold": 1})

    def test_unknown_op_refused(self):
        with pytest.raises(ValueError, match="unknown slo op"):
            parse_slo({"metric": "drop_rate", "op": "!=", "threshold": 1})

    def test_threshold_required_and_numeric(self):
        with pytest.raises(ValueError, match="threshold"):
            parse_slo({"metric": "drop_rate", "op": "<"})
        with pytest.raises(ValueError, match="threshold"):
            parse_slo(
                {"metric": "drop_rate", "op": "<", "threshold": "lots"}
            )

    def test_bad_severity_and_window_refused(self):
        with pytest.raises(ValueError, match="severity"):
            parse_slo(
                {
                    "metric": "drop_rate",
                    "op": "<",
                    "threshold": 1,
                    "severity": "panic",
                }
            )
        with pytest.raises(ValueError, match="window_ticks"):
            parse_slo(
                {
                    "metric": "drop_rate",
                    "op": "<",
                    "threshold": 1,
                    "window_ticks": -5,
                }
            )

    def test_group_on_run_global_metric_refused(self):
        """A silently-ignored scope would assert something other than
        what was written — refuse it."""
        with pytest.raises(ValueError, match="group"):
            parse_slo(
                {
                    "metric": "drop_rate",
                    "op": "<",
                    "threshold": 1,
                    "group": "clients",
                }
            )

    def test_group_scoping_latency_only(self):
        """[[groups.run.slo]] declarations default latency metrics to
        their own group (the faults scoping rule); run-global metrics
        refuse a group-level placement — a silently run-global rule
        would assert something other than what the operator wrote."""
        lat = parse_slo(
            {"metric": "latency_p99_ticks", "op": "<", "threshold": 8},
            default_group="clients",
        )
        assert lat.group == "clients"
        with pytest.raises(ValueError, match="global.run.slo"):
            parse_slo(
                {"metric": "drop_rate", "op": "<", "threshold": 0.5},
                default_group="clients",
            )

    def test_window_ticks_must_be_whole_number(self):
        for bad in (512.7, True, "soon"):
            with pytest.raises(ValueError, match="window_ticks"):
                parse_slo(
                    {
                        "metric": "drop_rate",
                        "op": "<",
                        "threshold": 1,
                        "window_ticks": bad,
                    }
                )


class TestBuildPlan:
    def test_nothing_declared_lowers_to_none(self):
        assert build_slo_plan([gspec("a", 4)], {}) is None
        assert build_slo_plan([gspec("a", 4)], {"a": []}) is None

    def test_unknown_group_refused(self):
        with pytest.raises(ValueError, match="unknown group"):
            build_slo_plan(
                [gspec("a", 4)],
                {
                    "": [
                        {
                            "metric": "latency_p99_ticks",
                            "op": "<",
                            "threshold": 8,
                            "group": "ghost",
                        }
                    ]
                },
            )

    def test_duplicate_names_refused(self):
        tbl = {"name": "x", "metric": "drop_rate", "op": "<", "threshold": 1}
        with pytest.raises(ValueError, match="duplicate"):
            build_slo_plan([gspec("a", 4)], {"": [dict(tbl), dict(tbl)]})

    def test_plan_shape(self):
        plan = build_slo_plan(
            [gspec("a", 4)],
            {
                "": [
                    {
                        "metric": "drop_rate",
                        "op": "<",
                        "threshold": 0.1,
                        "window_ticks": 100,
                    }
                ],
                "a": [
                    {
                        "metric": "latency_p95_ticks",
                        "op": "<",
                        "threshold": 8,
                        "severity": "fail",
                    }
                ],
            },
        )
        assert plan.count == 2
        assert plan.has_fail()
        assert plan.max_window_ticks() == 100
        assert "drop_rate" in plan.summary()


# -------------------------------------------------------------- evaluator


def make_eval(rules, groups=None, chunk=16, path=None, cancel=None):
    groups = groups or [gspec("g0", 8)]
    plan = build_slo_plan(groups, {"": [dict(r) for r in rules]})
    return SloEvaluator(
        plan, groups, tick_ms=1.0, chunk=chunk, path=path, cancel=cancel
    )


def rows_for(n, start=0, **counters):
    """n telemetry rows with the given per-tick counter values."""
    return [
        {
            "tick": start + i,
            "delivered": counters.get("delivered", 0),
            "sent": counters.get("sent", 0),
            "dropped": counters.get("dropped", 0),
            "fault_dropped": counters.get("fault_dropped", 0),
            "faults_crashed": counters.get("faults_crashed", 0),
            "faults_restarted": counters.get("faults_restarted", 0),
        }
        for i in range(n)
    ]


class TestEvaluator:
    def test_delivered_per_tick_breach_and_recovery(self):
        ev = make_eval(
            [
                {
                    "name": "rate",
                    "metric": "delivered_per_tick",
                    "op": ">=",
                    "threshold": 2.0,
                    "window_ticks": 16,
                }
            ]
        )
        ev.on_rows(rows_for(16, delivered=1))  # 1/tick < 2 → breach
        b = ev.evaluate()
        assert len(b) == 1 and b[0]["rule"] == "rate"
        assert b[0]["observed"] == pytest.approx(1.0)
        ev.on_rows(rows_for(16, start=16, delivered=4))  # windowed: 4/tick
        assert ev.evaluate() == []
        j = ev.journal()
        assert j["breaches"] == 1
        assert j["rules"][0]["last_observed"] == pytest.approx(4.0)

    def test_windowed_rule_waits_for_a_full_window(self):
        """The Prometheus for-clause rule: a windowed assertion is not
        judged until the run has produced a FULL window of history —
        warmup noise in chunk 1 must not fail a healthy soak."""
        ev = make_eval(
            [
                {
                    "name": "rate",
                    "metric": "delivered_per_tick",
                    "op": ">=",
                    "threshold": 2.0,
                    "window_ticks": 48,
                    "severity": "fail",
                }
            ]
        )
        ev.on_rows(rows_for(16, delivered=1))  # 16 < 48 ticks of history
        assert ev.evaluate() == []
        ev.on_rows(rows_for(16, start=16, delivered=1))
        assert ev.evaluate() == []  # still partial (32 < 48)
        assert ev.fatal is None
        ev.on_rows(rows_for(16, start=32, delivered=1))
        b = ev.evaluate()
        assert len(b) == 1  # full window → judged
        # inclusive clamped tick bounds of the evidence window
        assert b[0]["window"] == [0, 47]

    def test_whole_run_window_is_cumulative(self):
        ev = make_eval(
            [
                {
                    "metric": "delivered_per_tick",
                    "op": ">=",
                    "threshold": 2.0,
                }
            ]
        )
        ev.on_rows(rows_for(16, delivered=1))
        assert len(ev.evaluate()) == 1
        ev.on_rows(rows_for(16, start=16, delivered=4))
        # cumulative mean = (16 + 64)/32 = 2.5 → holds
        assert ev.evaluate() == []

    def test_drop_rate_skips_empty_window(self):
        ev = make_eval(
            [{"metric": "drop_rate", "op": "<", "threshold": 0.1}]
        )
        ev.on_rows(rows_for(8))  # zero sends → no evidence, no breach
        assert ev.evaluate() == []
        ev.on_rows(rows_for(8, start=8, sent=10, dropped=2))
        b = ev.evaluate()
        assert len(b) == 1
        assert b[0]["observed"] == pytest.approx(0.2)

    def test_crashed_fraction_is_state_not_window(self):
        ev = make_eval(
            [
                {
                    "metric": "crashed_fraction",
                    "op": "<",
                    "threshold": 0.2,
                    "window_ticks": 16,
                }
            ]
        )
        rows = rows_for(16)
        rows[5]["faults_crashed"] = 2  # 2/8 = 0.25 crashed
        ev.on_rows(rows)
        assert len(ev.evaluate()) == 1
        # the window moved on but nobody restarted: still crashed
        ev.on_rows(rows_for(16, start=16))
        assert len(ev.evaluate()) == 1
        rows = rows_for(16, start=32)
        rows[0]["faults_restarted"] = 2  # recovery
        ev.on_rows(rows)
        assert ev.evaluate() == []

    def test_latency_percentile_per_group_and_aggregate(self):
        from testground_tpu.sim.telemetry import LATENCY_BINS

        groups = [gspec("a", 4), gspec("b", 4)]
        plan = build_slo_plan(
            groups,
            {
                "a": [
                    {
                        "name": "a-p99",
                        "metric": "latency_p99_ticks",
                        "op": "<",
                        "threshold": 4.0,
                    }
                ],
                "": [
                    {
                        "name": "all-p50",
                        "metric": "latency_p50_ticks",
                        "op": "<",
                        "threshold": 100.0,
                    }
                ],
            },
        )
        ev = SloEvaluator(plan, groups, tick_ms=1.0, chunk=16)
        # group a: everything in bin 3 ([8, 16) ticks) → p99 ≥ 8 breaches
        # the < 4 assertion; group b: bin 0 → aggregate p50 stays low
        hist = np.zeros((2, LATENCY_BINS), np.int64)
        hist[0, 3] = 50
        hist[1, 0] = 50
        ev.on_rows(rows_for(16, delivered=6))
        ev.on_lat_delta(hist)
        breaches = ev.evaluate()
        assert [b["rule"] for b in breaches] == ["a-p99"]
        assert breaches[0]["observed"] >= 8.0
        assert breaches[0]["group"] == "a"

    def test_latency_skips_zero_delivery_window(self):
        ev = make_eval(
            [{"metric": "latency_p99_ticks", "op": "<", "threshold": 1.0}]
        )
        ev.on_rows(rows_for(16))
        assert ev.evaluate() == []  # no deliveries → no evidence

    def test_fail_severity_sets_cancel_and_fatal(self):
        import threading

        cancel = threading.Event()
        ev = make_eval(
            [
                {
                    "name": "warny",
                    "metric": "delivered_per_tick",
                    "op": ">=",
                    "threshold": 100.0,
                },
                {
                    "name": "fatal",
                    "metric": "drop_rate",
                    "op": "<",
                    "threshold": 0.1,
                    "severity": "fail",
                },
            ],
            cancel=cancel,
        )
        ev.on_rows(rows_for(16, delivered=1, sent=10, dropped=5))
        breaches = ev.evaluate()
        assert {b["rule"] for b in breaches} == {"warny", "fatal"}
        assert ev.fatal is not None and ev.fatal["rule"] == "fatal"
        assert cancel.is_set()
        err = SloBreachError(ev.fatal)
        assert "fatal" in str(err) and "drop_rate" in str(err)

    def test_warn_severity_never_cancels(self):
        import threading

        cancel = threading.Event()
        ev = make_eval(
            [
                {
                    "metric": "delivered_per_tick",
                    "op": ">=",
                    "threshold": 100.0,
                }
            ],
            cancel=cancel,
        )
        ev.on_rows(rows_for(16, delivered=1))
        assert len(ev.evaluate()) == 1
        assert ev.fatal is None and not cancel.is_set()

    def test_jsonl_records_conserve_journal_total(self, tmp_path):
        path = str(tmp_path / SLO_FILE)
        ev = make_eval(
            [
                {
                    "metric": "delivered_per_tick",
                    "op": ">=",
                    "threshold": 100.0,
                }
            ],
            path=path,
        )
        for i in range(5):
            ev.on_rows(rows_for(16, start=16 * i, delivered=1))
            ev.evaluate()
        ev.close()
        records = [json.loads(l) for l in open(path)]
        j = ev.journal()
        assert len(records) == ev.records_written == j["breaches"] == 5
        assert j["rules"][0]["breaches"] == 5
        assert j["file"] == SLO_FILE
        for r in records:
            assert r["metric"] == "delivered_per_tick"
            assert r["observed"] == pytest.approx(1.0)


# ------------------------------------------------------------- run cancel


class TestSloRunCancel:
    """The SLO fail path cancels the RUN; everything else holding the
    loop's cancel object (the stall watchdog above all) keeps TASK-level
    semantics — declaring an SLO must not weaken a stall."""

    def test_set_keeps_task_level_semantics(self):
        import threading

        from testground_tpu.sim.executor import _SloRunCancel

        task = threading.Event()
        rc = _SloRunCancel(task)
        rc.set()  # the stall watchdog's call on the loop's cancel
        assert task.is_set() and rc.is_set()

    def test_slo_fail_path_is_run_local(self):
        import threading

        from testground_tpu.sim.executor import _SloRunCancel

        task = threading.Event()
        rc = _SloRunCancel(task)
        rc.run_local.set()  # the evaluator's cancel target
        assert rc.is_set()
        assert not task.is_set()  # later [[runs]] still execute


# ----------------------------------------------------------- zero overhead


class TestZeroOverhead:
    def test_slo_never_reaches_the_program(self):
        """The SLO plane is host-side by contract: the ONE SimProgram
        construction site takes no slo parameter — adding one would be
        a program-shaping change and must re-pin this contract (cohort
        broadcast + BuildKey + jaxpr tests, like telemetry/faults)."""
        import inspect

        from testground_tpu.sim.executor import make_sim_program

        assert "slo" not in inspect.signature(make_sim_program).parameters

    def test_same_program_and_sync_count_with_evaluator_attached(
        self, monkeypatch
    ):
        """Jaxpr-identical and zero extra host syncs: attaching the SLO
        evaluator's callbacks (telemetry rows + latency deltas) to a
        telemetry run changes neither the traced chunk program nor the
        per-chunk done-poll count."""
        import jax

        from testground_tpu.api import RunGroup
        from testground_tpu.sim import engine as engine_mod
        from testground_tpu.sim.engine import SimProgram, build_groups
        from testground_tpu.sim.executor import load_sim_testcases
        from testground_tpu.sim.telemetry import rows_from_blocks

        calls = {"n": 0}
        real = engine_mod._poll_done

        def counting(done):
            calls["n"] += 1
            return real(done)

        monkeypatch.setattr(engine_mod, "_poll_done", counting)

        def build():
            tc = load_sim_testcases(os.path.join(PLANS, "network"))[
                "ping-pong"
            ]()
            return SimProgram(
                tc,
                build_groups(
                    [RunGroup(id="g0", instances=4, parameters={})]
                ),
                chunk=16,
                telemetry=True,
            )

        def run(with_slo):
            calls["n"] = 0
            prog = build()
            jaxpr = str(jax.make_jaxpr(prog._chunk_step)(prog.init_carry()))
            ev = None
            if with_slo:
                ev = make_eval(
                    [
                        {
                            "metric": "delivered_per_tick",
                            "op": ">=",
                            "threshold": 1e9,  # breaches every chunk
                        }
                    ]
                )
                res = prog.run(
                    max_ticks=512,
                    telemetry_cb=lambda b: ev.on_rows(
                        rows_from_blocks([b], ("g0",))
                    ),
                    lat_hist_cb=ev.on_lat_delta,
                    on_chunk=lambda ticks: ev.evaluate(),
                )
            else:
                res = prog.run(max_ticks=512)
            return jaxpr, calls["n"], res["ticks"], ev

        jaxpr_off, syncs_off, ticks_off, _ = run(False)
        jaxpr_on, syncs_on, ticks_on, ev = run(True)
        assert jaxpr_on == jaxpr_off  # program untouched
        assert ticks_on == ticks_off
        assert syncs_on == syncs_off  # ZERO extra host syncs
        assert ev.journal()["breaches"] > 0  # yet every chunk evaluated


# ------------------------------------------------------------- end to end


@pytest.fixture()
def sim_engine(tg_home):
    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.engine import Engine, EngineConfig
    from testground_tpu.sim.runner import SimJaxRunner

    env = EnvConfig.load()
    e = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    e.start_workers()
    yield e
    e.stop()


def run_sim_slo(engine, slo, telemetry=True, plan="network", case="ping-pong"):
    import time

    from testground_tpu.api import (
        Composition,
        Global,
        Group,
        Instances,
        RunParams,
        TestPlanManifest,
        generate_default_run,
    )
    from testground_tpu.engine import State

    comp = Composition(
        global_=Global(
            plan=plan,
            case=case,
            builder="sim:plan",
            runner="sim:jax",
            run_config={"telemetry": telemetry, "chunk": 16},
            run=RunParams(slo=[dict(s) for s in slo]),
        ),
        groups=[Group(id="all", instances=Instances(count=4))],
    )
    comp = generate_default_run(comp)
    manifest = TestPlanManifest.load_file(
        os.path.join(PLANS, plan, "manifest.toml")
    )
    tid = engine.queue_run(
        comp, manifest, sources_dir=os.path.join(PLANS, plan)
    )
    deadline = time.time() + 180
    while time.time() < deadline:
        t = engine.get_task(tid)
        if t is not None and t.state().state in (
            State.COMPLETE,
            State.CANCELED,
        ):
            return t
        time.sleep(0.05)
    raise TimeoutError(f"task {tid} did not finish")


WARN_RULE = {
    "name": "impossible-rate",
    "metric": "delivered_per_tick",
    "op": ">=",
    "threshold": 1e9,  # breaches every evaluated chunk, deterministically
    "severity": "warn",
}


class TestEndToEnd:
    def test_warn_breach_journal_jsonl_stats_prometheus(self, sim_engine):
        from testground_tpu.engine import Outcome
        from testground_tpu.metrics.prometheus import render_prometheus
        from testground_tpu.runners.pretty import render_telemetry_summary

        t = run_sim_slo(sim_engine, [WARN_RULE])
        assert t.outcome() == Outcome.SUCCESS  # warn records, never kills
        slo = t.result["journal"]["slo"]
        rule = slo["rules"][0]
        assert rule["name"] == "impossible-rate"
        assert rule["breaches"] > 0
        assert "error" not in slo
        # jsonl records conserve the journal total
        path = os.path.join(
            sim_engine.env.dirs.outputs(), "network", t.id, SLO_FILE
        )
        records = [json.loads(l) for l in open(path)]
        assert len(records) == slo["breaches"]
        assert all(r["run"] == t.id for r in records)
        # stats payload + table carry the verdict
        payload = t.stats_payload()
        assert payload["slo"]["breaches"] == slo["breaches"]
        table = render_telemetry_summary(payload)
        assert "slo impossible-rate" in table
        assert "breach(es)" in table
        # Prometheus: per-rule series + the scrape gauges
        text = render_prometheus([t], per_task_limit=10)
        assert 'tg_slo_breaches_total{' in text
        assert 'rule="impossible-rate"' in text
        assert "tg_slo_failed{" in text
        assert "tg_scrape_tasks_total 1" in text
        assert "tg_scrape_tasks_elided 0" in text

    def test_fail_breach_cancels_with_typed_error_and_keeps_journal(
        self, sim_engine
    ):
        from testground_tpu.engine import Outcome

        t = run_sim_slo(
            sim_engine, [{**WARN_RULE, "severity": "fail"}]
        )
        assert t.outcome() == Outcome.FAILURE
        err = t.result.get("error", "")
        assert "SLO breach" in err and "impossible-rate" in err
        journal = t.result["journal"]
        assert "SLO breach" in journal["slo"]["error"]
        # the fail-fast run kept its full telemetry record
        assert journal["telemetry"]["rows"] > 0
        assert journal["sim"]["ticks"] > 0
        # canceled at the first breaching chunk boundary: one chunk of
        # 16 ticks, not the full ~3-chunk ping-pong run
        assert journal["sim"]["ticks"] == 16
        # the task-level record is FAILURE (not CANCELED): the SLO
        # cancel is run-local
        assert t.state().state.value == "complete"

    def test_slo_without_telemetry_refuses_loudly(self, sim_engine):
        from testground_tpu.engine import Outcome

        t = run_sim_slo(sim_engine, [WARN_RULE], telemetry=False)
        assert t.outcome() == Outcome.FAILURE
        assert "telemetry" in t.error
        assert "SLO" in t.error

    def test_no_rules_no_journal_block(self, sim_engine):
        from tests.test_sim_runner import run_sim

        t = run_sim(
            sim_engine,
            "network",
            "ping-pong",
            instances=2,
            run_params={"telemetry": True, "chunk": 16},
        )
        assert "slo" not in t.result["journal"]
        run_dir = os.path.join(
            sim_engine.env.dirs.outputs(), "network", t.id
        )
        assert not os.path.exists(os.path.join(run_dir, SLO_FILE))
