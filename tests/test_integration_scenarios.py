"""E2E scenarios mirroring the reference's remaining shell suite
(SURVEY.md §4 tier 4): silent test failure
(``integration_tests/14_docker_silent_test_failure.sh``, issue-1349),
multi-run continue-on-failure with per-run CSV results
(``1493_continue_on_failure.sh``), and mixed builders in one composition
(``15_docker_mixed_builders_configuration.sh``)."""

import csv
import glob
import os
import stat

import pytest

from testground_tpu.builders.exec_bin import ExecBinBuilder
from testground_tpu.builders.exec_py import ExecPyBuilder
from testground_tpu.builders.sim_plan import SimPlanBuilder
from testground_tpu.cli.main import main
from testground_tpu.config import EnvConfig
from testground_tpu.engine import Engine, EngineConfig, Outcome
from testground_tpu.runners.local_exec import LocalExecRunner
from testground_tpu.sim.runner import SimJaxRunner

from tests.test_local_exec import run_plan

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


@pytest.fixture()
def engine(tg_home):
    e = Engine(
        EngineConfig(
            env=EnvConfig.load(),
            builders=[ExecPyBuilder(), ExecBinBuilder(), SimPlanBuilder()],
            runners=[LocalExecRunner(), SimJaxRunner()],
        )
    )
    e.start_workers()
    yield e
    e.stop()


@pytest.mark.slow  # ~50s each (the silent plan runs to its timeout by
# design): past the tier-1 870s budget's ~20s per-test ceiling
class TestSilentFailure:
    def test_silent_instance_fails_the_run(self, engine):
        """An instance that exits without a terminal event — not even a
        failure — must fail the run (issue-1349)."""
        t = run_plan(engine, "placebo", "silent", instances=2)
        assert t.outcome() == Outcome.FAILURE
        # every instance is accounted as not-ok, none crashed the runner
        outcomes = t.result["outcomes"]["all"]
        assert outcomes["ok"] == 0 and outcomes["total"] == 2

    def test_silent_run_exits_nonzero_via_cli(self, tg_home, capsys):
        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        capsys.readouterr()
        rc = main(
            [
                "run", "single", "placebo:silent",
                "--builder", "exec:py", "--runner", "local:exec",
                "-i", "1",
            ]
        )
        assert rc != 0
        assert "outcome: failure" in capsys.readouterr().out


class TestContinueOnFailure:
    COMPOSITION = """
[metadata]
name = "issue-1493-multiple-runs-obvious-failure"

[global]
plan = "placebo"
case = "optional-failure"
builder = "exec:py"
runner = "local:exec"

[[groups]]
id = "group_simple"
[groups.instances]
count = 1

[[runs]]
id = "run_simple_1"
[[runs.groups]]
id = "group_simple"
[runs.groups.instances]
count = 1

[[runs]]
id = "run_simple_2"
[[runs.groups]]
id = "group_simple"
[runs.groups.instances]
count = 2
[runs.groups.test_params]
should_fail = "true"

[[runs]]
id = "run_simple_4"
[[runs.groups]]
id = "group_simple"
[runs.groups.instances]
count = 4
"""

    def test_multi_run_continues_and_reports_per_run(
        self, tg_home, tmp_path, capsys
    ):
        """A failing middle run must not stop later runs; the CLI reports
        each run's outcome and --result-file gets one CSV row per run
        (``assert_runs_outcome_are`` / ``assert_runs_results``)."""
        main(["plan", "import", "--from", os.path.join(PLANS, "placebo")])
        comp_file = tmp_path / "comp.toml"
        comp_file.write_text(self.COMPOSITION)
        results_csv = tmp_path / "results.csv"
        capsys.readouterr()

        rc = main(
            [
                "run", "composition",
                "-f", str(comp_file),
                "--result-file", str(results_csv),
            ]
        )
        out = capsys.readouterr().out
        assert rc != 0  # aggregate outcome is failure
        assert "run run_simple_1: outcome: success" in out
        assert "run run_simple_2: outcome: failure" in out
        assert "run run_simple_4: outcome: success" in out

        with open(results_csv) as f:
            rows = list(csv.reader(f))
        assert rows[0] == ["task_id", "plan_case", "outcome", "error"]
        assert [r[2] for r in rows[1:]] == ["success", "failure", "success"]
        assert [r[0].rsplit("-", 1)[1] for r in rows[1:]] == [
            "run_simple_1", "run_simple_2", "run_simple_4",
        ]

    def test_optional_failure_agrees_across_runners(self, engine):
        """The per-run failure knob behaves identically on real processes
        and in the simulator (cross-runner equivalence)."""
        for builder, runner in (
            ("exec:py", "local:exec"),
            ("sim:plan", "sim:jax"),
        ):
            ok = run_plan(
                engine, "placebo", "optional-failure",
                builder=builder, runner=runner,
            )
            assert ok.outcome() == Outcome.SUCCESS, runner
            bad = run_plan(
                engine, "placebo", "optional-failure",
                params={"should_fail": "true"},
                builder=builder, runner=runner,
            )
            assert bad.outcome() == Outcome.FAILURE, runner


MIXED_MAIN_PY = '''
from testground_tpu.sdk import invoke_map


def ok(runenv):
    runenv.record_message("python edition fine")


if __name__ == "__main__":
    invoke_map({"ok": ok})
'''

# the exec:bin edition reuses the Python entry through the `run` shim —
# what matters is that the TWO groups build through DIFFERENT builders and
# both speak the instance protocol
MIXED_RUN_SH = """#!/bin/sh
exec python3 "$(dirname "$0")/main.py"
"""

MIXED_MANIFEST = """
name = "mixed"

[defaults]
builder = "exec:py"
runner = "local:exec"

[builders."exec:py"]
enabled = true

[builders."exec:bin"]
enabled = true

[runners."local:exec"]
enabled = true

[[testcases]]
name = "ok"
instances = { min = 1, max = 50, default = 1 }
"""

MIXED_COMPOSITION = """
[metadata]
name = "mixed-builders"

[global]
plan = "mixed"
case = "ok"
builder = "exec:py"
runner = "local:exec"

[[groups]]
id = "pythons"
builder = "exec:py"
[groups.instances]
count = 2

[[groups]]
id = "binaries"
builder = "exec:bin"
[groups.instances]
count = 2
"""


class TestMixedBuilders:
    def test_two_builders_one_composition(self, tg_home, tmp_path, capsys):
        """Groups of the same composition built by different builders run
        together in one run (``15_docker_mixed_builders_configuration.sh``:
        docker:go + docker:generic groups side by side)."""
        plan_dir = tmp_path / "mixed"
        plan_dir.mkdir()
        (plan_dir / "main.py").write_text(MIXED_MAIN_PY)
        run_sh = plan_dir / "run"
        run_sh.write_text(MIXED_RUN_SH)
        run_sh.chmod(run_sh.stat().st_mode | stat.S_IXUSR)
        (plan_dir / "manifest.toml").write_text(MIXED_MANIFEST)

        main(["plan", "import", "--from", str(plan_dir)])
        comp_file = tmp_path / "comp.toml"
        comp_file.write_text(MIXED_COMPOSITION)
        capsys.readouterr()

        rc = main(["run", "composition", "-f", str(comp_file)])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "outcome: success" in out


DEP_MAIN_PY = '''
from testground_tpu.sdk import invoke_map


def ok(runenv):
    import fancylib
    if fancylib.VALUE != "overridden":
        return f"expected overridden fancylib, got {fancylib.VALUE!r}"
    runenv.record_message("fancylib override active")


if __name__ == "__main__":
    invoke_map({"ok": ok})
'''

DEP_MANIFEST = """
name = "depplan"

[defaults]
builder = "exec:py"
runner = "local:exec"

[builders."exec:py"]
enabled = true

[runners."local:exec"]
enabled = true

[[testcases]]
name = "ok"
instances = { min = 1, max = 10, default = 1 }
"""


class TestDependencyOverrides:
    """The go.mod-rewrite analog (``20_exec_go_mod_rewrites.sh``,
    ``exec_go.go:94-118``): a composition's build dependency override with
    a local target must be visible to the running instances."""

    def _import_plan(self, tmp_path):
        plan_dir = tmp_path / "depplan"
        plan_dir.mkdir()
        (plan_dir / "main.py").write_text(DEP_MAIN_PY)
        (plan_dir / "manifest.toml").write_text(DEP_MANIFEST)
        main(["plan", "import", "--from", str(plan_dir)])

    def _composition(self, target=""):
        return f"""
[metadata]
name = "dep-override"

[global]
plan = "depplan"
case = "ok"
builder = "exec:py"
runner = "local:exec"

[[groups]]
id = "all"
[groups.instances]
count = 1
[[groups.build.dependencies]]
module = "fancylib"
version = "0.0.1"
{f'target = "{target}"' if target else ""}
"""

    def test_override_target_wins(self, tg_home, tmp_path, capsys):
        self._import_plan(tmp_path)
        override = tmp_path / "override"
        override.mkdir()
        (override / "fancylib.py").write_text('VALUE = "overridden"\n')
        comp = tmp_path / "comp.toml"
        comp.write_text(self._composition(target=str(override)))
        capsys.readouterr()
        rc = main(["run", "composition", "-f", str(comp)])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "outcome: success" in out

    def test_without_target_module_is_missing(self, tg_home, tmp_path, capsys):
        """No override target → the instance can't import fancylib and
        the run fails (proves the PYTHONPATH override is what made the
        positive case pass)."""
        self._import_plan(tmp_path)
        comp = tmp_path / "comp.toml"
        comp.write_text(self._composition())
        capsys.readouterr()
        rc = main(["run", "composition", "-f", str(comp)])
        assert rc != 0
        assert "outcome: failure" in capsys.readouterr().out


BROKEN_BUILD_SH = """#!/bin/sh
echo "this build always fails" >&2
exit 3
"""


class TestAbortOnBrokenBuild:
    """A broken build aborts the whole multi-run task before ANY run
    executes (``1493_abort_on_broken_build.sh``: builds happen up front,
    supervisor.go:495-518)."""

    def test_no_runs_execute_after_build_failure(
        self, tg_home, tmp_path, capsys
    ):
        plan_dir = tmp_path / "broken"
        plan_dir.mkdir()
        (plan_dir / "manifest.toml").write_text(
            'name = "broken"\n\n[defaults]\nbuilder = "exec:bin"\n'
            'runner = "local:exec"\n\n[builders."exec:bin"]\nenabled = true\n'
            '\n[runners."local:exec"]\nenabled = true\n\n[[testcases]]\n'
            'name = "ok"\ninstances = { min = 1, max = 10, default = 1 }\n'
        )
        build_sh = plan_dir / "build.sh"
        build_sh.write_text(BROKEN_BUILD_SH)
        build_sh.chmod(0o755)
        main(["plan", "import", "--from", str(plan_dir)])

        comp = tmp_path / "comp.toml"
        comp.write_text(
            "[metadata]\nname = \"broken-multi\"\n\n"
            "[global]\nplan = \"broken\"\ncase = \"ok\"\n"
            "builder = \"exec:bin\"\nrunner = \"local:exec\"\n\n"
            "[[groups]]\nid = \"g\"\n[groups.instances]\ncount = 1\n\n"
            "[[runs]]\nid = \"r1\"\n[[runs.groups]]\nid = \"g\"\n"
            "[runs.groups.instances]\ncount = 1\n\n"
            "[[runs]]\nid = \"r2\"\n[[runs.groups]]\nid = \"g\"\n"
            "[runs.groups.instances]\ncount = 1\n"
        )
        capsys.readouterr()
        rc = main(["run", "composition", "-f", str(comp)])
        out = capsys.readouterr().out
        assert rc != 0
        assert "outcome: failure" in out
        # the failure is the BUILD's: no per-run results were produced
        assert "run r1:" not in out and "run r2:" not in out
        # and no instance outputs exist for either run: the task dir
        # may carry only the archive-time control-plane trace artifacts
        # (task_spans.jsonl / task_trace.json — written for every
        # archived task, failures included), never run/group outputs
        outputs_root = os.path.join(EnvConfig.load().dirs.outputs(), "broken")
        for task_dir in glob.glob(os.path.join(outputs_root, "*")):
            leftovers = set(os.listdir(task_dir)) - {
                "task_spans.jsonl",
                "task_trace.json",
            }
            assert leftovers == set(), leftovers
