"""Sync-service stress at the local:exec envelope (~300 real processes).

The reference sizes its Redis infra for this tier (maxclients sizing,
``pkg/runner/local_common.go:55,77-104``; local runner envelope 2-300
instances, ``README.md:136-139``). Here BOTH per-run sync backends — the
Python thread-per-connection server and the native C++ event-loop server
— must hold 300 concurrent clients through a full-run pattern:
signal_and_wait barrier at target 300, one publish each, then every
client subscribe-reads all 300 entries. Measured timings land in
PERF.md's sync-envelope table.

Each client is a minimal raw-socket process (json+socket only — no SDK,
no jax) so the test stresses the SERVER, not interpreter startup."""

import os
import subprocess
import sys
import time

import pytest

from testground_tpu.native import build_syncsvc, native_available
from testground_tpu.sync import SyncServiceServer

N = 300

# argv: host port n idx — exits 0 only if barrier+publish+subscribe(n) all
# complete; the deliberately dumb line loop keeps the client beyond
# suspicion when the server misbehaves
CLIENT = r"""
import json, socket, sys
host, port, n, idx = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
sock = socket.create_connection((host, port), timeout=180)
f = sock.makefile("rw", encoding="utf-8")

def send(req):
    f.write(json.dumps(req) + "\n")
    f.flush()

def wait_reply(rid):
    for line in f:
        m = json.loads(line)
        if m.get("id") == rid:
            if "error" in m:
                sys.stderr.write(m["error"] + "\n")
                sys.exit(2)
            return m
    sys.exit(3)

send({"id": 1, "op": "signal_and_wait", "state": "stress:big",
      "target": n, "timeout": 170})
wait_reply(1)
send({"id": 2, "op": "publish", "topic": "stress:t", "payload": idx})
wait_reply(2)
send({"id": 3, "op": "subscribe", "topic": "stress:t"})
got = 0
for line in f:
    m = json.loads(line)
    if m.get("id") == 3 and "entry" in m:
        got += 1
        if got >= n:
            print("OK")
            sys.exit(0)
sys.exit(4)
"""


def _stress(server, label, tmp_path):
    host, port = server.address
    script = tmp_path / "client.py"
    script.write_text(CLIENT)
    env = {
        k: v
        for k, v in os.environ.items()
        # keep accelerator hooks out of 300 child interpreters (the
        # local_exec runner does the same for its instances)
        if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS")
    }
    t0 = time.time()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), host, str(port), str(N), str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for i in range(N)
    ]
    spawn_secs = time.time() - t0
    failures = []
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            failures.append((i, "timeout", err.strip()))
            continue
        if p.returncode != 0 or "OK" not in out:
            failures.append((i, p.returncode, err.strip()))
    total_secs = time.time() - t0
    assert not failures, f"{label}: {len(failures)} failed, first: {failures[:3]}"
    print(
        f"\n{label}: {N} clients barrier+pub+sub({N}) in "
        f"{total_secs:.1f}s (spawn {spawn_secs:.1f}s)"
    )
    return total_secs


@pytest.mark.slow  # ~430s + ~90s: far past the tier-1 870s budget's
# per-test ceiling (~20s, Makefile `test` durations note); runs in the
# full `make test` ladder
class TestSyncEnvelope:
    def test_python_server_holds_300_clients(self, tmp_path):
        server = SyncServiceServer().start()
        try:
            _stress(server, "python server", tmp_path)
        finally:
            server.stop()

    def test_native_server_holds_300_clients(self, tmp_path):
        if not native_available():
            pytest.skip("no C++ toolchain")
        from testground_tpu.native import NativeSyncService

        path = build_syncsvc(str(tmp_path / "bin"))
        server = NativeSyncService(path)
        try:
            _stress(server, "native server", tmp_path)
        finally:
            server.stop()
