"""Differential run analysis (docs/OBSERVABILITY.md "Run diff / bench
sentinel"): the hand-rolled rank test, the noise-aware verdict policy,
RunDiff assembly over sparse/corrupt inputs, the engine/daemon/CLI
surfaces, the ``perf_compare`` adapter pin, and the bench-history
sentinel.

The statistical policy under test is the load-bearing part: two
identically-seeded runs on a ±40% noisy box must NEVER judge
``regressed``/``improved`` (alpha=0.01 AND a ≥10% median shift are both
required), while a genuine slowdown flags with an auditable p-value.
Constants pinned here were cross-checked by hand against the normal
approximation with tie + continuity correction.
"""

import json
import os
import random
import time
import urllib.error
import urllib.request

import pytest

from testground_tpu.analysis import bench_history as bh
from testground_tpu.analysis.diff import (
    DIFF_PLANES,
    build_run_diff,
    judge_samples,
    mann_whitney_u,
    task_snapshot,
    validate_planes,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


class TestMannWhitney:
    def test_known_p_value_5v5_separation(self):
        """Complete separation at n=5 per side: U₁=0 and the corrected
        normal approximation gives p≈0.0122 (hand-checked:
        z=(0.5-12.5)/sqrt(275/12), p=erfc(|z|/√2))."""
        u1, p = mann_whitney_u([1, 2, 3, 4, 5], [6, 7, 8, 9, 10])
        assert u1 == 0.0
        assert p == pytest.approx(0.0121857803, rel=1e-6)

    def test_known_p_value_8v8_separation(self):
        u1, p = mann_whitney_u(list(range(8)), list(range(10, 18)))
        assert u1 == 0.0
        assert p == pytest.approx(0.0009391056, rel=1e-6)

    def test_statistic_symmetry(self):
        """U₁ + U₂ = n₁·n₂ — the defining identity of the statistic."""
        xs, ys = [3, 1, 4, 1, 5, 9, 2, 6], [5, 3, 5, 8, 9, 7]
        u1, p1 = mann_whitney_u(xs, ys)
        u2, p2 = mann_whitney_u(ys, xs)
        assert u1 + u2 == pytest.approx(len(xs) * len(ys))
        assert p1 == pytest.approx(p2)

    def test_identical_samples_not_significant(self):
        _, p = mann_whitney_u([1, 2, 3, 4, 5], [1, 2, 3, 4, 5])
        assert p == 1.0

    def test_degenerate_inputs_never_crash(self):
        assert mann_whitney_u([], [1, 2]) == (0.0, 1.0)
        assert mann_whitney_u([1, 2], []) == (0.0, 1.0)
        # every value tied: zero variance, no evidence of a shift
        assert mann_whitney_u([5, 5, 5], [5, 5, 5])[1] == 1.0


class TestJudgeSamples:
    def test_improved_when_significant_and_shifted(self):
        row = judge_samples(range(100, 108), range(150, 158))
        assert row["verdict"] == "improved"
        assert row["ratio"] == pytest.approx(1.483, abs=1e-3)
        assert row["p_value"] < 0.01
        assert row["n_a"] == row["n_b"] == 8

    def test_regressed_when_significant_and_shifted(self):
        row = judge_samples(range(100, 108), range(50, 58))
        assert row["verdict"] == "regressed"
        assert row["p_value"] < 0.01

    def test_lower_is_better_inverts_direction(self):
        """Wall-seconds semantics: larger B samples = slower = regressed."""
        a = [1.0 + 0.01 * i for i in range(8)]
        b = [2.0 + 0.01 * i for i in range(8)]
        assert (
            judge_samples(a, b, higher_is_better=False)["verdict"]
            == "regressed"
        )
        assert (
            judge_samples(b, a, higher_is_better=False)["verdict"]
            == "improved"
        )

    def test_identical_runs_unchanged(self):
        xs = [100 + (i % 7) for i in range(20)]
        row = judge_samples(xs, list(xs))
        assert row["verdict"] == "unchanged"

    def test_too_few_samples_inconclusive(self):
        row = judge_samples([1, 2], [30, 40])
        assert row["verdict"] == "inconclusive"
        assert "too few samples" in row["reason"]

    def test_shifted_but_not_significant_inconclusive(self):
        """A 25% median shift the rank test cannot confirm (p≈0.14 at
        n=5 with heavy overlap) must stay inconclusive — never a gate."""
        row = judge_samples([80, 90, 100, 110, 120], [95, 105, 125, 135, 145])
        assert row["verdict"] == "inconclusive"
        assert row["p_value"] == pytest.approx(0.1437, abs=1e-3)

    def test_forty_percent_noise_never_flags(self):
        """The acceptance property for the serving box: two sample sets
        drawn around the SAME underlying rate with ±40% uniform noise
        must never judge improved/regressed (fixed seed: deterministic)."""
        r = random.Random(7)
        for _ in range(10):
            a = [100 * (1 + r.uniform(-0.4, 0.4)) for _ in range(30)]
            b = [100 * (1 + r.uniform(-0.4, 0.4)) for _ in range(30)]
            verdict = judge_samples(a, b)["verdict"]
            assert verdict in ("unchanged", "inconclusive"), verdict


class TestPlaneValidation:
    def test_default_is_all_planes(self):
        assert validate_planes(None) == DIFF_PLANES
        assert validate_planes("") == DIFF_PLANES

    def test_subset_and_ordering(self):
        assert validate_planes("perf,counters") == ("perf", "counters")
        assert validate_planes(["latency"]) == ("latency",)

    def test_unknown_plane_raises_naming_known(self):
        with pytest.raises(ValueError, match="counters"):
            validate_planes("counters,bogus")


class TestRunDiffTolerance:
    def test_empty_tasks_build_without_planes(self):
        doc = build_run_diff(task_snapshot({}, []), task_snapshot({}, []))
        assert doc["findings"] == []
        assert doc["verdict"] == "clean"
        for plane in DIFF_PLANES:
            assert "absent" in doc[plane]

    def test_corrupt_blocks_never_raise(self):
        """Journal blocks of the wrong shape (a crashed run, a future
        schema) degrade to absent planes, never a traceback."""
        garbage = {
            "sim": "not-a-dict",
            "telemetry": [1, 2, 3],
            "slo": {"rules": "nope"},
            "composition": 7,
        }
        rows = [{"stream": "perf", "chunk": "NaN"}, "junk", None]
        doc = build_run_diff(
            task_snapshot(garbage, rows), task_snapshot({}, [])
        )
        assert doc["verdict"] in ("clean", "inconclusive")
        assert doc["findings"] == []

    def test_identical_snapshots_exact_equality(self):
        task = {
            "id": "t1",
            "composition": {
                "global": {"plan": "p", "case": "c", "run_config": {"seed": 3}}
            },
            "result": {
                "journal": {
                    "sim": {
                        "ticks": 512,
                        "tick_ms": 100,
                        "processes": 2,
                        "msgs_delivered": 99,
                        "msgs_sent": 100,
                        "msgs_dropped": 1,
                        "latency": {
                            "all": {"count": 99, "p50_ms": 1, "p95_ms": 2}
                        },
                    }
                }
            },
        }
        snap = task_snapshot(task, [])
        doc = build_run_diff(snap, dict(snap))
        assert doc["setup"]["identical"] is True
        assert doc["counters"]["mismatched"] == 0
        assert doc["counters"]["compared"] > 0
        assert doc["latency"]["mismatched"] == 0
        assert doc["findings"] == []

    def test_counter_mismatch_is_a_correctness_finding(self):
        base = {
            "id": "tA",
            "composition": {"global": {"run_config": {"seed": 3}}},
            "result": {
                "journal": {"sim": {"ticks": 512, "msgs_delivered": 99}}
            },
        }
        other = json.loads(json.dumps(base))
        other["id"] = "tB"
        other["result"]["journal"]["sim"]["msgs_delivered"] = 98
        doc = build_run_diff(task_snapshot(base, []), task_snapshot(other, []))
        assert doc["counters"]["mismatched"] == 1
        assert doc["findings"], "flow-total mismatch must be a finding"
        assert doc["findings"][0]["severity"] == "correctness"
        assert doc["verdict"] == "findings"

    def test_different_setup_suppresses_findings(self):
        """Counter deltas between runs of DIFFERENT compositions are
        expected, not correctness findings."""
        base = {
            "composition": {"global": {"run_config": {"seed": 3}}},
            "result": {"journal": {"sim": {"msgs_delivered": 99}}},
        }
        other = {
            "composition": {"global": {"run_config": {"seed": 4}}},
            "result": {"journal": {"sim": {"msgs_delivered": 55}}},
        }
        doc = build_run_diff(task_snapshot(base, []), task_snapshot(other, []))
        assert doc["setup"]["identical"] is False
        assert doc["counters"]["mismatched"] == 1
        assert doc["findings"] == []


class TestPerfCompareAdapter:
    def test_sim_perf_reexports_the_engine(self):
        """Satellite pin: sim.perf's compare surface IS analysis.diff's
        (one comparison codepath — `tg perf --compare` and `tg diff`
        can never drift apart)."""
        from testground_tpu.analysis import diff as adiff
        from testground_tpu.sim import perf as sperf

        assert sperf.perf_compare is adiff.perf_compare
        assert sperf._extract_metrics is adiff.extract_ledger_metrics
        assert sperf.fmt_rate is adiff.fmt_rate
        assert sperf.num is adiff.num


class TestBenchHistory:
    def _row(self, value, ts="2026-01-01T00:00:00+00:00", **over):
        row = {
            "ts": ts,
            "workload": "sustained",
            "instances": 512,
            "transport": "xla",
            "metric": "sim_peer_ticks_per_sec",
            "value": value,
            "fingerprint": {"backend": "cpu", "device_kind": "cpu"},
        }
        row.update(over)
        return row

    def test_bank_and_load_round_trip(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        bh.bank_row(path, self._row(100.0))
        bh.bank_row(path, self._row(110.0))
        with open(path, "a") as f:
            f.write("{corrupt\n")  # a crashed bench half-line
        rows = bh.load_history(path)
        assert [r["value"] for r in rows] == [100.0, 110.0]

    def test_sentinel_verdicts(self):
        rows = [self._row(100.0), self._row(104.0), self._row(101.0)]
        report = bh.sentinel_report(rows)
        assert report["regressions"] == 0
        (key,) = report["keys"]
        assert key["verdict"] == "ok"
        assert key["baseline"] == pytest.approx(102.0)  # median of priors

    def test_sentinel_flags_confident_regression_only(self):
        base = [self._row(100.0), self._row(100.0)]
        # 30% slower: within the generous 2.5x bound — journaled only
        within = bh.sentinel_report(base + [self._row(70.0)])
        assert within["regressions"] == 0
        assert within["keys"][0]["verdict"] == "inconclusive"
        # 3x slower: no plausible noise explains it — gate
        beyond = bh.sentinel_report(base + [self._row(33.0)])
        assert beyond["regressions"] == 1
        assert beyond["keys"][0]["verdict"] == "regressed"

    def test_first_row_per_key_inconclusive(self):
        report = bh.sentinel_report([self._row(100.0)])
        assert report["regressions"] == 0
        assert report["inconclusive"] == 1

    def test_keys_do_not_cross_hardware(self):
        tpu = self._row(
            500.0, fingerprint={"backend": "tpu", "device_kind": "TPU v4"}
        )
        report = bh.sentinel_report([self._row(100.0), tpu])
        assert len(report["keys"]) == 2
        assert report["regressions"] == 0

    def test_committed_history_parses_and_passes(self):
        """The checked-in bank (the smoke's baseline) must always load
        and hold no regression verdicts at HEAD."""
        rows = bh.load_history(os.path.join(REPO_ROOT, bh.HISTORY_FILE))
        assert rows, "committed BENCH_HISTORY.jsonl is empty/unreadable"
        assert bh.sentinel_report(rows)["regressions"] == 0


class TestDaemonRouteErrors:
    """The /diff route's error surface needs no finished runs, so these
    stay fast (daemon startup only)."""

    @pytest.fixture()
    def daemon(self, tg_home):
        from testground_tpu.config import EnvConfig
        from testground_tpu.daemon import Daemon

        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        yield d
        d.stop()

    def _get_code(self, url):
        try:
            urllib.request.urlopen(url, timeout=30)
            return 200
        except urllib.error.HTTPError as e:
            return e.code

    def test_missing_params_400(self, daemon):
        assert self._get_code(daemon.address + "/diff") == 400
        assert self._get_code(daemon.address + "/diff?a=x") == 400

    def test_unknown_task_404(self, daemon):
        assert self._get_code(daemon.address + "/diff?a=ghost&b=ghost2") == 404

    def test_unknown_plane_400(self, daemon):
        assert (
            self._get_code(daemon.address + "/diff?a=x&b=y&planes=bogus")
            == 400
        )

    def test_auth_required_when_configured(self, tg_home):
        from testground_tpu.client import Client, DaemonError
        from testground_tpu.config import EnvConfig
        from testground_tpu.daemon import Daemon

        env = EnvConfig.load()
        env.daemon.tokens = ["sekrit"]
        d = Daemon(env=env, listen="localhost:0")
        d.start()
        try:
            with pytest.raises(DaemonError, match="unauthorized"):
                Client(d.address).diff("a", "b")
            # with the token the request reaches the handler (404: no
            # such tasks — proving auth, not routing, was the barrier)
            with pytest.raises(DaemonError, match="unknown task"):
                Client(d.address, token="sekrit").diff("a", "b")
        finally:
            d.stop()


@pytest.mark.slow  # two real daemon-served sim runs (compile + 512 ticks
# each) feed every e2e assertion; well past the non-slow ~5s ceiling
class TestEndToEnd:
    @pytest.fixture(scope="class")
    def daemon(self, tmp_path_factory):
        home = tmp_path_factory.mktemp("tg-home")
        old = os.environ.get("TESTGROUND_HOME")
        os.environ["TESTGROUND_HOME"] = str(home)
        from testground_tpu.config import EnvConfig
        from testground_tpu.daemon import Daemon

        d = Daemon(env=EnvConfig.load(), listen="localhost:0")
        d.start()
        yield d
        d.stop()
        if old is None:
            os.environ.pop("TESTGROUND_HOME", None)
        else:
            os.environ["TESTGROUND_HOME"] = old

    def _run(self, daemon, extra=None):
        from testground_tpu.client import Client

        client = Client(daemon.address)
        client.import_plan(os.path.join(PLANS, "network"))
        cfg = {"telemetry": True, "chunk": 16, "max_ticks": 512}
        cfg.update(extra or {})
        tid = client.run(
            {
                "global": {
                    "plan": "network",
                    "case": "ping-pong",
                    "builder": "sim:plan",
                    "runner": "sim:jax",
                    "run_config": cfg,
                },
                "groups": [
                    {"id": "ping", "instances": {"count": 1}},
                    {"id": "pong", "instances": {"count": 1}},
                ],
            }
        )
        deadline = time.time() + 180
        while time.time() < deadline:
            t = client.status(tid)
            if t["states"][-1]["state"] in ("complete", "canceled"):
                assert t["outcome"] == "success"
                return tid
            time.sleep(0.2)
        raise TimeoutError(tid)

    @pytest.fixture(scope="class")
    def pair(self, daemon):
        # warmup: the first in-process run pays cold-compile and
        # first-touch costs that would otherwise shift the A/B medians
        self._run(daemon)
        return self._run(daemon), self._run(daemon)

    def test_identically_seeded_runs_diff_exactly(self, daemon, pair):
        """The headline acceptance: same composition, same seed ⇒ every
        deterministic counter equal, zero findings."""
        from testground_tpu.client import Client

        doc = Client(daemon.address).diff(*pair)
        assert doc["setup"]["identical"] is True
        assert doc["counters"]["mismatched"] == 0
        assert doc["counters"]["compared"] >= 15
        assert doc["latency"]["mismatched"] == 0
        assert doc["findings"] == []
        for row in doc["perf"].get("metrics", []):
            assert row["verdict"] in ("unchanged", "inconclusive"), row

    def test_planes_param_narrows_document(self, daemon, pair):
        from testground_tpu.client import Client

        doc = Client(daemon.address).diff(*pair, planes="counters")
        assert doc["planes"] == ["counters"]
        assert "perf" not in doc

    def test_cli_diff_renders_and_exits_clean(self, daemon, pair, capsys):
        from testground_tpu.cli.main import main

        rc = main(["--endpoint", daemon.address, "diff", *pair])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "exact equality" in out
        assert "verdict" in out
        assert "MISMATCH" not in out

    def test_cli_diff_json_contract(self, daemon, pair, capsys):
        from testground_tpu.cli.main import main

        rc = main(["--endpoint", daemon.address, "diff", *pair, "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["counters"]["mismatched"] == 0

    def test_cli_unknown_plane_exits_2(self, daemon, pair, capsys):
        from testground_tpu.cli.main import main

        rc = main(
            [
                "--endpoint",
                daemon.address,
                "diff",
                *pair,
                "--planes",
                "bogus",
            ]
        )
        assert rc == 2
        assert "bogus" in capsys.readouterr().err

    def test_slowed_run_flags_regressed(self, daemon, pair):
        """debug_chunk_sleep_ms inflates every chunk wall inside the
        timed window: the rank test must flag it with p far below
        alpha, and the rollup verdict must say so."""
        from testground_tpu.client import Client

        slow = self._run(daemon, {"debug_chunk_sleep_ms": 25})
        doc = Client(daemon.address).diff(pair[0], slow)
        rows = {r["metric"]: r for r in doc["perf"]["metrics"]}
        assert rows["chunk_ticks_per_sec"]["verdict"] == "regressed"
        assert rows["chunk_ticks_per_sec"]["p_value"] < 0.01
        assert doc["verdict"] == "regressed"
        # a debug knob is not a correctness delta: no findings
        assert doc["findings"] == []
