"""EnvConfig / directories / coalescing tests (``pkg/config``)."""

from dataclasses import dataclass, field

from testground_tpu.config import CoalescedConfig, EnvConfig


def test_defaults_applied(tg_home):
    e = EnvConfig.load()
    assert e.daemon.listen == "localhost:8042"
    assert e.daemon.scheduler.workers == 2
    assert e.daemon.scheduler.queue_size == 100
    assert e.daemon.scheduler.task_repo_type == "memory"
    # empty endpoint = in-process engine (the CLI's documented default);
    # the reference forces localhost:8042 only because it has no in-process
    # mode (loader.go:55-63)
    assert e.client.endpoint == ""


def test_directory_layout_created(tg_home):
    e = EnvConfig.load()
    assert e.dirs.home == str(tg_home)
    for d in e.dirs.all():
        import os

        assert os.path.isdir(d)
    assert e.dirs.outputs().endswith("data/outputs")
    assert e.dirs.work().endswith("data/work")


def test_env_toml_overrides(tg_home):
    (tg_home / ".env.toml").write_text(
        """
[daemon]
listen = ":9999"

[daemon.scheduler]
workers = 5
task_repo_type = "disk"

[client]
endpoint = "http://somewhere:9999"
user = "me"

[runners."local:exec"]
disabled = true

[runners."sim:jax"]
default_dt_ms = 5
"""
    )
    e = EnvConfig.load()
    assert e.daemon.listen == ":9999"
    assert e.daemon.scheduler.workers == 5
    assert e.daemon.scheduler.task_repo_type == "disk"
    assert e.daemon.scheduler.queue_size == 100  # default survives
    assert e.client.user == "me"
    assert e.runner_is_disabled("local:exec")
    assert not e.runner_is_disabled("sim:jax")
    assert e.runners["sim:jax"]["default_dt_ms"] == 5


def test_coalesced_config():
    @dataclass
    class RunnerCfg:
        workers: int = 1
        name: str = ""
        extras: list = field(default_factory=list)

    c = (
        CoalescedConfig({"workers": 2, "unknown_key": True})
        .append({"name": "a"})
        .append({"name": "b"})
        .append(None)
    )
    cfg = c.coalesce_into(RunnerCfg)
    assert cfg.workers == 2
    assert cfg.name == "b"  # later layers win
    assert cfg.extras == []


def test_coalesced_config_nested_dataclass():
    from testground_tpu.config import DaemonConfig

    cfg = CoalescedConfig({"listen": ":1", "scheduler": {"workers": 5}}).coalesce_into(
        DaemonConfig
    )
    assert cfg.listen == ":1"
    assert cfg.scheduler.workers == 5  # nested dict became SchedulerConfig
