"""Run packing (PERF.md "Serving: buckets + packing"): many compatible
runs batched into one vmapped device program.

Contracts pinned here:

1. **Per-member bit-equality**: every member of a pack — different
   seeds, different live sizes within one bucket — produces results,
   telemetry streams, and latency histograms bit-identical to an
   isolated run of the same (seed, size).
2. **Straggler rule**: a member finishing early freezes (the vmapped
   cond no-ops its lanes) and reports its OWN finish tick while the
   pack continues; a canceled member snapshots at its boundary.
3. **Admission**: the pack signature packs only what may share a
   program (same plan/case/params/counts-or-bucket/gates; seeds free),
   and refuses faults/trace/multi-runs/non-packed tasks; the queue
   claim respects priority order and marks tasks processing.
4. **Engine end-to-end**: queued pack-opted tasks execute as one pack
   through the real worker loop, each with its own journal carrying
   ``sim.pack``; an SLO-failing member fails ALONE.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from testground_tpu.api import RunGroup
from testground_tpu.sim.api import RUNNING, SUCCESS, SimTestcase
from testground_tpu.sim.buckets import plan_buckets
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import (
    instantiate_testcase,
    load_sim_testcases,
)
from testground_tpu.sim.pack import (
    PackMember,
    PackRunner,
    pack_width,
)

LADDER = (32, 64)
PP_PARAMS = {"latency_ms": "4", "latency2_ms": "2", "tolerance_ms": "15"}


def _pingpong(n_padded, live=None, telemetry=True, chunk=8):
    factory = load_sim_testcases("plans/network")["ping-pong"]
    groups = build_groups(
        [RunGroup(id="all", instances=n_padded, parameters=PP_PARAMS)]
    )
    tc = instantiate_testcase(factory, groups, tick_ms=1.0)
    return SimProgram(
        tc,
        groups,
        test_plan="network",
        test_case="ping-pong",
        tick_ms=1.0,
        chunk=chunk,
        telemetry=telemetry,
        live_counts=live,
    )


class _SeedClock(SimTestcase):
    """Finish tick depends on the per-instance PRNG key — members of a
    pack then finish at genuinely different chunks (the straggler
    case). Every instance of a run draws the same bound from the run's
    seed chain, so a run completes as a unit."""

    SHAPING = ("latency",)
    MSG_WIDTH = 1
    OUT_MSGS = 1
    IN_MSGS = 2
    MAX_LINK_TICKS = 4

    def init(self, env):
        until = 8 + jax.random.randint(env.key, (), 0, 40)
        return {"until": until.astype(jnp.int32)}

    def step(self, env, state, inbox, sync, t):
        return self.out(
            state,
            status=jnp.where(t >= state["until"], SUCCESS, RUNNING),
        )


class TestPackWidth:
    def test_pack_width(self):
        assert pack_width(2, 8) == 2
        assert pack_width(3, 8) == 4
        assert pack_width(5, 8) == 8
        assert pack_width(8, 8) == 8
        assert pack_width(1, 8) == 2  # a pack is ≥ 2 by construction
        assert pack_width(9, 8) == 9  # never below the member count


class TestPackBitEquality:
    def test_bucketed_members_bit_equal_isolated(self):
        """Three members, three live sizes, three seeds, one width-4
        program: each bit-equals its isolated run — results, telemetry
        stream, latency histograms."""
        sizes, seeds = (6, 8, 12), (0, 7, 42)
        bps = [plan_buckets([n], "auto", LADDER) for n in sizes]
        prog = _pingpong(32, live=bps[0].live_counts)
        runner = PackRunner(prog, pack_width(3, 8))
        tele = [[] for _ in sizes]
        members = [
            PackMember(
                seed=s,
                live_counts=bp.live_counts,
                max_ticks=512,
                telemetry_cb=(
                    lambda b, i=i: tele[i].append(np.asarray(b).copy())
                ),
            )
            for i, (s, bp) in enumerate(zip(seeds, bps))
        ]
        packed = runner.run(members)
        for i, (n, s, bp) in enumerate(zip(sizes, seeds, bps)):
            iso_blocks = []
            iso = _pingpong(32, live=bp.live_counts).run(
                seed=s,
                max_ticks=512,
                telemetry_cb=lambda b: iso_blocks.append(
                    np.asarray(b).copy()
                ),
            )
            assert int((np.asarray(iso["status"]) == 1).sum()) == n
            for key in (
                "status",
                "finished_at",
                "ticks",
                "sync_counts",
                "msgs_delivered",
                "msgs_sent",
                "msgs_enqueued",
                "msgs_dropped",
                "msgs_rejected",
                "cal_depth",
            ):
                assert np.array_equal(
                    np.asarray(iso[key]), np.asarray(packed[i][key])
                ), f"member {i} {key} diverged"
            for a, b in zip(
                jax.tree.leaves(iso["states"]),
                jax.tree.leaves(packed[i]["states"]),
            ):
                assert np.array_equal(np.asarray(a), np.asarray(b))
            assert np.array_equal(
                np.concatenate(iso_blocks), np.concatenate(tele[i])
            ), f"member {i} telemetry stream diverged"
            assert np.array_equal(
                np.asarray(iso["lat_hist"]),
                np.asarray(packed[i]["lat_hist"]),
            )

    def test_unbucketed_members_bit_equal_isolated(self):
        prog = _pingpong(8, telemetry=False)
        runner = PackRunner(prog, 2)
        packed = runner.run(
            [
                PackMember(seed=1, max_ticks=512),
                PackMember(seed=2, max_ticks=512),
            ]
        )
        for i, seed in enumerate((1, 2)):
            iso = _pingpong(8, telemetry=False).run(
                seed=seed, max_ticks=512
            )
            assert np.array_equal(
                np.asarray(iso["status"]), np.asarray(packed[i]["status"])
            )
            assert iso["msgs_delivered"] == packed[i]["msgs_delivered"]
            assert iso["ticks"] == packed[i]["ticks"]


class TestStragglersAndCancel:
    def _clock_prog(self):
        groups = build_groups(
            [RunGroup(id="all", instances=6, parameters={})]
        )
        return SimProgram(
            _SeedClock(),
            groups,
            test_plan="t",
            test_case="clock",
            tick_ms=1.0,
            chunk=8,
        )

    def test_early_finisher_freezes_and_reports_own_tick(self):
        """Members whose seeds finish at different chunks: each reports
        its OWN finish tick and its isolated results — the early
        finisher's lanes no-op while the pack runs on."""
        seeds = (3, 11, 29, 5)
        prog = self._clock_prog()
        runner = PackRunner(prog, pack_width(len(seeds), 8))
        packed = runner.run(
            [PackMember(seed=s, max_ticks=512) for s in seeds]
        )
        ticks = set()
        for i, s in enumerate(seeds):
            iso = self._clock_prog().run(seed=s, max_ticks=512)
            assert iso["ticks"] == packed[i]["ticks"], f"member {i}"
            assert np.array_equal(
                np.asarray(iso["finished_at"]),
                np.asarray(packed[i]["finished_at"]),
            )
            ticks.add(iso["ticks"])
        # the case only exercises stragglers if durations truly differ
        assert len(ticks) > 1, f"seed clock degenerate: {ticks}"

    def test_member_cancel_snapshots_at_boundary(self):
        """A canceled member's results freeze at the chunk boundary it
        stopped at (its device lanes keep ticking); the other member
        completes bit-equal to an isolated run."""
        prog = _pingpong(8, telemetry=False)
        runner = PackRunner(prog, 2)
        stop = {"flag": False}
        seen = []

        def on_chunk(ticks):
            seen.append(ticks)
            stop["flag"] = True  # cancel after the first chunk

        packed = runner.run(
            [
                PackMember(
                    seed=1,
                    max_ticks=512,
                    on_chunk=on_chunk,
                    cancel_check=lambda: stop["flag"],
                ),
                PackMember(seed=2, max_ticks=512),
            ]
        )
        # member 0 stopped at the first boundary: RUNNING instances
        # remain (ping-pong needs ≥ latency ticks), tick = chunk
        assert packed[0]["ticks"] == prog.chunk
        iso = _pingpong(8, telemetry=False).run(seed=2, max_ticks=512)
        assert np.array_equal(
            np.asarray(iso["status"]), np.asarray(packed[1]["status"])
        )
        assert iso["ticks"] == packed[1]["ticks"]

    def test_runner_refuses_unpackable_programs(self):
        from testground_tpu.sim.faults import build_fault_schedule

        groups = build_groups(
            [RunGroup(id="all", instances=4, parameters={})]
        )
        faults = build_fault_schedule(
            groups, {"all": [{"kind": "crash", "start_ms": 1.0}]}, 1.0
        )
        prog = SimProgram(
            _SeedClock(),
            groups,
            test_plan="t",
            test_case="c",
            faults=faults,
        )
        with pytest.raises(ValueError, match="fault-free"):
            PackRunner(prog, 2)


# ---------------------------------------------------------------- admission


def _run_task(run_config, n=5, plan="network", case="ping-pong", typ=None):
    from testground_tpu.api import (
        Composition,
        Global,
        Group,
        Instances,
        generate_default_run,
    )
    from testground_tpu.engine.task import (
        DatedState,
        State,
        Task,
        TaskType,
    )

    comp = generate_default_run(
        Composition(
            global_=Global(
                plan=plan,
                case=case,
                builder="sim:plan",
                runner="sim:jax",
                run_config=dict(run_config),
            ),
            groups=[Group(id="all", instances=Instances(count=n))],
        )
    )
    return Task(
        id=f"tk-{time.monotonic_ns()}",
        type=typ or TaskType.RUN,
        plan=plan,
        case=case,
        runner="sim:jax",
        composition=comp.to_dict(),
        input={"manifest": {}, "sources_dir": "/plans/network"},
        states=[DatedState(state=State.SCHEDULED, created=time.time())],
    )


PACK_CFG = {
    "pack": True,
    "bucket": "auto",
    "bucket_ladder": "32,64",
    "telemetry": True,
    "max_ticks": 512,
}


class TestPackSignature:
    def test_same_bucket_different_sizes_and_seeds_pack(self):
        from testground_tpu.engine.pack import pack_signature

        a = pack_signature(_run_task({**PACK_CFG, "seed": 1}, n=5))
        b = pack_signature(_run_task({**PACK_CFG, "seed": 9}, n=29))
        assert a is not None and a == b

    def test_unbucketed_requires_equal_counts(self):
        from testground_tpu.engine.pack import pack_signature

        cfg = {k: v for k, v in PACK_CFG.items() if k != "bucket"}
        assert pack_signature(_run_task(cfg, n=5)) == pack_signature(
            _run_task(cfg, n=5)
        )
        assert pack_signature(_run_task(cfg, n=5)) != pack_signature(
            _run_task(cfg, n=6)
        )

    def test_refusals(self):
        from testground_tpu.engine.pack import pack_signature
        from testground_tpu.engine.task import TaskType

        # not opted in
        assert pack_signature(_run_task({"bucket": "auto"})) is None
        # program-shaping exclusions
        for bad in (
            {"coordinator_address": "h:1"},
            {"resume_from": "t1"},
            {"checkpoint_chunks": 2},
            {"profile": True},
            {"additional_hosts": ["echo"]},
        ):
            assert (
                pack_signature(_run_task({**PACK_CFG, **bad})) is None
            ), bad
        # builds never pack
        assert (
            pack_signature(
                _run_task(PACK_CFG, typ=TaskType.BUILD)
            )
            is None
        )
        # declared faults run solo
        t = _run_task(PACK_CFG)
        t.composition["runs"][0]["groups"][0]["faults"] = [
            {"kind": "crash", "start_ms": 1.0}
        ]
        assert pack_signature(t) is None
        # ...including BACKING-group [groups.run.faults], which only
        # merge into the run groups at prepare time (pre-preparation
        # admission must still see them)
        t = _run_task(PACK_CFG)
        t.composition["groups"][0]["run"]["faults"] = [
            {"kind": "crash", "start_ms": 1.0}
        ]
        assert pack_signature(t) is None
        # backing-group run params key the signature too (they merge
        # into the effective params at prepare time)
        a = _run_task(PACK_CFG)
        b = _run_task(PACK_CFG)
        b.composition["groups"][0]["run"]["test_params"] = {
            "latency_ms": "9"
        }
        assert pack_signature(a) != pack_signature(b)
        # different gates split packs
        assert pack_signature(
            _run_task({**PACK_CFG, "transport": "pallas"})
        ) != pack_signature(_run_task(PACK_CFG))
        assert pack_signature(
            _run_task({**PACK_CFG, "max_ticks": 2048})
        ) != pack_signature(_run_task(PACK_CFG))


class TestQueueClaim:
    def test_claim_matching_pops_in_priority_order(self, tg_home):
        from testground_tpu.engine.queue import TaskQueue
        from testground_tpu.engine.storage import TaskStorage
        from testground_tpu.engine.task import State

        q = TaskQueue(TaskStorage(":memory:"), 16)
        lo = _run_task({**PACK_CFG, "seed": 1})
        hi = _run_task({**PACK_CFG, "seed": 2})
        hi.priority = 5
        other = _run_task({**PACK_CFG, "seed": 3}, case="traffic-shaped")
        for t in (lo, hi, other):
            q.push(t)
        from testground_tpu.engine.pack import pack_signature

        sig = pack_signature(lo)
        claimed = q.claim_matching(
            lambda t: pack_signature(t) == sig, limit=8
        )
        # hi priority first, then lo; 'other' (different case) stays
        assert [t.id for t in claimed] == [hi.id, lo.id]
        assert all(
            t.state().state == State.PROCESSING for t in claimed
        )
        assert len(q) == 1
        assert q.pop().id == other.id

    def test_claim_matching_respects_limit(self, tg_home):
        from testground_tpu.engine.queue import TaskQueue
        from testground_tpu.engine.storage import TaskStorage

        q = TaskQueue(TaskStorage(":memory:"), 16)
        tasks = [_run_task({**PACK_CFG, "seed": i}) for i in range(4)]
        for t in tasks:
            q.push(t)
        claimed = q.claim_matching(lambda t: True, limit=2)
        assert len(claimed) == 2
        assert len(q) == 2


# ------------------------------------------------------------- engine e2e


@pytest.fixture()
def pack_engine(tg_home):
    import os
    import shutil

    from testground_tpu.builders.sim_plan import SimPlanBuilder
    from testground_tpu.config import EnvConfig
    from testground_tpu.engine import Engine, EngineConfig
    from testground_tpu.sim.runner import SimJaxRunner

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = EnvConfig.load()
    plans = env.dirs.plans()
    os.makedirs(plans, exist_ok=True)
    if not os.path.isdir(os.path.join(plans, "network")):
        shutil.copytree(
            os.path.join(repo, "plans", "network"),
            os.path.join(plans, "network"),
        )
    e = Engine(
        EngineConfig(
            env=env, builders=[SimPlanBuilder()], runners=[SimJaxRunner()]
        )
    )
    # one worker so the claim is deterministic: queue first, start after
    e.env.daemon.scheduler.workers = 1
    yield e
    e.stop()


def _queue_pack_run(engine, n, seed, extra_cfg=None, slo=None):
    import os

    from testground_tpu.api import (
        Composition,
        Global,
        Group,
        Instances,
        RunParams,
        TestPlanManifest,
        generate_default_run,
    )

    comp = generate_default_run(
        Composition(
            global_=Global(
                plan="network",
                case="ping-pong",
                builder="sim:plan",
                runner="sim:jax",
                run_config={
                    **PACK_CFG,
                    "seed": seed,
                    "chunk": 16,
                    **(extra_cfg or {}),
                },
            ),
            groups=[Group(id="all", instances=Instances(count=n))],
        )
    )
    if slo is not None:
        comp.global_.run = comp.global_.run or RunParams()
        comp.global_.run.slo = slo
    plans = engine.env.dirs.plans()
    manifest = TestPlanManifest.load_file(
        os.path.join(plans, "network", "manifest.toml")
    )
    return engine.queue_run(
        comp, manifest, sources_dir=os.path.join(plans, "network")
    )


def _wait_all(engine, tids, budget=240):
    from testground_tpu.engine import State

    deadline = time.time() + budget
    while time.time() < deadline:
        if all(
            engine.get_task(t).state().state
            in (State.COMPLETE, State.CANCELED)
            for t in tids
        ):
            return [engine.get_task(t) for t in tids]
        time.sleep(0.2)
    raise TimeoutError(f"tasks not done in {budget}s")


class TestEnginePackE2E:
    def test_queued_runs_execute_as_one_pack(self, pack_engine):
        from testground_tpu.engine.task import Outcome

        sizes = (5, 9, 13)
        tids = [
            _queue_pack_run(pack_engine, n, i)
            for i, n in enumerate(sizes)
        ]
        pack_engine.start_workers()
        tasks = _wait_all(pack_engine, tids)
        for tsk, n in zip(tasks, sizes):
            assert tsk.outcome() == Outcome.SUCCESS, tsk.error
            sim = (tsk.result.get("journal") or {}).get("sim") or {}
            pack = sim.get("pack") or {}
            assert pack.get("members") == len(sizes)
            assert pack.get("width") == 4
            events = (tsk.result["journal"].get("events") or {}).get(
                "all"
            ) or {}
            assert events.get("success") == n, (n, events)
            # perf rows normalize by the exact live count
            perf = sim.get("perf") or {}
            assert perf.get("instances") == n
            assert perf.get("bucket") == 32

    def test_slo_fail_member_fails_alone(self, pack_engine):
        from testground_tpu.engine.task import Outcome

        bad = _queue_pack_run(
            pack_engine,
            5,
            0,
            slo=[
                {
                    "name": "impossible",
                    "metric": "delivered_per_tick",
                    "op": ">",
                    "threshold": 1e9,
                    "severity": "fail",
                }
            ],
        )
        good = _queue_pack_run(pack_engine, 9, 1)
        pack_engine.start_workers()
        tasks = _wait_all(pack_engine, [bad, good])
        sims = [
            ((t.result or {}).get("journal") or {}).get("sim") or {}
            for t in tasks
        ]
        # both rode one pack...
        assert all((s.get("pack") or {}).get("members") == 2 for s in sims)
        # ...but only the SLO-failing member failed
        assert tasks[0].outcome() == Outcome.FAILURE
        assert "impossible" in (tasks[0].error or "") or (
            (tasks[0].result.get("journal") or {}).get("slo") or {}
        ).get("error")
        assert tasks[1].outcome() == Outcome.SUCCESS, tasks[1].error
