"""Network-topology observability plane (docs/OBSERVABILITY.md "Traffic
matrix"): the per-group [NM_CHANNELS, GH, GH] traffic matrix accumulated
inside the jitted tick's carry and flushed once per chunk.

Pins, mirroring the telemetry plane's acceptance style:

1. **Exact conservation** — Σ matrix cells per channel equals the run's
   cumulative flow totals, cell-wise send identity included, on BOTH
   transports (xla and the pallas interpret gate) and with a hosts row.
2. **Zero overhead** — the plane off leaves the chunk jaxpr untouched
   and the plane on adds no blocking device→host sync beyond the
   one done-flag poll per chunk the loop already pays.
3. **Chaos bit-equality** — enabling the matrix perturbs NOTHING: the
   flow totals and statuses of a faulted run are identical on/off, and
   crash purges land in the fault_dropped channel at the right cells.
4. **Bucketed demux** — a padded (bucketed) run reports the exact-N
   matrix bit for bit.
5. **The cut advisor** — exhaustive optimality on small G, greedy
   cluster recovery on large G, the balance cap, canonical numbering.
6. **Bounded cardinality** — the Prometheus page exports top-K pairs
   plus one elision gauge, never raw G².
"""

import json
import os
import threading

import jax
import numpy as np
import pytest

from testground_tpu.api import RunGroup
from testground_tpu.sim import engine as engine_mod
from testground_tpu.sim import netmatrix as nm
from testground_tpu.sim.engine import SimProgram, build_groups
from testground_tpu.sim.executor import (
    instantiate_testcase,
    load_sim_testcases,
)

from tests.test_sim_faults import _SlowPinger, conservation_ok, sched

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLANS = os.path.join(REPO_ROOT, "plans")


def make_groups(*counts, params=None):
    return build_groups(
        [
            RunGroup(id=f"g{i}", instances=c, parameters=dict(params or {}))
            for i, c in enumerate(counts)
        ]
    )


def plan_case(plan, case):
    return load_sim_testcases(os.path.join(PLANS, plan))[case]()


def pingpong_prog(counts=(2, 2), transport="xla", **kw):
    kw.setdefault("chunk", 16)
    kw.setdefault("telemetry", True)
    return SimProgram(
        plan_case("network", "ping-pong"),
        make_groups(*counts),
        transport=transport,
        **kw,
    )


# ------------------------------------------------------------ schema pins


class TestSchemaPins:
    def test_msg_bytes_matches_the_wire_size(self):
        """NM_MSG_BYTES is duplicated so sim/netmatrix.py stays jax-free
        — it MUST track the transport's fixed message size."""
        from testground_tpu.sim.net import MSG_BYTES

        assert nm.NM_MSG_BYTES == MSG_BYTES

    def test_channel_order_is_frozen(self):
        """The jsonl cell schema and every host surface index by this
        order — changing it is a wire-format break."""
        assert nm.NM_CHANNEL_NAMES == (
            "sent",
            "enqueued",
            "delivered",
            "dropped",
            "rejected",
            "fault_dropped",
        )
        assert [
            nm.NM_SENT,
            nm.NM_ENQUEUED,
            nm.NM_DELIVERED,
            nm.NM_DROPPED,
            nm.NM_REJECTED,
            nm.NM_FAULT,
        ] == list(range(nm.NM_CHANNELS))

    def test_delta_rows_round_trip_the_matrix(self):
        delta = np.zeros((nm.NM_CHANNELS, 3, 3), np.int64)
        delta[nm.NM_SENT, 0, 2] = 7
        delta[nm.NM_ENQUEUED, 0, 2] = 5
        delta[nm.NM_DROPPED, 0, 2] = 2
        delta[nm.NM_DELIVERED, 2, 1] = 4
        row = nm.delta_row(delta, tick=16, chunk=0, ident={"run": "r"})
        assert row["run"] == "r" and row["tick"] == 16
        # sparse: only the two touched pairs, row-major
        assert [c[:2] for c in row["cells"]] == [[0, 2], [2, 1]]
        back = nm.matrix_from_rows([json.loads(json.dumps(row))], 3)
        assert np.array_equal(back, delta)

    def test_matrix_totals_and_bytes(self):
        delta = np.zeros((nm.NM_CHANNELS, 2, 2), np.int64)
        delta[nm.NM_ENQUEUED] = [[1, 2], [3, 4]]
        assert nm.matrix_totals(delta)["enqueued"] == 10
        assert nm.matrix_bytes(delta).sum() == 10 * nm.NM_MSG_BYTES


# ----------------------------------------------------------- conservation


class TestConservation:
    @pytest.mark.parametrize("transport", ["xla", "pallas"])
    def test_matrix_reconciles_exactly(self, transport):
        """The acceptance invariant on both transports: per channel,
        Σ cells == the engine's cumulative flow total, and the send-side
        identity closes CELL-WISE."""
        res = pingpong_prog(transport=transport, netmatrix=True).run(
            max_ticks=256
        )
        mat = np.asarray(res["net_matrix"], np.int64)
        assert mat.shape == (nm.NM_CHANNELS, 2, 2)
        assert res["msgs_delivered"] > 0, "no traffic to meter"
        assert nm.reconcile(mat, res) == []
        # cell-wise send identity: sent = enqueued + dropped + rejected
        # + fault_dropped at every (src, dst) pair
        assert np.array_equal(
            mat[nm.NM_SENT],
            mat[nm.NM_ENQUEUED]
            + mat[nm.NM_DROPPED]
            + mat[nm.NM_REJECTED]
            + mat[nm.NM_FAULT],
        )

    def test_chunk_deltas_sum_to_the_final_matrix(self):
        """netmatrix_cb receives one host delta per chunk; their sum —
        and the jsonl rows they encode — reconstruct results()'s
        accumulated matrix bit for bit."""
        prog = pingpong_prog(netmatrix=True)
        deltas = []
        res = prog.run(max_ticks=256, netmatrix_cb=deltas.append)
        chunks = res["ticks"] // 16
        assert len(deltas) == chunks, "expected one delta per chunk"
        mat = np.asarray(res["net_matrix"], np.int64)
        assert np.array_equal(np.sum(deltas, axis=0), mat)
        rows = [
            nm.delta_row(d, tick=(i + 1) * 16, chunk=i)
            for i, d in enumerate(deltas)
        ]
        assert np.array_equal(nm.matrix_from_rows(rows, 2), mat)

    def test_hosts_row_carries_echo_traffic(self):
        """additional_hosts lanes land in the extra hosts row/column so
        the matrix total still equals msgs_delivered exactly."""
        prog = SimProgram(
            plan_case("additional_hosts", "additional_hosts"),
            make_groups(4),
            chunk=16,
            hosts=("http-echo",),
            telemetry=True,
            netmatrix=True,
        )
        res = prog.run(max_ticks=64)
        mat = np.asarray(res["net_matrix"], np.int64)
        assert mat.shape == (nm.NM_CHANNELS, 2, 2)  # g0 + hosts
        assert nm.reconcile(mat, res) == []
        # the echo round trip: requests into the hosts column, echoes
        # back out of the hosts row
        assert mat[nm.NM_DELIVERED, 0, 1] > 0  # g0 → hosts
        assert mat[nm.NM_DELIVERED, 1, 0] > 0  # hosts → g0


# ------------------------------------------------------------------ chaos


class TestChaos:
    def _run(self, netmatrix):
        groups = make_groups(2, 2)
        prog = SimProgram(
            _SlowPinger(),  # 4-tick latency keeps messages in flight
            groups,
            chunk=8,
            telemetry=True,
            netmatrix=netmatrix,
            faults=sched(
                groups,
                [{"kind": "crash", "start_ms": 10, "instances": "2:4"}],
            ),
        )
        return prog.run(max_ticks=32)

    def test_enabling_the_matrix_perturbs_nothing(self):
        """Bit-equality under chaos: the matrix plane observes the same
        deterministic run — every flow total and status identical with
        the plane on or off."""
        on, off = self._run(True), self._run(False)
        for key in (
            "ticks",
            "msgs_sent",
            "msgs_enqueued",
            "msgs_delivered",
            "msgs_dropped",
            "msgs_rejected",
            "fault_dropped",
            "cal_depth",
            "faults_crashed",
        ):
            assert on[key] == off[key], key
        assert np.array_equal(on["status"], off["status"])
        assert np.array_equal(on["finished_at"], off["finished_at"])
        assert "net_matrix" not in off

    def test_fault_drops_charge_the_crashed_cells(self):
        """Crash losses (in-flight purges + send-time kills) land in the
        fault_dropped channel at (sender, crashed-receiver) cells only —
        g1 is the crashed group, so column g0 stays clean."""
        res = self._run(True)
        assert res["fault_dropped"] > 0 and conservation_ok(res)
        mat = np.asarray(res["net_matrix"], np.int64)
        assert nm.reconcile(mat, res) == []
        fault = mat[nm.NM_FAULT]
        assert fault[:, 1].sum() == res["fault_dropped"]
        assert fault[:, 0].sum() == 0  # nobody lost traffic TO g0


# ----------------------------------------------------------- zero overhead


class TestZeroOverhead:
    def test_plane_off_leaves_the_chunk_jaxpr_untouched(self):
        """netmatrix=False (the default) is not merely 'matrix unused':
        the traced chunk program is the identical jaxpr, and the carry
        holds no matrix leaf to allocate or thread."""
        a = pingpong_prog(netmatrix=False)
        b = pingpong_prog()  # knob omitted entirely
        carry = jax.eval_shape(lambda: a.init_carry(0))
        assert carry.net_mat is None and carry.net_bw_hiwater is None
        assert str(jax.make_jaxpr(a._chunk_step)(carry)) == str(
            jax.make_jaxpr(b._chunk_step)(carry)
        )
        # ...while ON is program-shaping: the matrix leaf rides the carry
        on = jax.eval_shape(
            lambda: pingpong_prog(netmatrix=True).init_carry(0)
        )
        assert on.net_mat.shape == (nm.NM_CHANNELS, 2, 2)

    def test_matrix_adds_no_host_syncs(self, monkeypatch):
        """One blocking device→host sync per chunk (the done-flag poll),
        matrix on or off — the delta rides the same dispatch result as
        the telemetry block."""
        calls = {"n": 0}
        real = engine_mod._poll_done

        def counting(done):
            calls["n"] += 1
            return real(done)

        monkeypatch.setattr(engine_mod, "_poll_done", counting)

        def run(netmatrix):
            calls["n"] = 0
            deltas = []
            res = pingpong_prog(netmatrix=netmatrix).run(
                max_ticks=256,
                netmatrix_cb=deltas.append if netmatrix else None,
            )
            return calls["n"], res["ticks"] // 16, deltas

        syncs_off, chunks_off, _ = run(False)
        syncs_on, chunks_on, deltas = run(True)
        assert chunks_on == chunks_off
        assert syncs_off == chunks_off  # one poll per dispatch
        assert syncs_on == syncs_off  # the matrix adds ZERO syncs
        assert len(deltas) == chunks_on  # yet every chunk flushed

    def test_matrix_requires_telemetry(self):
        """The matrix flushes beside the telemetry block — without that
        ride-along there is no zero-sync path, so the program refuses
        loudly instead of silently paying a new sync."""
        with pytest.raises(ValueError, match="telemetry"):
            pingpong_prog(telemetry=False, netmatrix=True)


# ---------------------------------------------------------- bucketed demux


class TestBucketedDemux:
    def test_padded_run_reports_the_exact_matrix(self):
        """Shape bucketing pads lanes, not groups: dead lanes send
        nothing, so the padded run's demuxed matrix is bit-equal to the
        exact-N run's."""
        from testground_tpu.sim.buckets import plan_buckets

        exact = pingpong_prog(netmatrix=True)
        res_e = exact.run(max_ticks=256)
        bp = plan_buckets([2, 2], "auto", (8,))
        assert bp is not None
        padded = build_groups(
            [
                RunGroup(id=g.id, instances=p, parameters=dict(g.params))
                for g, p in zip(exact.groups, bp.padded_counts)
            ]
        )
        prog_p = SimProgram(
            instantiate_testcase(
                type(exact.tc), padded, tick_ms=exact.tick_ms
            ),
            padded,
            chunk=16,
            telemetry=True,
            netmatrix=True,
            live_counts=bp.live_counts,
        )
        res_p = prog_p.run(max_ticks=256)
        mat_e = np.asarray(res_e["net_matrix"], np.int64)
        mat_p = np.asarray(res_p["net_matrix"], np.int64)
        assert np.array_equal(mat_p, mat_e)
        assert nm.reconcile(mat_p, res_p) == []


# ------------------------------------------------------------- cut advisor


def two_cluster_traffic(heavy=1000, light=1):
    """4 groups, clusters {0,1} and {2,3}: heavy intra, light cross."""
    w = np.full((4, 4), light, np.int64)
    np.fill_diagonal(w, 0)
    w[0, 1] = w[1, 0] = w[2, 3] = w[3, 2] = heavy
    return w


class TestCutAdvisor:
    def test_exhaustive_recovers_the_cluster_split(self):
        rec = nm.cut_advisor(
            two_cluster_traffic(), 2, labels=["a", "b", "c", "d"]
        )
        assert rec["method"] == "exhaustive"
        assert rec["assignment"] == [0, 0, 1, 1]
        assert rec["shards"] == [["a", "b"], ["c", "d"]]
        # the cut severs only the light cross-cluster pairs: 4 unordered
        # pairs × (1 + 1 symmetrized) = 8; the heavy links stay inside
        assert rec["cut"] == 8.0
        assert rec["total"] == 2 * 2000 + 8
        assert rec["cut_fraction"] == pytest.approx(8 / 4008)

    def test_greedy_recovers_clusters_at_scale(self):
        """Past the exhaustive budget the agglomerative pass still
        co-locates heavy talkers: two 5-group cliques reassemble."""
        g_n = 10
        w = np.ones((g_n, g_n), np.int64)
        np.fill_diagonal(w, 0)
        for c in (range(5), range(5, 10)):
            for i in c:
                for j in c:
                    if i != j:
                        w[i, j] = 500
        rec = nm.cut_advisor(w, 2, exhaustive_limit=10)
        assert rec["method"] == "greedy"
        assert rec["assignment"] == [0] * 5 + [1] * 5

    def test_balance_cap_blocks_the_trivial_answer(self):
        """Uniform traffic: any split costs the same, but no shard may
        hold more than ⌈G/N⌉ groups — all-on-one is never 'optimal'."""
        w = np.ones((6, 6), np.int64)
        np.fill_diagonal(w, 0)
        for shards in (2, 3):
            rec = nm.cut_advisor(w, shards)
            sizes = np.bincount(rec["assignment"], minlength=shards)
            assert sizes.max() <= -(-6 // shards)
            assert (sizes > 0).all()  # every shard used when G >= N

    def test_canonical_numbering_and_shard_overflow(self):
        rec = nm.cut_advisor(two_cluster_traffic(), 2)
        assert rec["assignment"][0] == 0  # first-appearance order
        # more shards than groups degrades to one group per shard
        rec = nm.cut_advisor(np.zeros((3, 3)), 10)
        assert sorted(rec["assignment"]) == [0, 1, 2]

    def test_zero_traffic_has_zero_cut_fraction(self):
        rec = nm.cut_advisor(np.zeros((4, 4)), 2)
        assert rec["cut"] == 0.0 and rec["cut_fraction"] == 0.0

    def test_input_validation(self):
        with pytest.raises(ValueError, match="square"):
            nm.cut_advisor(np.zeros((2, 3)), 2)
        with pytest.raises(ValueError, match="at least 1"):
            nm.cut_advisor(np.zeros((2, 2)), 0)
        with pytest.raises(ValueError, match="labels"):
            nm.cut_advisor(np.zeros((2, 2)), 2, labels=["only-one"])

    def test_top_pairs_order_and_elision(self):
        mat = np.zeros((nm.NM_CHANNELS, 3, 3), np.int64)
        mat[nm.NM_SENT, 0, 1] = 50
        mat[nm.NM_SENT, 2, 0] = 90
        mat[nm.NM_SENT, 1, 2] = 50  # ties break on (src, dst)
        mat[nm.NM_DROPPED, 2, 2] = 1  # nonzero pair with zero sent
        pairs, elided = nm.top_pairs(mat, 2)
        assert [(p["src"], p["dst"]) for p in pairs] == [(2, 0), (0, 1)]
        assert pairs[0]["sent"] == 90
        assert elided == 2
        # k >= nonzero pairs elides nothing
        assert nm.top_pairs(mat, 99)[1] == 0


# --------------------------------------------------------- executor e2e


@pytest.fixture(scope="class")
def netmatrix_run(tmp_path_factory):
    """One executor run with the plane on, asserted many ways."""
    from testground_tpu.api import RunInput
    from testground_tpu.config import EnvConfig
    from testground_tpu.rpc import discard_writer
    from testground_tpu.sim.executor import SimJaxConfig, execute_sim_run

    home = tmp_path_factory.mktemp("tghome")
    old = os.environ.get("TESTGROUND_HOME")
    os.environ["TESTGROUND_HOME"] = str(home)
    try:
        env = EnvConfig.load()
        job = RunInput(
            run_id="nmrun",
            test_plan="network",
            test_case="ping-pong",
            total_instances=4,
            groups=[
                RunGroup(
                    id=g,
                    instances=2,
                    artifact_path=os.path.join(PLANS, "network"),
                )
                for g in ("c0", "c1")
            ],
            runner_config=SimJaxConfig(
                telemetry=True,
                netmatrix=True,
                chunk=16,
                seed=5,
                max_ticks=512,
            ),
            env=env,
        )
        out = execute_sim_run(job, discard_writer(), threading.Event())
        yield {"env": env, "out": out}
    finally:
        if old is None:
            os.environ.pop("TESTGROUND_HOME", None)
        else:
            os.environ["TESTGROUND_HOME"] = old


class TestExecutorSurface:
    def test_journal_block_reconciles(self, netmatrix_run):
        sim = netmatrix_run["out"].result.journal["sim"]
        block = sim["net_matrix"]
        assert block["labels"] == ["c0", "c1"]
        assert block["mismatches"] == []
        mat = np.asarray(block["matrix"], np.int64)
        assert nm.matrix_totals(mat) == block["totals"]
        assert block["totals"]["delivered"] == sim["msgs_delivered"]
        assert block["totals"]["sent"] == sim["msgs_sent"]
        assert (
            block["bytes_total"]
            == block["totals"]["enqueued"] * nm.NM_MSG_BYTES
        )
        assert block["top_pairs"] == nm.top_pairs(mat, 16)[0]

    def test_stream_file_reconstructs_the_journal_matrix(
        self, netmatrix_run
    ):
        """sim_netmatrix.jsonl: one row per chunk, ticks contiguous, and
        the sparse cells sum back to the journal's dense matrix bit for
        bit — the contract resume alignment depends on."""
        block = netmatrix_run["out"].result.journal["sim"]["net_matrix"]
        env = netmatrix_run["env"]
        path = os.path.join(
            env.dirs.outputs(), "network", "nmrun", block["file"]
        )
        rows = list(nm.iter_rows(path))
        assert len(rows) == block["chunks"] > 0
        assert [r["chunk"] for r in rows] == list(range(len(rows)))
        assert [r["tick"] for r in rows] == [
            (i + 1) * 16 for i in range(len(rows))
        ]
        assert all(r["run"] == "nmrun" for r in rows)
        back = nm.matrix_from_rows(rows, 2)
        assert np.array_equal(
            back, np.asarray(block["matrix"], np.int64)
        )

    def test_stats_payload_and_renderers(self, netmatrix_run):
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )
        from testground_tpu.runners.pretty import (
            render_netmap,
            render_netmap_cut,
        )

        t = Task(
            id="nmrun",
            type=TaskType.RUN,
            plan="network",
            case="ping-pong",
            states=[DatedState(state=State.COMPLETE, created=0.0)],
            result=netmatrix_run["out"].result.to_dict(),
        )
        block = (t.stats_payload().get("sim") or {}).get("net_matrix")
        assert block, "sim.net_matrix missing from the stats payload"
        screen = render_netmap(block, ident="nmrun")
        assert "c0" in screen and "c1" in screen
        assert "conservation" in screen
        rec = nm.cut_advisor(
            nm.matrix_bytes(np.asarray(block["matrix"], np.int64)),
            2,
            labels=block["labels"],
        )
        cut_screen = render_netmap_cut(rec, 2)
        assert "shard" in cut_screen

    def test_prometheus_rides_the_task(self, netmatrix_run):
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )
        from testground_tpu.metrics.prometheus import render_prometheus

        t = Task(
            id="nmrun",
            type=TaskType.RUN,
            plan="network",
            case="ping-pong",
            states=[DatedState(state=State.COMPLETE, created=0.0)],
            result=netmatrix_run["out"].result.to_dict(),
        )
        text = render_prometheus([t], per_task_limit=10)
        assert 'tg_net_pair_msgs_total{' in text
        assert 'flow="delivered"' in text
        assert 'src="c0"' in text
        assert "tg_net_pairs_elided" in text
        assert "tg_net_conservation_mismatches" in text


class TestPrometheusCardinality:
    def test_exposition_is_topk_bounded_never_g_squared(self):
        """A 30-group all-talking matrix (900 nonzero pairs) must export
        ≤ 16 pair series per metric plus the elision gauge — the page
        never scales with G²."""
        from testground_tpu.engine.task import (
            DatedState,
            State,
            Task,
            TaskType,
        )
        from testground_tpu.metrics.prometheus import render_prometheus

        g_n = 30
        mat = np.zeros((nm.NM_CHANNELS, g_n, g_n), np.int64)
        rng = np.random.default_rng(7)
        sent = rng.integers(1, 1000, size=(g_n, g_n))
        mat[nm.NM_SENT] = sent
        mat[nm.NM_ENQUEUED] = sent
        pairs, elided = nm.top_pairs(mat, 16)
        assert len(pairs) == 16 and elided == g_n * g_n - 16
        block = {
            "labels": [f"g{i}" for i in range(g_n)],
            "matrix": mat.tolist(),
            "totals": nm.matrix_totals(mat),
            "bytes_total": int(nm.matrix_bytes(mat).sum()),
            "top_pairs": pairs,
            "elided_pairs": elided,
            "mismatches": [],
        }
        t = Task(
            id="big",
            type=TaskType.RUN,
            plan="p",
            case="c",
            states=[DatedState(state=State.COMPLETE, created=0.0)],
            result={"journal": {"sim": {"net_matrix": block}}},
        )
        text = render_prometheus([t], per_task_limit=10)
        msg_series = [
            ln
            for ln in text.splitlines()
            if ln.startswith("tg_net_pair_msgs_total{")
        ]
        byte_series = [
            ln
            for ln in text.splitlines()
            if ln.startswith("tg_net_pair_bytes_total{")
        ]
        assert len(msg_series) == 16 * 5  # top-K pairs × flow legs
        assert len(byte_series) == 16
        assert "tg_net_pairs_elided" in text
        elided_lines = [
            ln
            for ln in text.splitlines()
            if ln.startswith("tg_net_pairs_elided{")
        ]
        assert elided_lines and elided_lines[0].endswith(str(elided))
