"""Property-based fuzz of the calendar transport (SURVEY.md §4 tier 2,
strengthened): random message schedules through random link shapes must

1. deliver BIT-IDENTICALLY through the two plane storage layouts (flat
   vs 2-D rows — the unsharded and mesh-sharded forms, see the Calendar
   docstring), and
2. satisfy the delivery invariants regardless of shaping: every delivered
   message was actually sent (payload word0 is unique per send), arrives
   no earlier than one tick after its send, each original message is
   delivered at most once (at most twice with duplicate-shaping), and
   provenance (src) matches the true sender.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property-based tier needs hypothesis"
)
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from testground_tpu.sim import net
from testground_tpu.sim.net import Calendar, deliver, enqueue


@dataclasses.dataclass
class Schedule:
    n: int
    o: int
    slots: int
    horizon: int
    ticks: int
    latency_ms: float
    jitter_ms: float
    loss: float
    duplicate: float
    sends: list  # per tick: (dst [o,n], valid [o,n]) int arrays
    seed: int


def _draw_sends(draw, n, o, ticks):
    """Per-tick (dst [o,n], valid [o,n]) schedules — shared by both
    strategies so the send shape can never silently diverge."""
    sends = []
    for _ in range(ticks):
        dst = draw(
            st.lists(
                st.lists(st.integers(0, n - 1), min_size=n, max_size=n),
                min_size=o,
                max_size=o,
            )
        )
        valid = draw(
            st.lists(
                st.lists(st.booleans(), min_size=n, max_size=n),
                min_size=o,
                max_size=o,
            )
        )
        sends.append((dst, valid))
    return sends


def _uid_payload(base, o, n):
    """[o, 2, n] payload: word0 = globally unique send id, word1 = src."""
    ids = jnp.arange(base, base + o * n, dtype=jnp.int32).reshape(o, n)
    srcs = jnp.tile(jnp.arange(n, dtype=jnp.int32)[None, :], (o, 1))
    return jnp.stack([ids, srcs], axis=1)


@st.composite
def schedules(draw):
    n = draw(st.integers(2, 10))
    o = draw(st.integers(1, 3))
    slots = draw(st.integers(1, 4))
    horizon = draw(st.sampled_from([4, 8, 16]))
    ticks = draw(st.integers(1, 8))
    latency = float(draw(st.integers(1, min(horizon - 1, 5))))
    jitter = float(draw(st.sampled_from([0.0, 0.0, 2.0])))
    loss = float(draw(st.sampled_from([0.0, 0.0, 30.0])))
    dup = float(draw(st.sampled_from([0.0, 0.0, 100.0])))
    sends = _draw_sends(draw, n, o, ticks)
    return Schedule(
        n=n, o=o, slots=slots, horizon=horizon, ticks=ticks,
        latency_ms=latency, jitter_ms=jitter, loss=loss, duplicate=dup,
        sends=sends, seed=draw(st.integers(0, 2**30)),
    )


def _run(sched: Schedule, flat: bool, transport: str = "xla"):
    """Run the schedule; returns per-tick inbox snapshots (numpy)."""
    n, o = sched.n, sched.o
    width = 2
    cal = Calendar.empty(
        sched.horizon, n, sched.slots, width, track_src=True, flat=flat
    )
    link = net.make_link_state(
        n,
        1,
        [sched.latency_ms, sched.jitter_ms, 0.0, sched.loss, 0.0, 0.0,
         sched.duplicate],
    )
    out = []
    uid = 0
    total_ticks = sched.ticks + sched.horizon + 2
    for t in range(total_ticks):
        cal, inbox = deliver(cal, jnp.int32(t), transport=transport)
        out.append(
            (
                np.asarray(inbox.payload),
                np.asarray(inbox.src),
                np.asarray(inbox.valid),
            )
        )
        if t < sched.ticks:
            dst_l, val_l = sched.sends[t]
            dst = jnp.asarray(dst_l, jnp.int32)
            valid = jnp.asarray(val_l, bool)
            base = uid
            uid += o * n
            payload = _uid_payload(base, o, n)
            cal, _ = enqueue(
                cal,
                link,
                dst,
                payload,
                valid,
                jnp.int32(t),
                1.0,
                jax.random.key(sched.seed + t),
                transport=transport,
            )
    return out


def _sent_index(sched: Schedule):
    """uid -> (send_tick, src, dst, was_valid)."""
    idx = {}
    uid = 0
    for t in range(sched.ticks):
        dst_l, val_l = sched.sends[t]
        for oi in range(sched.o):
            for s in range(sched.n):
                idx[uid] = (t, s, dst_l[oi][s], bool(val_l[oi][s]))
                uid += 1
    return idx


@settings(max_examples=25, deadline=None)
@given(schedules())
def test_flat_and_rows_layouts_deliver_identically(sched):
    a = _run(sched, flat=False)
    b = _run(sched, flat=True)
    for (pa, sa, va), (pb, sb, vb) in zip(a, b):
        assert (va == vb).all()
        assert (np.where(va, sa, -1) == np.where(vb, sb, -1)).all()
        assert (np.where(va[None], pa, -1) == np.where(vb[None], pb, -1)).all()


@settings(max_examples=10, deadline=None)
@given(schedules())
def test_pallas_transport_delivers_identically(sched):
    """The hand-tiled commit + pop kernels (sim/pallas_transport.py,
    interpret mode on CPU) against the XLA scatter path, on the SAME 2-D
    plane layout, through random latency/jitter/loss/duplicate shaping —
    the net-level face of the ISSUE 5 equality pin. Fewer examples than
    the layout fuzz: every drawn shape compiles its own kernel pair."""
    a = _run(sched, flat=False, transport="xla")
    b = _run(sched, flat=False, transport="pallas")
    for (pa, sa, va), (pb, sb, vb) in zip(a, b):
        assert (va == vb).all()
        assert (np.where(va, sa, -1) == np.where(vb, sb, -1)).all()
        assert (np.where(va[None], pa, -1) == np.where(vb[None], pb, -1)).all()


@dataclasses.dataclass
class QueueSchedule:
    n: int
    o: int
    ticks: int
    rate: float  # msgs/tick service rate (HTB token bucket)
    cap: int  # queue bound in messages
    sends: list
    seed: int
    # mid-run rate change (VERDICT r4 weak #5): switch the shaped rate to
    # rate2 before the send at tick `switch` (None = steady rate)
    rate2: float | None = None
    switch: int = 0


@st.composite
def queue_schedules(draw):
    n = draw(st.integers(2, 6))
    o = draw(st.integers(1, 4))
    ticks = draw(st.integers(1, 6))
    rate = draw(st.sampled_from([0.25, 0.5, 1.0, 2.0]))
    cap = draw(st.sampled_from([2, 4, 128]))
    sends = []
    for _ in range(ticks):
        dst = draw(
            st.lists(
                st.lists(st.integers(0, n - 1), min_size=n, max_size=n),
                min_size=o,
                max_size=o,
            )
        )
        valid = draw(
            st.lists(
                st.lists(st.booleans(), min_size=n, max_size=n),
                min_size=o,
                max_size=o,
            )
        )
        sends.append((dst, valid))
    return QueueSchedule(
        n=n, o=o, ticks=ticks, rate=rate, cap=cap, sends=sends,
        seed=draw(st.integers(0, 2**30)),
    )


@st.composite
def rate_change_schedules(draw):
    """Queue schedules that ALWAYS change the service rate mid-run —
    both directions (increase and decrease) are drawn. At least two send
    ticks, so the switch (applied before the send at tick >= 1) always
    lands inside the schedule."""
    sched = draw(queue_schedules().filter(lambda s: s.ticks >= 2))
    sched.rate2 = draw(
        st.sampled_from([0.25, 0.5, 1.0, 2.0, 4.0]).filter(
            lambda r: r != sched.rate
        )
    )
    sched.switch = draw(st.integers(1, max(1, sched.ticks - 1)))
    return sched


def _set_rate(link, n, rate):
    """Rebuild the egress bandwidth row for `rate` msgs/tick at 1ms
    ticks, preserving the standing backlog — what apply_net_updates does
    when a plan reshapes bandwidth mid-run."""
    bw = rate * net.MSG_BYTES * 1000.0
    egress = link.egress.at[net.BANDWIDTH].set(jnp.float32(bw))
    return dataclasses.replace(link, egress=egress)


def _run_queue(sched: QueueSchedule, flat: bool):
    """Random schedule through HTB bandwidth_queue shaping; returns
    (per-tick inboxes, total bw_dropped, total clamped). Inbox slots and
    horizon are sized so NOTHING else can drop — every loss must be a
    counted queue tail-drop. A sched.rate2 switches the shaped rate
    before the send at sched.switch."""
    n, o = sched.n, sched.o
    width = 2
    slots = sched.ticks * o * n  # worst-case same-bucket stacking
    # worst dt: every send queued at the slowest rate in play (across a
    # rate change the occupancy bound is approximate, so the cap cannot
    # be trusted to bound depth — size for the whole schedule)
    min_rate = min(sched.rate, sched.rate2 or sched.rate)
    horizon = int(sched.ticks * o * n / min_rate) + sched.ticks + 8
    cal = Calendar.empty(horizon, n, slots, width, track_src=True, flat=flat)
    bw = sched.rate * net.MSG_BYTES * 1000.0  # rate msgs/tick at 1ms ticks
    link = net.make_link_state(
        n, 1, [1.0, 0.0, bw, 0.0, 0.0, 0.0, 0.0], track_backlog=True
    )
    out = []
    uid = 0
    dropped = 0
    clamped = 0
    total_ticks = sched.ticks + horizon
    for t in range(total_ticks):
        cal, inbox = deliver(cal, jnp.int32(t))
        out.append(
            (
                np.asarray(inbox.payload),
                np.asarray(inbox.src),
                np.asarray(inbox.valid),
            )
        )
        if t < sched.ticks:
            if sched.rate2 is not None and t == sched.switch:
                link = _set_rate(link, n, sched.rate2)
            dst_l, val_l = sched.sends[t]
            base = uid
            uid += o * n
            cal, fb = enqueue(
                cal,
                link,
                jnp.asarray(dst_l, jnp.int32),
                _uid_payload(base, o, n),
                jnp.asarray(val_l, bool),
                jnp.int32(t),
                1.0,
                jax.random.key(sched.seed + t),
                features=("latency", "bandwidth_queue"),
                bw_queue_cap=sched.cap,
            )
            link = dataclasses.replace(link, backlog=fb.backlog)
            dropped += int(fb.bw_dropped)
            clamped += int(fb.clamped)
    return out, dropped, clamped


def _check_queue_properties(sched):
    """Shared HTB assertions: (1) conservation — every valid send is
    delivered exactly once OR counted as a queue tail-drop (nothing
    vanishes silently, the property the old drop-at-send bandwidth could
    not offer); (2) per-src FIFO — a src's queued messages arrive in
    send order (the reference's HTB class queue can never reorder, and a
    rate change must not let new traffic overtake the standing backlog);
    (3) both plane layouts agree. Returns (deliveries, dropped)."""
    inboxes, dropped, clamped = _run_queue(sched, flat=True)
    assert clamped == 0  # horizon was sized to make clamps impossible

    deliveries = {}  # uid -> arrival tick
    for t, (pay, src, valid) in enumerate(inboxes):
        for slot in range(valid.shape[0]):
            for d in range(valid.shape[1]):
                if valid[slot, d]:
                    uid = int(pay[0, slot, d])
                    assert uid not in deliveries, f"{uid} delivered twice"
                    deliveries[uid] = t

    valid_sends = 0
    per_src_uids = {}
    uid = 0
    for t in range(sched.ticks):
        dst_l, val_l = sched.sends[t]
        for oi in range(sched.o):
            for s in range(sched.n):
                if val_l[oi][s]:
                    valid_sends += 1
                    per_src_uids.setdefault(s, []).append(uid)
                uid += 1
    assert len(deliveries) == valid_sends - dropped, (
        f"sent {valid_sends}, delivered {len(deliveries)}, "
        f"counted drops {dropped}"
    )
    # FIFO: uids ascend in send order (tick, then outbox slot — exactly
    # the queue admission order), so arrivals must be non-decreasing
    for s, uids in per_src_uids.items():
        arrivals = [deliveries[u] for u in uids if u in deliveries]
        assert arrivals == sorted(arrivals), (
            f"src {s} deliveries reordered: {arrivals}"
        )

    # layout equality on the same schedule
    inboxes_r, dropped_r, _ = _run_queue(sched, flat=False)
    assert dropped_r == dropped
    for (pa, sa, va), (pb, sb, vb) in zip(inboxes, inboxes_r):
        assert (va == vb).all()
        assert (np.where(va, sa, -1) == np.where(vb, sb, -1)).all()
        assert (np.where(va[None], pa, -1) == np.where(vb[None], pb, -1)).all()
    return deliveries, dropped


@settings(max_examples=25, deadline=None)
@given(queue_schedules())
def test_bandwidth_queue_conserves_and_keeps_fifo(sched):
    _check_queue_properties(sched)


@settings(max_examples=25, deadline=None)
@given(rate_change_schedules())
def test_bandwidth_queue_rate_change_conserves_and_keeps_fifo(sched):
    """VERDICT r4 weak #5 / next #6: the documented rate-change envelope
    (net.py bandwidth_queue notes) under fuzz, in BOTH directions. What
    stays exact across a change: conservation (delivered + counted drops
    = sent), per-src FIFO (an increase drains the backlog at the new
    rate WITHOUT overtaking already-scheduled messages; a decrease
    queues new traffic behind the old busy time), and layout equality.
    What is approximate: only the tail-drop occupancy bound — drops are
    still exactly COUNTED, so conservation holds regardless of where the
    approximate bound lands."""
    deliveries, dropped = _check_queue_properties(sched)
    # approximation envelope: q_msgs = backlog*rate + ahead values the
    # standing busy time at the CURRENT rate, so it can overstate depth
    # by at most max_rate/min_rate; a cap beyond total*(ratio+1) is
    # unreachable even through the approximation and must never drop
    total = sched.ticks * sched.o * sched.n
    ratio = max(sched.rate, sched.rate2) / min(sched.rate, sched.rate2)
    if sched.cap >= total * (ratio + 1):
        assert dropped == 0, (
            f"cap {sched.cap} unreachable for {total} sends at rate "
            f"ratio {ratio} but dropped {dropped}"
        )


def _two_burst_sched(rate, rate2, b1, b2):
    """src 0 bursts b1 messages to dst 1 at tick 0 (rate), then b2 more
    at tick 1 after the rate switches to rate2."""
    o = max(b1, b2)
    sends = []
    for count in (b1, b2):
        dst = [[1, 0] for _ in range(o)]
        valid = [[oi < count, False] for oi in range(o)]
        sends.append((dst, valid))
    return QueueSchedule(
        n=2, o=o, ticks=2, rate=rate, cap=1000, sends=sends, seed=0,
        rate2=rate2, switch=1,
    )


def _src0_arrivals(sched, deliveries):
    """Arrival ticks of src 0's messages, in send (uid) order."""
    o, n = sched.o, sched.n
    out = []
    for t in range(sched.ticks):
        _, val_l = sched.sends[t]
        for oi in range(o):
            if val_l[oi][0]:
                out.append(deliveries[t * o * n + oi * n + 0])
    return out


class TestRateChangePacing:
    """Exact departure schedules across a rate change, both directions —
    hand-computed from the documented busy-time model (net.py
    bandwidth_queue notes): message j of a tick's burst departs
    floor(backlog + j/rate) ticks late, then backlog advances by
    admitted/rate − 1 tick of service. These pin the EXACT semantics the
    fuzz envelope only bounds."""

    def test_increase_drains_backlog_at_new_rate_without_overtaking(self):
        # burst 4 @ rate 1 → arrivals 1,2,3,4; backlog 0+4/1−1 = 3 ticks.
        # rate → 2, burst 4 @ t=1: dt = floor(3 + j/2) = 3,3,4,4 →
        # arrivals 5,5,6,6 — paced at the NEW rate, strictly AFTER the
        # standing busy time (no overtake of the rate-1 schedule)
        sched = _two_burst_sched(1.0, 2.0, 4, 4)
        deliveries, dropped = _check_queue_properties(sched)
        assert dropped == 0
        assert _src0_arrivals(sched, deliveries) == [1, 2, 3, 4, 5, 5, 6, 6]

    def test_decrease_queues_new_traffic_behind_old_busy_time(self):
        # burst 4 @ rate 2 → dt = floor(j/2) = 0,0,1,1 → arrivals
        # 1,1,2,2; backlog 0+4/2−1 = 1 tick. rate → 0.5, burst 2 @ t=1:
        # dt = floor(1 + 2j) = 1,3 → arrivals 3,5 — one message per two
        # ticks at the NEW rate, behind the remaining rate-2 busy time
        sched = _two_burst_sched(2.0, 0.5, 4, 2)
        deliveries, dropped = _check_queue_properties(sched)
        assert dropped == 0
        assert _src0_arrivals(sched, deliveries) == [1, 1, 2, 2, 3, 5]


class TestRateChangeCounter:
    def test_reshape_under_backlog_is_counted_and_journaled(self):
        """A plan that reshapes bandwidth while its egress queue is
        nonempty must increment the bw_rate_change_backlogged journal
        counter (ADVICE r4: the occupancy-bound approximation must be
        loud at runtime, not silent)."""
        from testground_tpu.api import RunGroup
        from testground_tpu.sim.api import RUNNING, SUCCESS, SimTestcase, Outbox
        from testground_tpu.sim.engine import SimProgram, build_groups

        def bw(rate):  # bytes/s for `rate` msgs/tick at 1 ms ticks
            return rate * net.MSG_BYTES * 1000.0

        class BwReshape(SimTestcase):
            SHAPING = ("latency", "bandwidth_queue")
            MSG_WIDTH = 1
            OUT_MSGS = 4
            IN_MSGS = 4
            MAX_LINK_TICKS = 32
            DEFAULT_LINK = (1.0, 0.0, bw(0.5), 0.0, 0.0, 0.0, 0.0)

            def init(self, env):
                return {"received": jnp.int32(0)}

            def step(self, env, state, inbox, sync, t):
                partner = env.global_seq ^ 1
                ob = Outbox(
                    dst=jnp.full((4,), partner, jnp.int32),
                    payload=jnp.ones((4, 1), jnp.int32),
                    valid=jnp.full((4,), t == 0, bool),
                )
                # backlog after tick 0 is 4/0.5−1 = 7 ticks; reshaping
                # at t == 1 lands while it is nonzero
                return self.out(
                    {"received": state["received"] + inbox.count},
                    status=jnp.where(
                        (t >= 20) & (state["received"] == 4),
                        SUCCESS,
                        RUNNING,
                    ),
                    outbox=ob,
                    net_shape=self.link_shape(
                        latency_ms=1.0, bandwidth=bw(2.0)
                    ),
                    net_shape_valid=t == 1,
                )

        prog = SimProgram(
            BwReshape(),
            build_groups([RunGroup(id="all", instances=2, parameters={})]),
            test_plan="fuzz",
            test_case="bw-reshape",
            tick_ms=1.0,
            chunk=8,
        )
        res = prog.run(max_ticks=64)
        assert (np.asarray(res["status"]) == 1).all()
        # both instances reshaped under a standing backlog, once each
        assert res["bw_rate_change_backlogged"] == 2
        assert res["bw_queue_dropped"] == 0


@settings(max_examples=25, deadline=None)
@given(schedules())
def test_delivery_invariants(sched):
    sent = _sent_index(sched)
    deliveries = {}  # uid -> list of (arrival_tick, to, src_seen)
    for t, (pay, src, valid) in enumerate(_run(sched, flat=True)):
        for slot in range(valid.shape[0]):
            for d in range(valid.shape[1]):
                if not valid[slot, d]:
                    continue
                uid = int(pay[0, slot, d])
                deliveries.setdefault(uid, []).append(
                    (t, d, int(src[slot, d]))
                )
    max_copies = 2 if sched.duplicate > 0 else 1
    for uid, arrivals in deliveries.items():
        assert uid in sent, f"delivered a never-sent message {uid}"
        t0, s, d0, was_valid = sent[uid]
        assert was_valid, f"invalid send {uid} was delivered"
        assert len(arrivals) <= max_copies, (
            f"message {uid} delivered {len(arrivals)} times"
        )
        for t, to, src_seen in arrivals:
            assert to == d0, f"message {uid} delivered to {to}, sent to {d0}"
            assert src_seen == s, (
                f"message {uid} src {src_seen}, sender was {s}"
            )
            assert t >= t0 + 1, f"message {uid} arrived before send+1"
            assert t <= t0 + sched.horizon, (
                f"message {uid} outlived the horizon"
            )
