"""Sync-plane failure hardening (docs/CROSSHOST.md): server death yields
a typed ``SyncLostError`` (no hang), partitions heal via bounded
reconnect (barrier re-arm + subscription resume), dead clients are
evicted with their barrier occupancy released, and mutations are
idempotent under reconnect replay — on BOTH wire-compatible backends."""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from testground_tpu.sync import (
    InMemSyncService,
    SyncClient,
    SyncLostError,
    SyncRetry,
    SyncServiceServer,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fast_retry(**over) -> SyncRetry:
    kw = dict(
        connect_timeout=0.5,
        attempts=3,
        deadline_secs=3.0,
        backoff_base=0.05,
        backoff_cap=0.3,
        heartbeat_secs=0.2,
    )
    kw.update(over)
    return SyncRetry(**kw)


@pytest.fixture(scope="session")
def native_bin(tmp_path_factory):
    from testground_tpu.native import build_syncsvc, native_available

    if not native_available():
        pytest.skip("no C++ toolchain")
    return build_syncsvc(str(tmp_path_factory.mktemp("syncsvc-bin")))


def _spawn_server(backend: str, native_bin: str | None, port=0, idle=0.0):
    """A killable sync-server SUBPROCESS of either backend; returns
    (proc, host, port)."""
    if backend == "python":
        code = (
            "from testground_tpu.sync.server import _main; "
            f"_main(['--port', '{port}', '--idle-timeout', '{idle}'])"
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env={**os.environ, "PYTHONPATH": REPO_ROOT},
        )
        parts = proc.stdout.readline().split()
        assert parts and parts[0] == "LISTENING", parts
        return proc, parts[1], int(parts[2])
    argv = [native_bin, "--port", str(port)]
    if idle:
        argv += ["--idle-timeout", str(idle)]
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True
    )
    parts = proc.stdout.readline().split()
    assert parts and parts[0] == "LISTENING", parts
    return proc, "127.0.0.1", int(parts[1])


@pytest.fixture(params=["python", "native"])
def killable_server(request):
    native = None
    if request.param == "native":
        native = request.getfixturevalue("native_bin")
    proc, host, port = _spawn_server(request.param, native)
    yield proc, host, port
    if proc.poll() is None:
        proc.kill()
    proc.wait(timeout=10)


@pytest.fixture(params=["python", "native"])
def idle_server(request):
    """In-process-managed server of either backend with a fast idle
    sweep; yields an object with .address/.stop()."""
    if request.param == "python":
        srv = SyncServiceServer(idle_timeout=0.8, evict_grace=0.3).start()
        yield srv
        srv.stop()
    else:
        from testground_tpu.native import NativeSyncService

        srv = NativeSyncService(
            request.getfixturevalue("native_bin"),
            idle_timeout=0.8,
            evict_grace=0.3,
        )
        yield srv
        srv.stop()


def _wait_stats(client, key, value, timeout=8.0):
    deadline = time.time() + timeout
    s = {}
    while time.time() < deadline:
        s = client.sync_stats(timeout=2)
        if s.get(key) == value:
            return s
        time.sleep(0.05)
    raise AssertionError(f"sync_stats never reached {key}={value}: {s}")


class TestServerDeath:
    """Acceptance pin: a killed sync server yields a typed SyncLostError
    within the reconnect budget — never an indefinite block."""

    def test_sigkill_mid_barrier_raises_typed(self, killable_server):
        proc, host, port = killable_server
        c = SyncClient(host, port, retry=_fast_retry(attempts=2, deadline_secs=2))
        got: list = []

        def park():
            try:
                c.barrier("never", 5, timeout=60)
            except BaseException as e:  # noqa: BLE001
                got.append(e)

        t = threading.Thread(target=park, daemon=True)
        t.start()
        time.sleep(0.3)
        start = time.time()
        proc.kill()
        proc.wait(timeout=10)
        t.join(timeout=15)
        assert not t.is_alive(), "barrier waiter hung past the budget"
        assert got and isinstance(got[0], SyncLostError), got
        assert f"{host}:{port}" in str(got[0])
        assert time.time() - start < 12
        c.close()

    def test_sigkill_mid_subscribe_raises_typed(self, killable_server):
        proc, host, port = killable_server
        c = SyncClient(host, port, retry=_fast_retry(attempts=2, deadline_secs=2))
        c.publish("topic", "a")
        sub = c.subscribe("topic", timeout=30)
        assert next(sub) == "a"
        got: list = []

        def drain():
            try:
                for _ in sub:
                    pass
            except BaseException as e:  # noqa: BLE001
                got.append(e)

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        time.sleep(0.2)
        proc.kill()
        proc.wait(timeout=10)
        t.join(timeout=15)
        assert not t.is_alive(), "subscriber hung past the budget"
        assert got and isinstance(got[0], SyncLostError), got
        c.close()

    def test_initial_connect_failure_names_address(self):
        import socket

        with socket.socket() as s:  # a port with nothing listening
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        start = time.time()
        with pytest.raises(SyncLostError) as ei:
            SyncClient(
                "127.0.0.1",
                port,
                retry=_fast_retry(attempts=2, deadline_secs=1.5),
            )
        assert f"127.0.0.1:{port}" in str(ei.value)
        assert ei.value.attempts == 2
        assert time.time() - start < 10


class TestReconnect:
    def test_partition_heal_rearms_barrier_and_resumes_subscribe(
        self, killable_server
    ):
        """SIGSTOP the server (half-open partition): the client detects
        it by pong timeout, retries within budget, and after SIGCONT the
        in-flight barrier completes and the subscription resumes without
        duplicates or loss."""
        proc, host, port = killable_server
        retry = _fast_retry(attempts=60, deadline_secs=30)
        c = SyncClient(host, port, namespace="run:z:", retry=retry)
        helper = SyncClient(
            host, port, namespace="run:z:", retry=_fast_retry(
                attempts=60, deadline_secs=30
            )
        )
        c.publish("topic", "a")
        sub = c.subscribe("topic", timeout=25)
        assert next(sub) == "a"
        got: list = []
        t = threading.Thread(
            target=lambda: got.append(c.signal_and_wait("gate", 2, timeout=25)),
            daemon=True,
        )
        t.start()
        time.sleep(0.3)
        os.kill(proc.pid, signal.SIGSTOP)
        time.sleep(1.5)  # heartbeat must declare the conn half-open
        os.kill(proc.pid, signal.SIGCONT)
        helper.publish("topic", "b")
        assert next(sub) == "b"  # no replayed "a", no lost "b"
        seq = helper.signal_and_wait("gate", 2, timeout=15)
        t.join(timeout=15)
        assert got and sorted([got[0], seq]) == [1, 2]
        c.close()
        helper.close()

    def test_server_restart_detected_by_boot_id(self, killable_server):
        """Reconnecting to a RESTARTED (state-lost) service must surface
        SyncLostError — never silently resume against an empty world."""
        proc, host, port = killable_server
        c = SyncClient(
            host, port, retry=_fast_retry(attempts=40, deadline_secs=20)
        )
        assert c.signal_entry("s") == 1
        proc.kill()
        proc.wait(timeout=10)
        # new server, same port, fresh boot id
        if proc.args[0] == sys.executable:
            proc2, _, _ = _spawn_server("python", None, port=port)
        else:
            proc2, _, _ = _spawn_server("native", proc.args[0], port=port)
        try:
            with pytest.raises(SyncLostError, match="restart"):
                deadline = time.time() + 20
                while time.time() < deadline:
                    c.counter("s")
                    time.sleep(0.1)
        finally:
            c.close()
            proc2.kill()
            proc2.wait(timeout=10)


class TestEviction:
    """Acceptance pin: a killed sync client never wedges survivors — its
    barrier occupancy is evicted and its death is published."""

    def test_sigkilled_client_releases_occupancy_and_publishes(
        self, idle_server
    ):
        host, port = idle_server.address
        watcher = SyncClient(host, port, retry=_fast_retry())
        events = watcher.subscribe("run:r:__run_events__", timeout=15)
        victim_code = f"""
import sys
sys.path.insert(0, {REPO_ROOT!r})
from testground_tpu.sync import SyncClient, SyncRetry
c = SyncClient({host!r}, {port}, namespace="run:r:",
               retry=SyncRetry(heartbeat_secs=0.2),
               identity={{"events_topic": "run:r:__run_events__",
                          "group": "g", "instance": 5}})
print("READY", flush=True)
c.barrier("never", 9, timeout=60)
"""
        victim = subprocess.Popen(
            [sys.executable, "-c", victim_code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        try:
            assert victim.stdout.readline().strip() == "READY"
            _wait_stats(watcher, "waiters", 1)
            victim.kill()
            victim.wait(timeout=10)
            evt = next(events)
            assert evt["type"] == "evicted"
            assert evt["group"] == "g" and evt["instance"] == 5
            _wait_stats(watcher, "waiters", 0)
        finally:
            if victim.poll() is None:
                victim.kill()
            watcher.close()

    def test_half_open_client_swept_by_idle_timeout(self, idle_server):
        """A client that stops heartbeating (the SIGSTOP/partition
        shape, where no FIN ever arrives) is evicted by the idle sweep
        and its parked waiter released."""
        host, port = idle_server.address
        watcher = SyncClient(host, port, retry=_fast_retry())
        silent = SyncClient(
            host,
            port,
            namespace="run:r:",
            retry=_fast_retry(heartbeat_secs=0.0, attempts=0, deadline_secs=0.5),
            identity={
                "events_topic": "run:r:__run_events__",
                "group": "g2",
                "instance": 3,
            },
        )
        events = watcher.subscribe("run:r:__run_events__", timeout=15)
        got: list = []

        def park():
            try:
                silent.barrier("never", 9, timeout=30)
            except BaseException as e:  # noqa: BLE001
                got.append(e)

        t = threading.Thread(target=park, daemon=True)
        t.start()
        _wait_stats(watcher, "waiters", 1)
        evt = next(events)  # the sweep evicts the silent client
        assert evt["type"] == "evicted" and evt["instance"] == 3
        _wait_stats(watcher, "waiters", 0)
        t.join(timeout=15)
        assert got and isinstance(got[0], SyncLostError), got
        watcher.close()
        silent.close()

    def test_transient_reconnect_is_not_an_eviction(self, idle_server):
        """A client whose connection drops abnormally but who RECONNECTS
        within the grace window (the heartbeat force-close / partition
        heal shape) must not be announced dead — otherwise every
        reconnect would spuriously evict a live instance."""
        host, port = idle_server.address
        watcher = SyncClient(host, port, retry=_fast_retry())
        c = SyncClient(
            host,
            port,
            namespace="run:r:",
            retry=_fast_retry(attempts=30, deadline_secs=15),
            identity={
                "events_topic": "run:r:__run_events__",
                "group": "g",
                "instance": 9,
            },
        )
        # drop the socket out from under the client (what the heartbeat
        # does on pong timeout); the reconnect re-hellos immediately
        c._sock.close()
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                c.ping(timeout=1)
                break
            except (TimeoutError, RuntimeError):
                time.sleep(0.1)
        assert c.signal_entry("alive") >= 1  # recovered
        sub = watcher.subscribe("run:r:__run_events__", timeout=1.2)
        with pytest.raises(TimeoutError):  # grace canceled the eviction
            evt = next(sub)
            raise AssertionError(f"spurious eviction: {evt}")
        c.close()
        watcher.close()

    def test_clean_close_publishes_no_eviction(self, idle_server):
        host, port = idle_server.address
        watcher = SyncClient(host, port, retry=_fast_retry())
        c = SyncClient(
            host,
            port,
            namespace="run:r:",
            retry=_fast_retry(),
            identity={
                "events_topic": "run:r:__run_events__",
                "group": "g",
                "instance": 1,
            },
        )
        c.signal_entry("s")
        c.close()
        sub = watcher.subscribe("run:r:__run_events__", timeout=1.2)
        with pytest.raises(TimeoutError):
            next(sub)
        watcher.close()


class TestIdempotencyTokens:
    def test_inmem_signal_token_dedup(self):
        s = InMemSyncService()
        assert s.signal_entry("x", token="t1") == 1
        assert s.signal_entry("x", token="t1") == 1  # replay: same seq
        assert s.signal_entry("x", token="t2") == 2
        assert s.counter("x") == 2

    def test_inmem_publish_token_dedup(self):
        s = InMemSyncService()
        assert s.publish("t", "a", token="p1") == 1
        assert s.publish("t", "a", token="p1") == 1
        assert s.topic_len("t") == 1

    def test_wire_replay_does_not_double_count(self, killable_server):
        """Re-sending a tokened op over the wire (what the reconnect
        replay does) must not double-signal/publish."""
        import json
        import socket

        proc, host, port = killable_server
        with socket.create_connection((host, port), timeout=5) as s:
            f = s.makefile("rw", encoding="utf-8")
            for rid in (1, 2):  # identical token, two sends
                f.write(
                    json.dumps(
                        {
                            "id": rid,
                            "op": "signal_entry",
                            "state": "st",
                            "token": "tok",
                        }
                    )
                    + "\n"
                )
                f.flush()
            seqs = [json.loads(f.readline())["seq"] for _ in range(2)]
            assert seqs == [1, 1]
            f.write(
                json.dumps({"id": 3, "op": "counter", "state": "st"}) + "\n"
            )
            f.flush()
            assert json.loads(f.readline())["count"] == 1


class TestRunParamsThreading:
    def test_sync_budget_round_trips_env(self):
        from testground_tpu.sdk.runparams import RunParams

        p = RunParams(
            sync_connect_timeout=3.5,
            sync_retry_attempts=4,
            sync_retry_deadline=12.0,
            sync_heartbeat=1.5,
        )
        env = p.to_env()
        assert env["SYNC_CONNECT_TIMEOUT"] == "3.5"
        assert env["SYNC_RETRY_ATTEMPTS"] == "4"
        q = RunParams.from_env(env)
        assert q.sync_connect_timeout == 3.5
        assert q.sync_retry_attempts == 4
        assert q.sync_retry_deadline == 12.0
        assert q.sync_heartbeat == 1.5
